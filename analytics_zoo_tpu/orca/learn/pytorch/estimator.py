"""Orca PyTorch estimator — creator-function surface on the TPU engine.

Mirrors ``Estimator.from_torch`` (reference: pyzoo/zoo/orca/learn/pytorch/
estimator.py:38; Ray path pytorch_ray_estimator.py:90-185 with model_creator/
optimizer_creator/loss_creator/scheduler_creator and TrainingOperator hooks).
Three reference backends (bigdl-JEP, torch_distributed DDP-gloo, horovod)
collapse into the one jitted engine; ``backend`` is accepted and ignored
except to reject truly unsupported requests.

Two creator styles:
* creators returning torch objects (nn.Module / torch.optim / torch losses):
  converted to flax+optax via torch_bridge (standard layer stacks; weights
  imported) — custom forward() raises with porting guidance;
* creators returning jax objects (flax module / optax tx / loss callable):
  used directly — the recommended TPU-native style.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ..estimator import TPUEstimator
from .torch_bridge import (build_flax_from_torch, convert_torch_loss,
                           convert_torch_optimizer)


def _is_torch_module(obj) -> bool:
    try:
        import torch.nn as tnn
        return isinstance(obj, tnn.Module) and not isinstance(
            obj, tnn.modules.loss._Loss)
    except ImportError:
        return False


class Estimator:
    @staticmethod
    def from_torch(*, model_creator: Callable,
                   optimizer_creator: Optional[Callable] = None,
                   loss_creator: Optional[Callable] = None,
                   scheduler_creator: Optional[Callable] = None,
                   training_operator_cls=None,
                   config: Optional[dict] = None,
                   backend: str = "tpu",
                   metrics=None, model_dir: Optional[str] = None,
                   workers_per_node: int = 1, use_tqdm: bool = False,
                   scheduler_step_freq: str = "batch", sync_stats: bool = True,
                   log_level=None, **_):
        if backend in ("horovod",):
            # horovod's only role was allreduce; the engine does that over ICI
            pass
        cfg = dict(config or {})
        model = model_creator(cfg)
        loss = loss_creator(cfg) if (loss_creator and
                                     not isinstance(loss_creator, type)) \
            else (loss_creator() if isinstance(loss_creator, type) else None)

        param_loader = None
        if _is_torch_module(model):
            module, param_loader = build_flax_from_torch(model)
            jax_loss = convert_torch_loss(loss) if loss is not None else None
            tx = None
            if optimizer_creator is not None:
                torch_opt = optimizer_creator(model, cfg)
                tx = convert_torch_optimizer(torch_opt, model)
            est = PyTorchTPUEstimator(module, loss=jax_loss,
                                      optimizer=tx or "adam", metrics=metrics,
                                      model_dir=model_dir, config=cfg)
            est._param_loader = param_loader
        else:
            tx = None
            if optimizer_creator is not None:
                maybe = optimizer_creator(model, cfg)
                tx = convert_torch_optimizer(maybe) or maybe
            est = PyTorchTPUEstimator(model, loss=loss, optimizer=tx or "adam",
                                      metrics=metrics, model_dir=model_dir,
                                      config=cfg)
        est.training_operator_cls = training_operator_cls
        return est

    latest_checkpoint = staticmethod(
        lambda model_dir: __import__(
            "analytics_zoo_tpu.orca.learn.estimator", fromlist=["Estimator"]
        ).Estimator.latest_checkpoint(model_dir))


class PyTorchTPUEstimator(TPUEstimator):
    """TPUEstimator + torch-flavored conveniences (data loaders, imported
    weights)."""

    _param_loader = None
    training_operator_cls = None

    def fit(self, data, epochs=1, batch_size=32, **kwargs):
        data = _maybe_from_dataloader(data, self.config, batch_size)
        first_build = self.engine.params is None
        if first_build and (self._param_loader is not None or
                            self.training_operator_cls is not None):
            it_kwargs = {k: kwargs[k] for k in ("feature_cols", "label_cols")
                         if k in kwargs}
            from .. import utils as learn_utils
            it = learn_utils.data_to_iterator(
                data, batch_size, self.mesh, config=self.config,
                **it_kwargs)
            sample = next(it.epoch(shuffle=False, prefetch=False))
            self.engine.build(tuple(np.asarray(a) for a in sample.x))
            if self._param_loader is not None:
                self._load_torch_weights()
        if self.training_operator_cls is not None:
            return self._fit_with_operator(data, epochs, batch_size, **kwargs)
        return super().fit(data, epochs=epochs, batch_size=batch_size,
                           **kwargs)

    def _fit_with_operator(self, data, epochs, batch_size,
                           feature_cols=None, label_cols=None, **_):
        from .. import utils as learn_utils
        op = self.training_operator_cls(self.config, self.engine,
                                        world_rank=self.ctx.process_id)
        it = learn_utils.data_to_iterator(
            data, batch_size, self.mesh, feature_cols, label_cols,
            shuffle=True, config=self.config)
        stats = []
        for ep in range(epochs):
            s = op.train_epoch(it.epoch(), {"epoch_idx": ep})
            s["epoch"] = ep + 1
            stats.append(s)
        self._operator = op
        return stats

    def evaluate(self, data, batch_size=32, **kwargs):
        data = _maybe_from_dataloader(data, self.config, batch_size)
        if self.engine.params is None and self._param_loader is not None:
            from .. import utils as learn_utils
            it = learn_utils.data_to_iterator(data, batch_size, self.mesh,
                                              config=self.config)
            sample = next(it.epoch(shuffle=False, prefetch=False))
            self.engine.build(tuple(np.asarray(a) for a in sample.x))
            self._load_torch_weights()
        return super().evaluate(data, batch_size=batch_size, **kwargs)

    def predict(self, data, batch_size=32, **kwargs):
        data = _maybe_from_dataloader(data, self.config, batch_size)
        if self.engine.params is None and self._param_loader is not None:
            from .. import utils as learn_utils
            shards = learn_utils.xshards_from_arrays(data)
            # chunked: only the first rows are ever touched, no merged copy
            chunked = learn_utils.chunk_shards(shards)
            self.engine.build(tuple(np.asarray(a[:1]) for a in chunked["x"]))
            self._load_torch_weights()
        return super().predict(data, batch_size=batch_size, **kwargs)

    def _load_torch_weights(self):
        import jax
        variables = {"params": jax.device_get(self.engine.params),
                     **jax.device_get(self.engine.extra_vars)}
        loaded = self._param_loader(variables)
        state = self.engine.get_state()
        state["params"] = loaded["params"]
        state["extra_vars"] = {k: v for k, v in loaded.items()
                               if k != "params"}
        self.engine.set_state(state)


def _maybe_from_dataloader(data, config, batch_size):
    """Accept a torch DataLoader / Dataset (or a creator returning one) and
    materialize to arrays — the reference wraps loaders with
    DistributedSampler (torch_runner.py:222-249); on TPU the iterator's
    output is just host data for the infeed."""
    try:
        import torch.utils.data as tud
    except ImportError:
        return data
    produced = data
    if callable(data) and not isinstance(data, (list, tuple, dict)):
        try:
            produced = data(config or {}, batch_size)
        except TypeError:
            return data
        if not isinstance(produced, (tud.DataLoader, tud.Dataset)):
            return data  # ordinary data_creator; handled downstream
    if isinstance(produced, tud.Dataset) and not isinstance(
            produced, tud.IterableDataset):
        produced = tud.DataLoader(produced, batch_size=len(produced))
    if isinstance(produced, tud.DataLoader):
        xs, ys = [], []
        for batch in produced:
            if isinstance(batch, (list, tuple)) and len(batch) == 2:
                x, y = batch
                xs.append(np.asarray(x))
                ys.append(np.asarray(y))
            else:
                xs.append(np.asarray(batch))
        x = np.concatenate(xs)
        if ys:
            return {"x": x, "y": np.concatenate(ys)}
        return {"x": x}
    return data
