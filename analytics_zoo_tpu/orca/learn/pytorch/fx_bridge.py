"""torch.fx-traced conversion: arbitrary ``forward()`` graphs -> flax.

The round-1 bridge (torch_bridge.py) covers ``nn.Sequential`` pipelines.
This module lifts the restriction the way the reference lifts it with
TorchScript (pyzoo/zoo/pipeline/api/torch/torch_model.py traces the module
with ``torch.jit.trace`` and ships the graph to JVM workers): here we
``torch.fx.symbolic_trace`` the module and re-emit every graph node as a
jax/flax operation, so residual adds, concats, reshapes and any other
data-flow a tracer can see compile into the one XLA program.

Layout note: unlike the Sequential fast path (which transposes to NHWC),
the fx interpreter keeps **torch's native NCHW** end-to-end — convolutions
run through ``lax.conv_general_dilated`` with ``('NCHW','OIHW','NCHW')``
dimension numbers and weights import with zero permutation. XLA:TPU lays
out conv operands internally, so this costs ~3% vs the native-NHWC flax
twin — MEASURED round 3 on a v5e chip: interleaved A/B of an fx-converted
torchvision-style ResNet-18 vs models/image/resnet.py at f32/batch 64 gave
fx/native step-time ratios 1.028 (NCHW) and 1.025 (per-conv NHWC routing —
i.e. a layout pass would buy nothing; XLA already assigns layouts). Models
written natively in flax remain the peak-perf path mainly via bf16.

Unsupported ops raise ``TorchConversionError`` naming the exact node and
op so users know what to port.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from .torch_bridge import TorchConversionError, _pair


def _sanitize(target: str) -> str:
    return str(target).replace(".", "_")


# --------------------------------------------------------------------------
# NCHW pooling / conv helpers (jax side)
# --------------------------------------------------------------------------

def _conv2d_nchw(x, w, stride, padding, groups, dilation=(1, 1)):
    import jax.lax as lax
    if isinstance(padding, str):
        pad = padding.upper()  # 'SAME' / 'VALID'
    else:
        ph, pw = _pair(padding)
        pad = [(ph, ph), (pw, pw)]
    return lax.conv_general_dilated(
        x, w, window_strides=_pair(stride), padding=pad,
        rhs_dilation=_pair(dilation),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups)


def _max_pool2d_nchw(x, kernel, stride, padding):
    import jax.lax as lax
    import jax.numpy as jnp
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    ph, pw = _pair(padding)
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, kh, kw), (1, 1, sh, sw),
        ((0, 0), (0, 0), (ph, ph), (pw, pw)))


def _avg_pool2d_nchw(x, kernel, stride, padding):
    import jax.lax as lax
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    ph, pw = _pair(padding)
    summed = lax.reduce_window(
        x, 0.0, lax.add, (1, 1, kh, kw), (1, 1, sh, sw),
        ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    return summed / (kh * kw)   # torch count_include_pad=True default


def _adaptive_avg_pool2d_nchw(x, output_size):
    out = _pair(output_size) if output_size is not None else (1, 1)
    if tuple(out) != (1, 1):
        raise TorchConversionError(
            f"adaptive_avg_pool2d only supported with output size 1, "
            f"got {output_size}")
    return x.mean(axis=(2, 3), keepdims=True)


def _flatten(x, start_dim=0, end_dim=-1):
    shape = list(x.shape)
    nd = len(shape)
    s = start_dim % nd
    e = end_dim % nd
    new_shape = shape[:s] + [int(np.prod(shape[s:e + 1]))] + shape[e + 1:]
    return x.reshape(new_shape)


def _cat(tensors, dim=0):
    import jax.numpy as jnp
    return jnp.concatenate(tensors, axis=dim)


def _f_pad(x, pad, mode="constant", value=0.0):
    """torch.nn.functional.pad: `pad` lists (left, right) pairs starting
    from the LAST dimension."""
    import jax.numpy as jnp
    if mode != "constant":
        from .torch_bridge import TorchConversionError
        raise TorchConversionError(
            f"F.pad mode={mode!r} is not supported (constant only)")
    if any(int(p) < 0 for p in pad):
        # torch treats negative pad as cropping; reject loudly rather than
        # letting jnp.pad raise an opaque ValueError at apply time
        from .torch_bridge import TorchConversionError
        raise TorchConversionError(
            f"F.pad with negative (cropping) widths {tuple(pad)} is not "
            "supported; slice the tensor instead")
    widths = [(0, 0)] * x.ndim
    for i in range(len(pad) // 2):
        widths[x.ndim - 1 - i] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    return jnp.pad(x, widths, constant_values=value)


def _build_function_table() -> Dict[Any, Callable]:
    import jax
    import jax.numpy as jnp
    import torch
    import torch.nn.functional as F

    def act(fn):
        return lambda x, *a, inplace=False, **k: fn(x, *a, **k)

    table: Dict[Any, Callable] = {
        operator.add: operator.add, operator.iadd: operator.add,
        operator.sub: operator.sub, operator.mul: operator.mul,
        operator.imul: operator.mul, operator.truediv: operator.truediv,
        operator.neg: operator.neg, operator.getitem: operator.getitem,
        operator.matmul: jnp.matmul,
        operator.gt: operator.gt, operator.lt: operator.lt,
        operator.ge: operator.ge, operator.le: operator.le,
        operator.eq: operator.eq, operator.ne: operator.ne,
        torch.add: lambda a, b, alpha=1: a + alpha * b,
        torch.sub: lambda a, b, alpha=1: a - alpha * b,
        torch.mul: operator.mul,
        torch.div: operator.truediv,
        torch.matmul: jnp.matmul,
        torch.bmm: jnp.matmul,
        torch.cat: _cat,
        torch.concat: _cat,
        torch.stack: lambda ts, dim=0: jnp.stack(ts, axis=dim),
        torch.flatten: _flatten,
        torch.relu: act(jax.nn.relu),
        torch.sigmoid: jax.nn.sigmoid,
        torch.tanh: jnp.tanh,
        torch.exp: jnp.exp,
        torch.mean: lambda x, dim=None, keepdim=False: jnp.mean(
            x, axis=dim, keepdims=keepdim),
        torch.sum: lambda x, dim=None, keepdim=False: jnp.sum(
            x, axis=dim, keepdims=keepdim),
        torch.transpose: lambda x, d0, d1: jnp.swapaxes(x, d0, d1),
        torch.permute: lambda x, dims: jnp.transpose(x, dims),
        torch.softmax: lambda x, dim=-1: jax.nn.softmax(x, axis=dim),
        torch.unsqueeze: lambda x, dim: jnp.expand_dims(x, dim),
        torch.squeeze: lambda x, dim=None: jnp.squeeze(x, axis=dim),
        F.relu: act(jax.nn.relu),
        F.relu6: act(jax.nn.relu6),
        F.elu: act(jax.nn.elu),
        F.gelu: lambda x, approximate="none": jax.nn.gelu(
            x, approximate=approximate != "none"),
        F.silu: act(jax.nn.silu),
        F.leaky_relu: lambda x, negative_slope=0.01, inplace=False:
            jax.nn.leaky_relu(x, negative_slope),
        F.hardtanh: lambda x, min_val=-1.0, max_val=1.0, inplace=False:
            jnp.clip(x, min_val, max_val),
        F.sigmoid: jax.nn.sigmoid,
        F.tanh: jnp.tanh,
        F.softmax: lambda x, dim=-1, **k: jax.nn.softmax(x, axis=dim),
        F.log_softmax: lambda x, dim=-1, **k: jax.nn.log_softmax(
            x, axis=dim),
        F.max_pool2d: _max_pool2d_nchw,
        F.avg_pool2d: _avg_pool2d_nchw,
        F.adaptive_avg_pool2d: _adaptive_avg_pool2d_nchw,
        F.flatten if hasattr(F, "flatten") else torch.flatten: _flatten,
        F.normalize: lambda x, p=2.0, dim=1, eps=1e-12:
            x / jnp.maximum(jnp.linalg.norm(x, ord=p, axis=dim,
                                            keepdims=True), eps),
        torch.clamp: lambda x, min=None, max=None: jnp.clip(x, min, max),
        torch.pow: lambda x, p: x ** p,
        operator.pow: operator.pow,
        torch.sqrt: jnp.sqrt,
        torch.rsqrt: lambda x: 1.0 / jnp.sqrt(x),
        torch.abs: jnp.abs,
        torch.minimum: jnp.minimum,
        torch.maximum: jnp.maximum,
        torch.where: jnp.where,
        torch.log: jnp.log,
        torch.log1p: jnp.log1p,
        torch.erf: lambda x: jax.scipy.special.erf(x),
        F.pad: _f_pad,
        F.dropout: _f_dropout,
    }
    return table


def _f_dropout(x, p=0.5, training=False, inplace=False):
    """F.dropout converts as identity ONLY when the traced training flag is
    False — fx concretizes `training=self.training` at trace time, and a
    silently-dropped train-mode dropout would change training dynamics.
    Use nn.Dropout modules for convertible dropout (they map to flax
    Dropout honoring the train flag)."""
    if training:
        from .torch_bridge import TorchConversionError
        raise TorchConversionError(
            "F.dropout(..., training=True) cannot be converted (the traced "
            "flag is baked in); use an nn.Dropout module instead, which "
            "maps to flax Dropout")
    return x


_METHODS: Dict[str, Callable] = {}


def _build_method_table() -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    def size(x, dim=None):
        return x.shape if dim is None else x.shape[dim]

    return {
        "view": lambda x, *shape: x.reshape(
            shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple,
                                                                  list))
            else shape),
        "reshape": lambda x, *shape: x.reshape(
            shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple,
                                                                  list))
            else shape),
        "flatten": _flatten,
        "contiguous": lambda x: x,
        "clone": lambda x: x,
        "detach": lambda x: x,
        "size": size,
        "dim": lambda x: x.ndim,
        "mean": lambda x, dim=None, keepdim=False: jnp.mean(
            x, axis=dim, keepdims=keepdim),
        "sum": lambda x, dim=None, keepdim=False: jnp.sum(
            x, axis=dim, keepdims=keepdim),
        "permute": lambda x, *dims: jnp.transpose(
            x, dims[0] if len(dims) == 1 and isinstance(dims[0], (tuple,
                                                                  list))
            else dims),
        "transpose": lambda x, d0, d1: jnp.swapaxes(x, d0, d1),
        "unsqueeze": lambda x, dim: jnp.expand_dims(x, dim),
        "squeeze": lambda x, dim=None: jnp.squeeze(x, axis=dim),
        "add": lambda x, y, alpha=1: x + alpha * y,
        "add_": lambda x, y, alpha=1: x + alpha * y,
        "mul": operator.mul,
        "mul_": operator.mul,
        "relu": lambda x: jax.nn.relu(x),
        "relu_": lambda x: jax.nn.relu(x),
        "sigmoid": lambda x: jax.nn.sigmoid(x),
        "tanh": jnp.tanh,
        "softmax": lambda x, dim=-1: jax.nn.softmax(x, axis=dim),
        "float": lambda x: x.astype(jnp.float32),
        "chunk": lambda x, chunks, dim=0: tuple(jnp.split(x, chunks,
                                                          axis=dim)),
        "split": lambda x, size, dim=0: tuple(
            jnp.split(x, range(size, x.shape[dim], size), axis=dim)),
        "t": lambda x: x.T,
        "expand_as": lambda x, other: jnp.broadcast_to(x, other.shape),
    }


def build_flax_from_torch_fx(module):
    """Trace ``module`` with torch.fx and return (flax_module, loader).

    The flax module interprets the traced graph node-by-node; parameters of
    call_module nodes become flax params named after the torch module path,
    so the loader is a straight state_dict copy (Linear kernels transposed,
    conv kernels kept OIHW)."""
    import torch
    import torch.nn as tnn
    import torch.fx

    try:
        gm = torch.fx.symbolic_trace(module)
    except Exception as e:
        raise TorchConversionError(
            f"torch.fx could not trace {type(module).__name__}: {e}. "
            "Dynamic control flow on tensor values cannot be converted — "
            "port the model to flax (see analytics_zoo_tpu.models).") from e

    nodes = list(gm.graph.nodes)
    submodules = dict(gm.named_modules())
    # get_attr targets: buffers/captured tensors become frozen constants,
    # but nn.Parameters accessed directly in forward() must stay TRAINABLE —
    # they become flax params initialized from the torch value
    consts: Dict[str, np.ndarray] = {}
    param_attrs: Dict[str, np.ndarray] = {}
    for node in nodes:
        if node.op == "get_attr":
            obj = gm
            for part in str(node.target).split("."):
                obj = getattr(obj, part)
            arr = (obj.detach().cpu().numpy() if hasattr(obj, "detach")
                   else np.asarray(obj))
            if isinstance(obj, torch.nn.Parameter):
                param_attrs[str(node.target)] = arr
            else:
                consts[str(node.target)] = arr

    # pre-validate module nodes so conversion errors fire at build time
    _MOD_KINDS = (tnn.Linear, tnn.Conv2d, tnn.BatchNorm1d, tnn.BatchNorm2d,
                  tnn.LayerNorm, tnn.Embedding, tnn.Dropout, tnn.Flatten,
                  tnn.MaxPool2d, tnn.AvgPool2d, tnn.AdaptiveAvgPool2d,
                  tnn.Identity, tnn.ReLU, tnn.ReLU6, tnn.GELU, tnn.SiLU,
                  tnn.ELU, tnn.Sigmoid, tnn.Tanh, tnn.Softmax,
                  tnn.LogSoftmax, tnn.LeakyReLU, tnn.Hardtanh)
    seen_targets = set()
    for node in nodes:
        if node.op == "call_module":
            if str(node.target) in seen_targets and \
                    submodules[str(node.target)].state_dict():
                # flax compact naming can't express torch weight sharing
                raise TorchConversionError(
                    f"module '{node.target}' is called more than once "
                    "(weight sharing); duplicate the layer or port the "
                    "model to flax with explicit param reuse")
            seen_targets.add(str(node.target))
            sub = submodules[str(node.target)]
            if not isinstance(sub, _MOD_KINDS):
                raise TorchConversionError(
                    f"unsupported torch module {type(sub).__name__} at "
                    f"'{node.target}' (fx path). Supported: "
                    f"{sorted(t.__name__ for t in _MOD_KINDS)}.")
            if isinstance(sub, tnn.Conv2d) and _pair(sub.dilation) != (1, 1) \
                    and _pair(sub.stride) != (1, 1):
                raise TorchConversionError(
                    f"conv with both stride and dilation at '{node.target}' "
                    "is not supported")
            if isinstance(sub, tnn.Conv2d) and sub.padding_mode != "zeros":
                raise TorchConversionError(
                    f"conv padding_mode={sub.padding_mode!r} at "
                    f"'{node.target}' is not supported (zeros only)")
            if isinstance(sub, (tnn.MaxPool2d, tnn.AvgPool2d)) and \
                    getattr(sub, "ceil_mode", False):
                raise TorchConversionError(
                    f"pool with ceil_mode=True at '{node.target}' is not "
                    "supported (output shape would silently differ)")
            if isinstance(sub, tnn.MaxPool2d) and \
                    _pair(sub.dilation) != (1, 1):
                raise TorchConversionError(
                    f"MaxPool2d with dilation at '{node.target}' is not "
                    "supported")
            if isinstance(sub, tnn.AvgPool2d) and (
                    not sub.count_include_pad
                    or sub.divisor_override is not None):
                raise TorchConversionError(
                    f"AvgPool2d with count_include_pad=False or "
                    f"divisor_override at '{node.target}' is not supported "
                    "(values would silently differ)")

    import flax.linen as fnn
    from ....ops.embedding import MXUEmbed
    import jax.numpy as jnp

    fn_table = _build_function_table()
    method_table = _build_method_table()

    for node in nodes:  # fail at conversion time, not first apply
        if node.op == "call_function" and node.target not in fn_table:
            raise TorchConversionError(
                f"unsupported function {node.target} at node '{node.name}'."
                " Port this op to flax or extend fx_bridge's function "
                "table.")
        if node.op == "call_method" and node.target not in method_table:
            raise TorchConversionError(
                f"unsupported tensor method .{node.target}() at node "
                f"'{node.name}'. Port this op to flax or extend fx_bridge's "
                "method table.")

    class FxConverted(fnn.Module):
        @fnn.compact
        def __call__(self, *args, train: bool = False):
            env: Dict[str, Any] = {}
            arg_iter = iter(args)

            def lookup(a):
                return torch.fx.map_arg(a, lambda n: env[n.name])

            out = None
            for node in nodes:
                if node.op == "placeholder":
                    try:
                        env[node.name] = next(arg_iter)
                    except StopIteration:
                        # placeholder with default (e.g. train flag)
                        env[node.name] = node.args[0] if node.args else None
                elif node.op == "get_attr":
                    target = str(node.target)
                    if target in param_attrs:
                        init_val = param_attrs[target]
                        env[node.name] = self.param(
                            _sanitize(target),
                            lambda rng, v=init_val: jnp.asarray(v))
                    else:
                        env[node.name] = jnp.asarray(consts[target])
                elif node.op == "call_module":
                    sub = submodules[str(node.target)]
                    x = lookup(node.args)[0]
                    env[node.name] = self._apply_module(
                        str(node.target), sub, x, train)
                elif node.op == "call_function":
                    fn = fn_table.get(node.target)
                    if fn is None:
                        raise TorchConversionError(
                            f"unsupported function {node.target} at node "
                            f"'{node.name}'")
                    env[node.name] = fn(*lookup(node.args),
                                        **lookup(node.kwargs))
                elif node.op == "call_method":
                    fn = method_table.get(node.target)
                    if fn is None:
                        raise TorchConversionError(
                            f"unsupported tensor method .{node.target}() at "
                            f"node '{node.name}'")
                    env[node.name] = fn(*lookup(node.args),
                                        **lookup(node.kwargs))
                elif node.op == "output":
                    out = lookup(node.args)[0]
            return out

        def _apply_module(self, target, sub, x, train):
            import torch.nn as tnn
            import jax
            nm = _sanitize(target)
            if isinstance(sub, tnn.Linear):
                return fnn.Dense(sub.out_features,
                                 use_bias=sub.bias is not None, name=nm)(x)
            if isinstance(sub, tnn.Conv2d):
                # kernel is stored OIHW (torch layout); lecun_normal assumes
                # (..., fan_in, fan_out) so fan axes must be given explicitly:
                # fan_in = in_channels/groups * kh * kw (axes 1,2,3), out = 0
                kernel = self.param(
                    nm + "_kernel",
                    fnn.initializers.variance_scaling(
                        1.0, "fan_in", "truncated_normal",
                        in_axis=(1, 2, 3), out_axis=0),
                    (sub.out_channels, sub.in_channels // sub.groups,
                     *_pair(sub.kernel_size)))
                y = _conv2d_nchw(x, kernel, sub.stride, sub.padding,
                                 sub.groups, sub.dilation)
                if sub.bias is not None:
                    bias = self.param(nm + "_bias", fnn.initializers.zeros,
                                      (sub.out_channels,))
                    y = y + bias.reshape(1, -1, 1, 1)
                return y
            if isinstance(sub, (tnn.BatchNorm1d, tnn.BatchNorm2d)):
                axis = 1 if x.ndim > 2 else -1
                # torch momentum=None means cumulative averaging (no flax
                # analogue; use the 0.1 default); momentum=0.0 means frozen
                # stats, which maps to flax momentum=1.0 — `or 0.1` would
                # silently turn frozen BN into updating BN
                t_mom = 0.1 if sub.momentum is None else sub.momentum
                return fnn.BatchNorm(
                    use_running_average=not train,
                    momentum=1.0 - t_mom, epsilon=sub.eps,
                    axis=axis, use_bias=sub.affine, use_scale=sub.affine,
                    name=nm)(x)
            if isinstance(sub, tnn.LayerNorm):
                if len(sub.normalized_shape) != 1:
                    raise TorchConversionError(
                        f"LayerNorm over multiple dims at '{target}'")
                affine = sub.elementwise_affine
                return fnn.LayerNorm(epsilon=sub.eps, use_scale=affine,
                                     use_bias=affine and sub.bias is not None,
                                     name=nm)(x)
            if isinstance(sub, tnn.Embedding):
                return MXUEmbed(sub.num_embeddings, sub.embedding_dim,
                                 name=nm)(x.astype(jnp.int32))
            if isinstance(sub, tnn.Dropout):
                return fnn.Dropout(rate=sub.p, deterministic=not train,
                                   name=nm)(x)
            if isinstance(sub, tnn.Flatten):
                return _flatten(x, sub.start_dim, sub.end_dim)
            if isinstance(sub, tnn.MaxPool2d):
                return _max_pool2d_nchw(x, sub.kernel_size, sub.stride,
                                        sub.padding)
            if isinstance(sub, tnn.AvgPool2d):
                return _avg_pool2d_nchw(x, sub.kernel_size, sub.stride,
                                        sub.padding)
            if isinstance(sub, tnn.AdaptiveAvgPool2d):
                return _adaptive_avg_pool2d_nchw(x, sub.output_size)
            if isinstance(sub, tnn.Identity):
                return x
            if isinstance(sub, tnn.ReLU):
                return jax.nn.relu(x)
            if isinstance(sub, tnn.ReLU6):
                return jax.nn.relu6(x)
            if isinstance(sub, tnn.GELU):
                return jax.nn.gelu(x, approximate=sub.approximate != "none")
            if isinstance(sub, tnn.SiLU):
                return jax.nn.silu(x)
            if isinstance(sub, tnn.ELU):
                return jax.nn.elu(x, sub.alpha)
            if isinstance(sub, tnn.Sigmoid):
                return jax.nn.sigmoid(x)
            if isinstance(sub, tnn.Tanh):
                return jnp.tanh(x)
            if isinstance(sub, tnn.Softmax):
                return jax.nn.softmax(x, axis=sub.dim if sub.dim is not None
                                      else -1)
            if isinstance(sub, tnn.LogSoftmax):
                return jax.nn.log_softmax(x, axis=sub.dim
                                          if sub.dim is not None else -1)
            if isinstance(sub, tnn.LeakyReLU):
                return jax.nn.leaky_relu(x, sub.negative_slope)
            if isinstance(sub, tnn.Hardtanh):
                return jnp.clip(x, sub.min_val, sub.max_val)
            raise TorchConversionError(
                f"unsupported torch module {type(sub).__name__} at "
                f"'{target}'")

    # ---- weight import -----------------------------------------------------
    state = {k: v.detach().cpu().numpy()
             for k, v in module.state_dict().items()}

    def load_params(variables):
        import jax
        variables = jax.tree.map(np.asarray, jax.device_get(variables))
        params = dict(variables.get("params", {}))
        batch_stats = dict(variables.get("batch_stats", {}))
        for target in param_attrs:      # directly-accessed nn.Parameters
            params[_sanitize(target)] = state[target]
        for node in nodes:
            if node.op != "call_module":
                continue
            target = str(node.target)
            sub = submodules[target]
            nm = _sanitize(target)
            if isinstance(sub, tnn.Linear):
                params[nm] = {"kernel": state[f"{target}.weight"].T}
                if sub.bias is not None:
                    params[nm]["bias"] = state[f"{target}.bias"]
            elif isinstance(sub, tnn.Conv2d):
                params[nm + "_kernel"] = state[f"{target}.weight"]  # OIHW
                if sub.bias is not None:
                    params[nm + "_bias"] = state[f"{target}.bias"]
            elif isinstance(sub, (tnn.BatchNorm1d, tnn.BatchNorm2d)):
                if sub.affine:
                    params[nm] = {"scale": state[f"{target}.weight"],
                                  "bias": state[f"{target}.bias"]}
                batch_stats[nm] = {
                    "mean": state[f"{target}.running_mean"],
                    "var": state[f"{target}.running_var"]}
            elif isinstance(sub, tnn.LayerNorm):
                if sub.elementwise_affine:
                    params[nm] = {"scale": state[f"{target}.weight"]}
                    if sub.bias is not None:
                        params[nm]["bias"] = state[f"{target}.bias"]
            elif isinstance(sub, tnn.Embedding):
                params[nm] = {"embedding": state[f"{target}.weight"]}
        out = {"params": params}
        if batch_stats:
            out["batch_stats"] = batch_stats
        return out

    return FxConverted(), load_params
