"""torch -> flax conversion for the ``from_torch`` estimator path.

The reference executes pickled torch modules inside JVM workers via JEP
(pyzoo/zoo/pipeline/api/torch/torch_model.py; zoo/.../net/TorchModel.scala:34)
or DDP-gloo Ray actors (orca/learn/pytorch/torch_runner.py:136). Neither can
target a TPU. The TPU-native route: translate the module graph into flax and
import the weights, so the whole train step compiles to XLA.

Round-1 coverage: ``nn.Sequential`` pipelines (and modules whose forward is
the default container behavior) over the common layer set — Linear, Conv2d,
BatchNorm1d/2d, LayerNorm, Embedding, Dropout, Flatten, MaxPool2d, AvgPool2d,
AdaptiveAvgPool2d(1), ReLU/GELU/Sigmoid/Tanh/Softmax/LogSoftmax/LeakyReLU.
Layout is handled TPU-first: inputs stay NCHW at the boundary (torch
convention) and are transposed to NHWC internally so convs hit the MXU; the
first Linear after a Flatten gets its weight columns permuted to match.
Arbitrary custom ``forward`` code is out of scope (needs tracing a la
torch_xla2) and raises with guidance.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class TorchConversionError(ValueError):
    pass


def _op_specs_from_torch(module) -> List[Dict[str, Any]]:
    import torch.nn as tnn

    specs: List[Dict[str, Any]] = []

    def emit(m, prefix: str):
        name = f"{prefix}" if prefix else "root"
        t = type(m)
        if isinstance(m, tnn.Sequential):
            for child_name, child in m.named_children():
                emit(child, f"{prefix}.{child_name}" if prefix else child_name)
            return
        if isinstance(m, tnn.Linear):
            specs.append({"kind": "linear", "out": m.out_features,
                          "bias": m.bias is not None, "src": name})
        elif isinstance(m, tnn.Conv2d):
            if m.groups != 1:
                raise TorchConversionError(
                    f"grouped conv not supported yet ({name})")
            specs.append({"kind": "conv2d", "out": m.out_channels,
                          "kernel": tuple(m.kernel_size),
                          "stride": tuple(m.stride),
                          "padding": tuple(m.padding) if isinstance(
                              m.padding, (tuple, list)) else m.padding,
                          "bias": m.bias is not None, "src": name})
        elif isinstance(m, (tnn.BatchNorm1d, tnn.BatchNorm2d)):
            specs.append({"kind": "batchnorm", "eps": m.eps,
                          "momentum": 1.0 - (m.momentum or 0.1), "src": name})
        elif isinstance(m, tnn.LayerNorm):
            specs.append({"kind": "layernorm", "eps": m.eps, "src": name})
        elif isinstance(m, tnn.Embedding):
            specs.append({"kind": "embedding", "num": m.num_embeddings,
                          "dim": m.embedding_dim, "src": name})
        elif isinstance(m, tnn.Dropout):
            specs.append({"kind": "dropout", "rate": m.p, "src": name})
        elif isinstance(m, tnn.Flatten):
            specs.append({"kind": "flatten", "src": name})
        elif isinstance(m, tnn.MaxPool2d):
            specs.append({"kind": "maxpool", "kernel": _pair(m.kernel_size),
                          "stride": _pair(m.stride or m.kernel_size),
                          "padding": _pair(m.padding), "src": name})
        elif isinstance(m, tnn.AvgPool2d):
            specs.append({"kind": "avgpool", "kernel": _pair(m.kernel_size),
                          "stride": _pair(m.stride or m.kernel_size),
                          "padding": _pair(m.padding), "src": name})
        elif isinstance(m, tnn.AdaptiveAvgPool2d):
            specs.append({"kind": "globalavgpool", "src": name})
        elif isinstance(m, tnn.ReLU):
            specs.append({"kind": "act", "fn": "relu", "src": name})
        elif isinstance(m, tnn.LeakyReLU):
            specs.append({"kind": "act", "fn": "leaky_relu",
                          "slope": m.negative_slope, "src": name})
        elif isinstance(m, tnn.GELU):
            specs.append({"kind": "act", "fn": "gelu", "src": name})
        elif isinstance(m, tnn.Sigmoid):
            specs.append({"kind": "act", "fn": "sigmoid", "src": name})
        elif isinstance(m, tnn.Tanh):
            specs.append({"kind": "act", "fn": "tanh", "src": name})
        elif isinstance(m, tnn.Softmax):
            specs.append({"kind": "act", "fn": "softmax", "src": name})
        elif isinstance(m, tnn.LogSoftmax):
            specs.append({"kind": "act", "fn": "log_softmax", "src": name})
        elif isinstance(m, tnn.Identity):
            pass
        else:
            raise TorchConversionError(
                f"unsupported torch module {t.__name__} at '{name}'. "
                "from_torch covers nn.Sequential over standard layers; for "
                "custom forward() code, port the model to flax (see "
                "analytics_zoo_tpu.models for templates) or express it as a "
                "jax model_creator.")

    import torch.nn as tnn2
    if isinstance(module, tnn2.Sequential):
        emit(module, "")
    elif type(module).forward is tnn2.Module.forward:
        emit(module, "")
    else:
        # any overridden forward() — even one that only calls a child
        # Sequential — may add logic a layer walk can't see (e.g.
        # `return self.seq(x) + 1`); route to the fx graph tracer, which
        # converts the actual data flow
        raise TorchConversionError(
            f"{type(module).__name__} has a custom forward(); tracing "
            "required")
    return specs


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def build_flax_from_torch(module):
    """Return (flax_module, param_loader) where param_loader(variables)
    overwrites initialized variables with the torch weights.

    Sequential-style modules take the NHWC fast path below; anything with a
    custom ``forward()`` falls through to the torch.fx graph tracer
    (fx_bridge.py), which handles residuals/concats/reshapes generally."""
    import flax.linen as fnn
    from ....ops.embedding import MXUEmbed
    import jax.numpy as jnp

    try:
        specs = tuple((tuple(sorted(s.items(), key=lambda kv: kv[0])))
                      for s in _op_specs_from_torch(module))
    except TorchConversionError:
        from .fx_bridge import build_flax_from_torch_fx
        return build_flax_from_torch_fx(module)
    spec_dicts = [dict(s) for s in specs]

    class TorchConverted(fnn.Module):
        @fnn.compact
        def __call__(self, x, train: bool = False):
            layout_nhwc = False
            if x.ndim == 4:  # NCHW at the boundary -> NHWC inside
                x = jnp.transpose(x, (0, 2, 3, 1))
                layout_nhwc = True
            for i, s in enumerate(spec_dicts):
                k = s["kind"]
                nm = f"op_{i}"
                if k == "linear":
                    x = fnn.Dense(s["out"], use_bias=s["bias"], name=nm)(x)
                elif k == "conv2d":
                    pad = s["padding"]
                    pad = [(pad[0], pad[0]), (pad[1], pad[1])] if isinstance(
                        pad, tuple) else pad
                    x = fnn.Conv(s["out"], s["kernel"], s["stride"],
                                 padding=pad, use_bias=s["bias"], name=nm)(x)
                elif k == "batchnorm":
                    x = fnn.BatchNorm(use_running_average=not train,
                                      momentum=s["momentum"], epsilon=s["eps"],
                                      name=nm)(x)
                elif k == "layernorm":
                    x = fnn.LayerNorm(epsilon=s["eps"], name=nm)(x)
                elif k == "embedding":
                    x = MXUEmbed(s["num"], s["dim"], name=nm)(
                        x.astype(jnp.int32))
                elif k == "dropout":
                    x = fnn.Dropout(rate=s["rate"], deterministic=not train,
                                    name=nm)(x)
                elif k == "flatten":
                    if layout_nhwc and x.ndim == 4:
                        # torch flattens CHW; permute back so weights line up
                        x = jnp.transpose(x, (0, 3, 1, 2))
                        layout_nhwc = False
                    x = x.reshape(x.shape[0], -1)
                elif k == "maxpool":
                    pad = [(p, p) for p in s["padding"]]
                    x = fnn.max_pool(x, s["kernel"], s["stride"], pad)
                elif k == "avgpool":
                    pad = [(p, p) for p in s["padding"]]
                    x = fnn.avg_pool(x, s["kernel"], s["stride"], pad)
                elif k == "globalavgpool":
                    x = x.mean(axis=(1, 2))
                    layout_nhwc = False
                elif k == "act":
                    import jax
                    fn = s["fn"]
                    if fn == "leaky_relu":
                        x = jax.nn.leaky_relu(x, s.get("slope", 0.01))
                    elif fn in ("softmax", "log_softmax"):
                        x = getattr(jax.nn, fn)(x, axis=-1)
                    else:
                        x = getattr(jax.nn, fn)(x)
            return x

    # ---- weight import -----------------------------------------------------
    state = {k: v.detach().cpu().numpy() for k, v in module.state_dict().items()}

    def load_params(variables):
        import jax
        variables = jax.tree.map(np.asarray, jax.device_get(variables))
        params = dict(variables.get("params", {}))
        batch_stats = dict(variables.get("batch_stats", {}))
        for i, s in enumerate(spec_dicts):
            nm, src, k = f"op_{i}", s["src"], s["kind"]
            if k == "linear":
                w = state[f"{src}.weight"].T  # torch (out,in) -> (in,out)
                params[nm] = {"kernel": w}
                if s["bias"]:
                    params[nm]["bias"] = state[f"{src}.bias"]
            elif k == "conv2d":
                w = np.transpose(state[f"{src}.weight"], (2, 3, 1, 0))  # OIHW->HWIO
                params[nm] = {"kernel": w}
                if s["bias"]:
                    params[nm]["bias"] = state[f"{src}.bias"]
            elif k == "batchnorm":
                params[nm] = {"scale": state[f"{src}.weight"],
                              "bias": state[f"{src}.bias"]}
                batch_stats[nm] = {"mean": state[f"{src}.running_mean"],
                                   "var": state[f"{src}.running_var"]}
            elif k == "layernorm":
                params[nm] = {"scale": state[f"{src}.weight"],
                              "bias": state[f"{src}.bias"]}
            elif k == "embedding":
                params[nm] = {"embedding": state[f"{src}.weight"]}
        out = {"params": params}
        if batch_stats:
            out["batch_stats"] = batch_stats
        return out

    return TorchConverted(), load_params


def convert_torch_loss(loss) -> Optional[Callable]:
    """torch loss instance/class -> our jax loss fn."""
    from .. import losses as L
    if loss is None or callable(loss) and not _is_torch_loss(loss):
        return loss
    name = type(loss).__name__ if not isinstance(loss, type) else loss.__name__
    table = {
        "MSELoss": L.mean_squared_error,
        "L1Loss": L.mean_absolute_error,
        "BCELoss": L.binary_crossentropy,
        "BCEWithLogitsLoss": lambda t, p: L.binary_crossentropy(
            t, p, from_logits=True),
        "CrossEntropyLoss": lambda t, p: L.sparse_categorical_crossentropy(
            t, p, from_logits=True),
        "NLLLoss": lambda t, p: L.sparse_categorical_crossentropy(
            t, np_exp_safe(p), from_logits=False),
        "SmoothL1Loss": L.huber,
        "HingeEmbeddingLoss": L.hinge,
        "KLDivLoss": L.kld,
    }
    if name not in table:
        raise TorchConversionError(f"unsupported torch loss {name}")
    return table[name]


def np_exp_safe(p):
    import jax.numpy as jnp
    return jnp.exp(p)


def _is_torch_loss(obj) -> bool:
    try:
        import torch.nn as tnn
        return isinstance(obj, tnn.modules.loss._Loss)
    except Exception:
        return False


def convert_torch_optimizer(opt_or_creator, model=None):
    """torch.optim instance -> optax transform (by class + hyperparams)."""
    import optax
    try:
        import torch.optim as topt
    except ImportError:
        return None
    opt = opt_or_creator
    if not isinstance(opt, topt.Optimizer):
        return None
    g = opt.param_groups[0]
    name = type(opt).__name__
    if name == "SGD":
        tx = optax.sgd(g["lr"], momentum=g.get("momentum") or None,
                       nesterov=g.get("nesterov", False))
    elif name in ("Adam", "AdamW"):
        b1, b2 = g.get("betas", (0.9, 0.999))
        maker = optax.adamw if name == "AdamW" else optax.adam
        kwargs = {"b1": b1, "b2": b2, "eps": g.get("eps", 1e-8)}
        if name == "AdamW":
            kwargs["weight_decay"] = g.get("weight_decay", 0.01)
        tx = maker(g["lr"], **kwargs)
    elif name == "RMSprop":
        tx = optax.rmsprop(g["lr"], decay=g.get("alpha", 0.99),
                           eps=g.get("eps", 1e-8))
    elif name == "Adagrad":
        tx = optax.adagrad(g["lr"])
    else:
        raise TorchConversionError(f"unsupported torch optimizer {name}")
    wd = g.get("weight_decay", 0)
    if wd and name not in ("AdamW",):
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx
