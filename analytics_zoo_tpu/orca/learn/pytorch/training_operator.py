"""TrainingOperator — the user hook surface of the reference's Ray torch path
(pyzoo/zoo/orca/learn/pytorch/training_operator.py:56-466: setup, train_epoch,
train_batch, validate, validate_batch, predict_batch, state_dict hooks plus
model/optimizer/config/world_rank properties).

On TPU the default hooks delegate to the jitted engine; overriding
``train_batch``/``validate_batch`` lets users inject custom per-batch logic
(host-side — e.g. logging, curriculum) around the compiled step. Heavy custom
math belongs in the model/loss, where it compiles."""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np


class TrainingOperator:
    def __init__(self, config: Dict, engine, world_rank: int = 0):
        self._config = config
        self._engine = engine
        self._world_rank = world_rank
        self.setup(config)

    # --- overridable hooks --------------------------------------------------
    def setup(self, config: Dict):
        """(reference: training_operator.py:128)"""

    def train_epoch(self, iterator: Iterator, info: Dict) -> Dict[str, float]:
        """(reference: training_operator.py:137) — iterate batches, call
        train_batch, aggregate."""
        losses, n = [], 0
        for batch_idx, batch in enumerate(iterator):
            m = self.train_batch(batch, {"batch_idx": batch_idx, **info})
            losses.append(m["train_loss"])
            n += m.get("num_samples", 0)
        return {"train_loss": float(np.mean(losses)) if losses else 0.0,
                "num_samples": n}

    def train_batch(self, batch, batch_info: Dict) -> Dict[str, float]:
        """(reference: training_operator.py:220)"""
        import jax
        loss = self._engine.train_batch(batch)
        n = (len(batch.x[0]) if batch.w is None     # None == unpadded batch
             else int(batch.w.sum()))
        return {"train_loss": float(jax.device_get(loss)),
                "num_samples": n}

    def validate(self, val_iterator: Iterator, info: Dict, metrics
                 ) -> Dict[str, float]:
        """(reference: training_operator.py:284)"""
        import jax
        states = self._engine.init_metric_states()
        loss_sum, count = 0.0, 0.0
        for batch in val_iterator:
            states, bl, n = self._engine.eval_batch(states, batch)
            loss_sum += float(jax.device_get(bl))
            count += float(jax.device_get(n))
        return self._engine.finalize_metrics(states, loss_sum, count)

    def predict_batch(self, batch):
        """(reference: training_operator.py:341)"""
        return self._engine.predict_batch(batch.x)

    def state_dict(self) -> Dict[str, Any]:
        """(reference: training_operator.py:395)"""
        return self._engine.get_state()

    def load_state_dict(self, state_dict: Dict[str, Any]):
        self._engine.set_state(state_dict)

    # --- properties (reference: training_operator.py:410-466) ---------------
    @property
    def config(self) -> Dict:
        return self._config

    @property
    def model(self):
        return self._engine.module

    @property
    def optimizer(self):
        return self._engine.tx

    @property
    def world_rank(self) -> int:
        return self._world_rank

    @property
    def criterion(self):
        return self._engine.loss_fn
