from .estimator import Estimator, TF2TPUEstimator

__all__ = ["Estimator", "TF2TPUEstimator"]
