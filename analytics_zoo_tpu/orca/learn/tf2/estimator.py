"""Orca TF2 estimator — ``from_keras(model_creator)`` on the TPU engine.

Reference surface: pyzoo/zoo/orca/learn/tf2/estimator.py:36-93 (from_keras
with model_creator/config/workers_per_node/backend) and TensorFlow2Estimator
fit/evaluate/predict (:166-405). The Ray-actor + MultiWorkerMirroredStrategy
machinery (tf2/tf_runner.py:226-360) is replaced by keras->flax conversion +
the single jitted engine; ``backend`` ("tf2"/"horovod"/"ray") is accepted for
source compatibility and ignored.

model_creator(config) may return:
* a compiled tf.keras model  — converted (layers + weights + compile args);
* a flax module              — used directly (recommended);
* (module, loss, optimizer)  — explicit jax triple.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ..estimator import TPUEstimator


def _is_keras_model(obj) -> bool:
    try:
        import tensorflow as tf
        return isinstance(obj, tf.keras.Model)
    except Exception:
        return False


class Estimator:
    @staticmethod
    def from_keras(model_creator: Optional[Callable] = None,
                   config: Optional[dict] = None, verbose: bool = False,
                   workers_per_node: int = 1, compile_args_creator=None,
                   backend: str = "tf2", cpu_binding: bool = False,
                   model_dir: Optional[str] = None,
                   loss=None, optimizer=None, metrics=None, **_):
        cfg = dict(config or {})
        model = model_creator(cfg)
        if isinstance(model, tuple):
            module, loss, optimizer = model
            return TF2TPUEstimator(module, loss=loss,
                                   optimizer=optimizer or "adam",
                                   metrics=metrics, model_dir=model_dir,
                                   config=cfg)
        if _is_keras_model(model):
            from .keras_bridge import build_flax_from_keras, extract_compile_args
            module, loader = build_flax_from_keras(model)
            k_loss, k_opt, k_metrics = extract_compile_args(model)
            est = TF2TPUEstimator(module, loss=loss or k_loss,
                                  optimizer=optimizer or k_opt,
                                  metrics=metrics or k_metrics,
                                  model_dir=model_dir, config=cfg)
            est._param_loader = loader
            return est
        return TF2TPUEstimator(model, loss=loss, optimizer=optimizer or "adam",
                               metrics=metrics, model_dir=model_dir,
                               config=cfg)

    latest_checkpoint = staticmethod(
        lambda model_dir: TPUEstimator and __import__(
            "analytics_zoo_tpu.orca.learn.estimator", fromlist=["Estimator"]
        ).Estimator.latest_checkpoint(model_dir))


class TF2TPUEstimator(TPUEstimator):
    _param_loader = None

    def _ensure_built_with_weights(self, data, batch_size, feature_cols=None,
                                   label_cols=None):
        if self.engine.params is not None or self._param_loader is None:
            return
        from .. import utils as learn_utils
        shards = learn_utils.xshards_from_arrays(data, feature_cols,
                                                 label_cols) \
            if not callable(data) else None
        if shards is None:
            it = learn_utils.data_to_iterator(data, batch_size, self.mesh,
                                              feature_cols, label_cols,
                                              config=self.config)
            sample = next(it.epoch(shuffle=False, prefetch=False))
            self.engine.build(tuple(np.asarray(a) for a in sample.x))
        else:
            # chunked: only the first rows are ever touched, no merged copy
            chunked = learn_utils.chunk_shards(shards)
            self.engine.build(tuple(np.asarray(a[:1])
                                    for a in chunked["x"]))
        self._load_keras_weights()

    def _load_keras_weights(self):
        import jax
        variables = {"params": jax.device_get(self.engine.params),
                     **jax.device_get(self.engine.extra_vars)}
        loaded = self._param_loader(variables)
        state = self.engine.get_state()
        state["params"] = loaded["params"]
        state["extra_vars"] = {k: v for k, v in loaded.items()
                               if k != "params"}
        self.engine.set_state(state)

    def fit(self, data, epochs=1, batch_size=32, **kwargs):
        self._ensure_built_with_weights(
            data, batch_size, kwargs.get("feature_cols"),
            kwargs.get("label_cols"))
        return super().fit(data, epochs=epochs, batch_size=batch_size,
                           **kwargs)

    def evaluate(self, data, batch_size=32, **kwargs):
        self._ensure_built_with_weights(
            data, batch_size, kwargs.get("feature_cols"),
            kwargs.get("label_cols"))
        return super().evaluate(data, batch_size=batch_size, **kwargs)

    def predict(self, data, batch_size=32, **kwargs):
        self._ensure_built_with_weights(data, batch_size,
                                        kwargs.get("feature_cols"), None)
        return super().predict(data, batch_size=batch_size, **kwargs)
