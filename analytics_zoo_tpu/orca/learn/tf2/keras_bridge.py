"""tf.keras -> flax conversion for the tf2 ``from_keras`` path.

The reference's TF2 estimator ships the user's ``model_creator`` (returning a
compiled tf.keras model) to Ray actors running MultiWorkerMirroredStrategy
(pyzoo/zoo/orca/learn/tf2/tf_runner.py:226-360). Here the keras model is
translated once, on the driver, into flax + optax + our losses/metrics (layer
configs and weights are introspectable; keras is already NHWC so no layout
gymnastics), and the jitted engine trains it on TPU.

Coverage: Sequential and Functional graphs — including branching/merge
topologies (Add/Subtract/Multiply/Average/Maximum/Minimum/Concatenate, see
``build_flax_from_keras_graph``) — over Dense, Conv2D, BatchNormalization,
LayerNormalization, Dropout, Flatten, MaxPooling2D, AveragePooling2D,
GlobalAveragePooling2D, Embedding, Activation, ReLU, Softmax, InputLayer.
Custom layers raise with porting guidance (write the model as a flax module
instead).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("analytics_zoo_tpu")


class KerasConversionError(ValueError):
    pass


_MERGE_KINDS = {"Add": "add", "Subtract": "sub", "Multiply": "mul",
                "Average": "avg", "Maximum": "max", "Minimum": "min",
                "Concatenate": "concat"}


def _dilation(cfg):
    d = cfg.get("dilation_rate", 1)
    return tuple(d) if isinstance(d, (list, tuple)) else (int(d),)


def _spec_for(lyr) -> Optional[Dict[str, Any]]:
    """Spec dict for one keras layer; None for InputLayer; raises for
    unsupported types."""
    import tensorflow as tf
    K = tf.keras.layers
    cfg = lyr.get_config()
    if isinstance(lyr, K.InputLayer):
        return None
    tname = type(lyr).__name__
    if tname in _MERGE_KINDS:
        return {"kind": "merge", "op": _MERGE_KINDS[tname],
                "axis": cfg.get("axis", -1), "name": lyr.name}
    if isinstance(lyr, K.Dense):
        return {"kind": "dense", "units": cfg["units"],
                "activation": cfg.get("activation"),
                "use_bias": cfg.get("use_bias", True), "name": lyr.name}
    _transpose_types = tuple(
        t for t in (getattr(K, "Conv1DTranspose", None),
                    getattr(K, "Conv2DTranspose", None),
                    getattr(K, "Conv3DTranspose", None)) if t)
    if isinstance(lyr, _transpose_types):
        raise KerasConversionError(
            f"transpose convolutions are not supported ('{lyr.name}'); "
            "port the model to flax (nn.ConvTranspose)")
    if isinstance(lyr, (K.Conv1D, K.Conv2D)) and not isinstance(
            lyr, (K.DepthwiseConv2D, K.SeparableConv2D)):
        return {"kind": "conv2d", "filters": cfg["filters"],
                "kernel": tuple(cfg["kernel_size"]),
                "strides": tuple(cfg["strides"]),
                "padding": cfg["padding"].upper(),
                "dilation": _dilation(cfg),
                "activation": cfg.get("activation"),
                "use_bias": cfg.get("use_bias", True), "name": lyr.name}
    if isinstance(lyr, K.DepthwiseConv2D):
        return {"kind": "depthwise_conv2d",
                "kernel": tuple(cfg["kernel_size"]),
                "strides": tuple(cfg["strides"]),
                "padding": cfg["padding"].upper(),
                "dilation": _dilation(cfg),
                "mult": cfg.get("depth_multiplier", 1),
                "activation": cfg.get("activation"),
                "use_bias": cfg.get("use_bias", True), "name": lyr.name}
    if isinstance(lyr, K.SeparableConv2D):
        return {"kind": "separable_conv2d", "filters": cfg["filters"],
                "kernel": tuple(cfg["kernel_size"]),
                "strides": tuple(cfg["strides"]),
                "padding": cfg["padding"].upper(),
                "dilation": _dilation(cfg),
                "mult": cfg.get("depth_multiplier", 1),
                "activation": cfg.get("activation"),
                "use_bias": cfg.get("use_bias", True), "name": lyr.name}
    if isinstance(lyr, K.UpSampling2D):
        if cfg.get("interpolation", "nearest") != "nearest":
            raise KerasConversionError(
                f"UpSampling2D interpolation="
                f"{cfg['interpolation']!r} ('{lyr.name}') is not supported "
                "(nearest only); use jax.image.resize in a flax module")
        return {"kind": "upsampling2d", "size": tuple(cfg["size"]),
                "name": lyr.name}
    if isinstance(lyr, K.ZeroPadding2D):
        pad = cfg["padding"]
        pad = ((pad, pad), (pad, pad)) if isinstance(pad, int) else \
            tuple(tuple(p) if isinstance(p, (list, tuple)) else (p, p)
                  for p in pad)
        return {"kind": "zeropad2d", "padding": pad, "name": lyr.name}
    if isinstance(lyr, K.GlobalMaxPooling2D):
        return {"kind": "globalmaxpool",
                "keepdims": bool(cfg.get("keepdims", False)),
                "name": lyr.name}
    if isinstance(lyr, K.MaxPooling1D):
        return {"kind": "maxpool1d", "pool": int(cfg["pool_size"][0]
                if isinstance(cfg["pool_size"], (list, tuple))
                else cfg["pool_size"]),
                "strides": int((cfg["strides"] or cfg["pool_size"])[0]
                if isinstance(cfg["strides"] or cfg["pool_size"],
                              (list, tuple))
                else (cfg["strides"] or cfg["pool_size"])),
                "padding": cfg["padding"].upper(), "name": lyr.name}
    if isinstance(lyr, K.BatchNormalization):
        return {"kind": "batchnorm", "eps": cfg["epsilon"],
                "momentum": cfg["momentum"], "name": lyr.name}
    if isinstance(lyr, K.LayerNormalization):
        return {"kind": "layernorm", "eps": cfg["epsilon"], "name": lyr.name}
    if isinstance(lyr, K.Dropout):
        return {"kind": "dropout", "rate": cfg["rate"], "name": lyr.name}
    if isinstance(lyr, K.Flatten):
        return {"kind": "flatten", "name": lyr.name}
    if isinstance(lyr, K.MaxPooling2D):
        return {"kind": "maxpool", "pool": tuple(cfg["pool_size"]),
                "strides": tuple(cfg["strides"] or cfg["pool_size"]),
                "padding": cfg["padding"].upper(), "name": lyr.name}
    if isinstance(lyr, K.AveragePooling2D):
        return {"kind": "avgpool", "pool": tuple(cfg["pool_size"]),
                "strides": tuple(cfg["strides"] or cfg["pool_size"]),
                "padding": cfg["padding"].upper(), "name": lyr.name}
    if isinstance(lyr, K.GlobalAveragePooling2D):
        return {"kind": "globalavgpool",
                "keepdims": bool(cfg.get("keepdims", False)),
                "name": lyr.name}
    if isinstance(lyr, K.Embedding):
        return {"kind": "embedding", "num": cfg["input_dim"],
                "dim": cfg["output_dim"], "name": lyr.name}
    if isinstance(lyr, K.Activation):
        return {"kind": "act", "fn": cfg["activation"], "name": lyr.name}
    if isinstance(lyr, K.ReLU):
        return {"kind": "act", "fn": "relu", "name": lyr.name}
    if isinstance(lyr, K.Softmax):
        return {"kind": "act", "fn": "softmax", "name": lyr.name}
    raise KerasConversionError(
        f"unsupported keras layer {type(lyr).__name__} ('{lyr.name}')."
        " Supported: Dense/Conv2D/BN/LN/Dropout/Flatten/pooling/"
        "Embedding/Activation/Add/Concatenate and friends. For custom "
        "layers, write the model as a flax module (see analytics_zoo_tpu."
        "models) and use Estimator.from_keras(model=flax_module).")


def _layer_specs(model) -> List[Dict[str, Any]]:
    layers = getattr(model, "layers", None)
    if layers is None:
        raise KerasConversionError("expected a keras Model")
    specs: List[Dict[str, Any]] = []
    for lyr in layers:
        s = _spec_for(lyr)
        if s is None:
            continue
        if s["kind"] == "merge":
            raise KerasConversionError(
                f"merge layer '{lyr.name}' in a Sequential walk — use the "
                "functional graph path")
        specs.append(s)
    return specs


_ACTS = {"relu", "sigmoid", "tanh", "softmax", "gelu", "elu", "selu",
         "softplus", "silu", "swish", "log_softmax"}


def _apply_act(x, fn: Optional[str]):
    import jax
    if not fn or fn == "linear":
        return x
    if fn == "swish":
        fn = "silu"
    if fn == "softmax" or fn == "log_softmax":
        return getattr(jax.nn, fn)(x, axis=-1)
    if fn not in _ACTS:
        raise KerasConversionError(f"unsupported activation '{fn}'")
    return getattr(jax.nn, fn)(x)


def _run_spec(s: Dict[str, Any], xs: list, nm: str, train: bool):
    """Apply one layer spec to its inputs. Must be called from inside a
    flax compact __call__ (submodules register against the caller)."""
    import flax.linen as fnn
    from ....ops.embedding import MXUEmbed
    import jax.numpy as jnp

    k = s["kind"]
    x = xs[0]
    if k == "merge":
        op = s["op"]
        if op == "concat":
            return jnp.concatenate(xs, axis=s.get("axis", -1))
        if op == "add":
            return sum(xs[1:], xs[0])
        if op == "sub":
            return xs[0] - xs[1]
        if op == "mul":
            out = xs[0]
            for o in xs[1:]:
                out = out * o
            return out
        if op == "avg":
            return sum(xs[1:], xs[0]) / len(xs)
        if op == "max":
            out = xs[0]
            for o in xs[1:]:
                out = jnp.maximum(out, o)
            return out
        if op == "min":
            out = xs[0]
            for o in xs[1:]:
                out = jnp.minimum(out, o)
            return out
    if k == "dense":
        x = fnn.Dense(s["units"], use_bias=s["use_bias"], name=nm)(x)
        return _apply_act(x, s.get("activation"))
    if k == "conv2d":                   # 1D and 2D convs (kernel rank)
        x = fnn.Conv(s["filters"], s["kernel"], s["strides"],
                     padding=s["padding"], use_bias=s["use_bias"],
                     kernel_dilation=s.get("dilation"), name=nm)(x)
        return _apply_act(x, s.get("activation"))
    if k == "depthwise_conv2d":
        in_ch = x.shape[-1]
        x = fnn.Conv(in_ch * s["mult"], s["kernel"], s["strides"],
                     padding=s["padding"], use_bias=s["use_bias"],
                     kernel_dilation=s.get("dilation"),
                     feature_group_count=in_ch, name=nm)(x)
        return _apply_act(x, s.get("activation"))
    if k == "separable_conv2d":
        in_ch = x.shape[-1]
        x = fnn.Conv(in_ch * s["mult"], s["kernel"], s["strides"],
                     padding=s["padding"], use_bias=False,
                     kernel_dilation=s.get("dilation"),
                     feature_group_count=in_ch, name=f"{nm}_dw")(x)
        x = fnn.Conv(s["filters"], (1, 1), use_bias=s["use_bias"],
                     name=f"{nm}_pw")(x)
        return _apply_act(x, s.get("activation"))
    if k == "upsampling2d":
        sh, sw = s["size"]
        return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
    if k == "zeropad2d":
        (t, b), (l, r) = s["padding"]
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))
    if k == "globalmaxpool":
        return x.max(axis=(1, 2), keepdims=s.get("keepdims", False))
    if k == "maxpool1d":
        return fnn.max_pool(x, (s["pool"],), (s["strides"],), s["padding"])
    if k == "batchnorm":
        return fnn.BatchNorm(use_running_average=not train,
                             momentum=s["momentum"], epsilon=s["eps"],
                             name=nm)(x)
    if k == "layernorm":
        return fnn.LayerNorm(epsilon=s["eps"], name=nm)(x)
    if k == "dropout":
        return fnn.Dropout(rate=s["rate"], deterministic=not train,
                           name=nm)(x)
    if k == "flatten":
        return x.reshape(x.shape[0], -1)
    if k == "maxpool":
        return fnn.max_pool(x, s["pool"], s["strides"], s["padding"])
    if k == "avgpool":
        return fnn.avg_pool(x, s["pool"], s["strides"], s["padding"])
    if k == "globalavgpool":
        return x.mean(axis=(1, 2), keepdims=s.get("keepdims", False))
    if k == "embedding":
        return MXUEmbed(s["num"], s["dim"], name=nm)(x.astype(jnp.int32))
    if k == "act":
        return _apply_act(x, s["fn"])
    raise KerasConversionError(f"unhandled spec kind {k}")


def build_flax_from_keras(model):
    """Return (flax_module, param_loader(variables)->variables).

    Sequential models (and models whose get_config has no graph topology)
    use the linear chain below; Functional models go through the DAG
    interpreter (build_flax_from_keras_graph), which supports branching and
    merge layers (Add/Concatenate/...)."""
    import flax.linen as fnn

    cfg = {}
    try:
        cfg = model.get_config()
    except Exception as e:  # noqa: BLE001 — arbitrary user get_config
        logger.warning("model.get_config() failed (%s: %s); treating the "
                       "model as a Sequential layer chain",
                       type(e).__name__, e)
    if isinstance(cfg, dict) and "input_layers" in cfg:
        return build_flax_from_keras_graph(model, cfg)

    specs = _layer_specs(model)

    class KerasConverted(fnn.Module):
        @fnn.compact
        def __call__(self, x, train: bool = False):
            for i, s in enumerate(specs):
                x = _run_spec(s, [x], f"op_{i}", train)
            return x

    pairs = [(s, f"op_{i}") for i, s in enumerate(specs)]
    return KerasConverted(), _make_loader(_snapshot_weights(model), pairs)


def _make_loader(weights: Dict[str, list], pairs):
    """Shared weight loader: ``pairs`` is [(spec, flax_name), ...]."""

    def load_params(variables):
        import jax
        variables = jax.tree.map(np.asarray, jax.device_get(variables))
        params = dict(variables.get("params", {}))
        batch_stats = dict(variables.get("batch_stats", {}))
        for s, nm in pairs:
            _load_spec_weights(params, batch_stats, s, nm,
                               weights.get(s["name"], []))
        out = {"params": params}
        if batch_stats:
            out["batch_stats"] = batch_stats
        return out

    return load_params


def _snapshot_weights(model) -> Dict[str, list]:
    weights = {}
    for lyr in model.layers:
        try:
            weights[lyr.name] = [np.asarray(w) for w in lyr.get_weights()]
        except Exception:
            weights[lyr.name] = []
    return weights


def _load_spec_weights(params, batch_stats, s, nm, w):
    k = s["kind"]
    if not w:
        return
    if k in ("dense", "conv2d"):        # conv2d covers 1D convs too
        params[nm] = {"kernel": w[0]}
        if s["use_bias"] and len(w) > 1:
            params[nm]["bias"] = w[1]
    elif k == "depthwise_conv2d":
        # keras depthwise kernel (kh, kw, in, mult) -> flax grouped-conv
        # kernel (kh, kw, 1, in*mult); reshape is in-major, matching flax's
        # per-group output ordering
        dw = w[0]
        params[nm] = {"kernel": dw.reshape(*dw.shape[:2], 1, -1)}
        if s["use_bias"] and len(w) > 1:
            params[nm]["bias"] = w[1]
    elif k == "separable_conv2d":
        dw, pw = w[0], w[1]
        params[f"{nm}_dw"] = {"kernel": dw.reshape(*dw.shape[:2], 1, -1)}
        params[f"{nm}_pw"] = {"kernel": pw}
        if s["use_bias"] and len(w) > 2:
            params[f"{nm}_pw"]["bias"] = w[2]
    elif k == "batchnorm":
        params[nm] = {"scale": w[0], "bias": w[1]}
        batch_stats[nm] = {"mean": w[2], "var": w[3]}
    elif k == "layernorm":
        params[nm] = {"scale": w[0], "bias": w[1]}
    elif k == "embedding":
        params[nm] = {"embedding": w[0]}


def _parse_inbound(node_cfg) -> List[str]:
    """Parent layer names from a keras-3 inbound_nodes entry (nested
    __keras_tensor__ dicts with keras_history) or the legacy nested-list
    format [[name, node_idx, tensor_idx, {}], ...]."""
    parents: List[str] = []

    def walk(obj):
        if isinstance(obj, dict):
            if obj.get("class_name") == "__keras_tensor__":
                parents.append(obj["config"]["keras_history"][0])
            else:
                for v in obj.values():
                    walk(v)
        elif isinstance(obj, (list, tuple)):
            if (len(obj) >= 3 and isinstance(obj[0], str)
                    and isinstance(obj[1], int) and isinstance(obj[2], int)):
                parents.append(obj[0])  # legacy [name, n, t, {}]
            else:
                for v in obj:
                    walk(v)

    walk(node_cfg)
    return parents


def build_flax_from_keras_graph(model, cfg: Optional[dict] = None):
    """DAG interpreter for Functional keras models: every layer node is
    re-emitted as flax against its actual parents, so branching topologies
    and merge layers (Add/Concatenate/...) convert exactly. Multi-input /
    multi-output models map to ``__call__(*inputs) -> tuple``."""
    import flax.linen as fnn

    cfg = cfg or model.get_config()
    entries = []          # (layer_name, spec|None, parent names)
    for lcfg in cfg["layers"]:
        name = lcfg.get("name") or lcfg["config"]["name"]
        lyr = model.get_layer(name)
        spec = _spec_for(lyr)
        inbound = lcfg.get("inbound_nodes", [])
        if spec is not None and len(inbound) > 1:
            # one env slot per layer name: a layer called at multiple graph
            # sites (shared weights) would silently merge its parent lists
            raise KerasConversionError(
                f"layer '{name}' is called {len(inbound)} times (shared "
                "weights); the graph converter supports one call site per "
                "layer — duplicate the layer or port the model to flax")
        parents = _parse_inbound(inbound)
        entries.append((name, spec, parents))

    def norm_io(io):
        # ['name', 0, 0] or [['a',0,0], ['b',0,0]]
        if io and isinstance(io[0], str):
            return [io[0]]
        return [e[0] for e in io]

    input_names = norm_io(cfg["input_layers"])
    output_names = norm_io(cfg["output_layers"])

    class KerasGraphConverted(fnn.Module):
        @fnn.compact
        def __call__(self, *inputs, train: bool = False):
            if len(inputs) != len(input_names):
                raise ValueError(
                    f"model expects {len(input_names)} inputs "
                    f"({input_names}), got {len(inputs)}")
            env = dict(zip(input_names, inputs))
            for name, spec, parents in entries:
                if spec is None:        # InputLayer
                    continue
                xs = [env[p] for p in parents]
                env[name] = _run_spec(spec, xs, name.replace(".", "_"),
                                      train)
            outs = tuple(env[n] for n in output_names)
            return outs[0] if len(outs) == 1 else outs

    pairs = [(spec, name.replace(".", "_"))
             for name, spec, _parents in entries if spec is not None]
    return KerasGraphConverted(), _make_loader(_snapshot_weights(model),
                                               pairs)


def extract_compile_args(model) -> Tuple[Optional[str], Any, list]:
    """Pull loss/optimizer/metrics out of a compiled keras model."""
    loss = None
    optimizer = "adam"
    metrics: list = []
    k_loss = getattr(model, "loss", None)
    if isinstance(k_loss, str):
        loss = {"mse": "mse", "mean_squared_error": "mse",
                "mae": "mae", "mean_absolute_error": "mae",
                "binary_crossentropy": "binary_crossentropy",
                "categorical_crossentropy": "categorical_crossentropy",
                "sparse_categorical_crossentropy":
                    "sparse_categorical_crossentropy"}.get(k_loss, k_loss)
    elif k_loss is not None:
        loss = {"MeanSquaredError": "mse", "MeanAbsoluteError": "mae",
                "BinaryCrossentropy": "binary_crossentropy",
                "CategoricalCrossentropy": "categorical_crossentropy",
                "SparseCategoricalCrossentropy":
                    "sparse_categorical_crossentropy"}.get(
            type(k_loss).__name__)
    k_opt = getattr(model, "optimizer", None)
    if k_opt is not None:
        import optax
        name = type(k_opt).__name__.lower()
        try:
            lr = float(k_opt.learning_rate.numpy())
        except Exception:
            lr = 1e-3
        if "sgd" in name:
            try:
                mom = float(getattr(k_opt, "momentum", 0.0))
            except Exception:
                mom = 0.0
            optimizer = optax.sgd(lr, momentum=mom or None)
        elif "adamw" in name:
            optimizer = optax.adamw(lr)
        elif "adam" in name:
            optimizer = optax.adam(lr)
        elif "rmsprop" in name:
            optimizer = optax.rmsprop(lr)
        elif "adagrad" in name:
            optimizer = optax.adagrad(lr)
        else:
            optimizer = optax.adam(lr)
    raw_metrics = getattr(model, "_compile_metrics", None) or []
    names = []
    try:
        names = [m if isinstance(m, str) else getattr(m, "name", None)
                 for m in (raw_metrics if isinstance(raw_metrics, list)
                           else [])]
    except Exception as e:  # noqa: BLE001 — arbitrary user metric objects
        logger.warning("could not read compiled metric names (%s: %s); "
                       "continuing without converted metrics",
                       type(e).__name__, e)
    table = {"accuracy": "accuracy", "acc": "accuracy", "mae": "mae",
             "mse": "mse", "auc": "auc",
             "sparse_categorical_accuracy": "sparse_categorical_accuracy",
             "categorical_accuracy": "categorical_accuracy"}
    metrics = [table[n] for n in names if n in table]
    return loss, optimizer, metrics
