"""tf.keras -> flax conversion for the tf2 ``from_keras`` path.

The reference's TF2 estimator ships the user's ``model_creator`` (returning a
compiled tf.keras model) to Ray actors running MultiWorkerMirroredStrategy
(pyzoo/zoo/orca/learn/tf2/tf_runner.py:226-360). Here the keras model is
translated once, on the driver, into flax + optax + our losses/metrics (layer
configs and weights are introspectable; keras is already NHWC so no layout
gymnastics), and the jitted engine trains it on TPU.

Coverage: Sequential / linear Functional graphs over Dense, Conv2D,
BatchNormalization, LayerNormalization, Dropout, Flatten, MaxPooling2D,
AveragePooling2D, GlobalAveragePooling2D, Embedding, Activation, ReLU,
Softmax, InputLayer. Branching functional graphs and custom layers raise with
porting guidance (write the model as a flax module instead).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class KerasConversionError(ValueError):
    pass


def _layer_specs(model) -> List[Dict[str, Any]]:
    import tensorflow as tf
    K = tf.keras.layers

    layers = getattr(model, "layers", None)
    if layers is None:
        raise KerasConversionError("expected a keras Model")
    # verify linear topology for functional models
    specs: List[Dict[str, Any]] = []
    for lyr in layers:
        cfg = lyr.get_config()
        if isinstance(lyr, K.InputLayer):
            continue
        if isinstance(lyr, K.Dense):
            specs.append({"kind": "dense", "units": cfg["units"],
                          "activation": cfg.get("activation"),
                          "use_bias": cfg.get("use_bias", True),
                          "name": lyr.name})
        elif isinstance(lyr, K.Conv2D):
            specs.append({"kind": "conv2d", "filters": cfg["filters"],
                          "kernel": tuple(cfg["kernel_size"]),
                          "strides": tuple(cfg["strides"]),
                          "padding": cfg["padding"].upper(),
                          "activation": cfg.get("activation"),
                          "use_bias": cfg.get("use_bias", True),
                          "name": lyr.name})
        elif isinstance(lyr, K.BatchNormalization):
            specs.append({"kind": "batchnorm", "eps": cfg["epsilon"],
                          "momentum": cfg["momentum"], "name": lyr.name})
        elif isinstance(lyr, K.LayerNormalization):
            specs.append({"kind": "layernorm", "eps": cfg["epsilon"],
                          "name": lyr.name})
        elif isinstance(lyr, K.Dropout):
            specs.append({"kind": "dropout", "rate": cfg["rate"],
                          "name": lyr.name})
        elif isinstance(lyr, K.Flatten):
            specs.append({"kind": "flatten", "name": lyr.name})
        elif isinstance(lyr, K.MaxPooling2D):
            specs.append({"kind": "maxpool", "pool": tuple(cfg["pool_size"]),
                          "strides": tuple(cfg["strides"] or cfg["pool_size"]),
                          "padding": cfg["padding"].upper(), "name": lyr.name})
        elif isinstance(lyr, K.AveragePooling2D):
            specs.append({"kind": "avgpool", "pool": tuple(cfg["pool_size"]),
                          "strides": tuple(cfg["strides"] or cfg["pool_size"]),
                          "padding": cfg["padding"].upper(), "name": lyr.name})
        elif isinstance(lyr, K.GlobalAveragePooling2D):
            specs.append({"kind": "globalavgpool", "name": lyr.name})
        elif isinstance(lyr, K.Embedding):
            specs.append({"kind": "embedding", "num": cfg["input_dim"],
                          "dim": cfg["output_dim"], "name": lyr.name})
        elif isinstance(lyr, K.Activation):
            specs.append({"kind": "act", "fn": cfg["activation"],
                          "name": lyr.name})
        elif isinstance(lyr, K.ReLU):
            specs.append({"kind": "act", "fn": "relu", "name": lyr.name})
        elif isinstance(lyr, K.Softmax):
            specs.append({"kind": "act", "fn": "softmax", "name": lyr.name})
        else:
            raise KerasConversionError(
                f"unsupported keras layer {type(lyr).__name__} ('{lyr.name}')."
                " Supported: Dense/Conv2D/BN/LN/Dropout/Flatten/pooling/"
                "Embedding/Activation. For custom layers or branching graphs,"
                " write the model as a flax module (see analytics_zoo_tpu."
                "models) and use Estimator.from_keras(model=flax_module).")
    return specs


_ACTS = {"relu", "sigmoid", "tanh", "softmax", "gelu", "elu", "selu",
         "softplus", "silu", "swish", "log_softmax"}


def _apply_act(x, fn: Optional[str]):
    import jax
    if not fn or fn == "linear":
        return x
    if fn == "swish":
        fn = "silu"
    if fn == "softmax" or fn == "log_softmax":
        return getattr(jax.nn, fn)(x, axis=-1)
    if fn not in _ACTS:
        raise KerasConversionError(f"unsupported activation '{fn}'")
    return getattr(jax.nn, fn)(x)


def build_flax_from_keras(model):
    """Return (flax_module, param_loader(variables)->variables)."""
    import flax.linen as fnn
    import jax.numpy as jnp

    specs = _layer_specs(model)

    class KerasConverted(fnn.Module):
        @fnn.compact
        def __call__(self, x, train: bool = False):
            for i, s in enumerate(specs):
                k, nm = s["kind"], f"op_{i}"
                if k == "dense":
                    x = fnn.Dense(s["units"], use_bias=s["use_bias"],
                                  name=nm)(x)
                    x = _apply_act(x, s.get("activation"))
                elif k == "conv2d":
                    x = fnn.Conv(s["filters"], s["kernel"], s["strides"],
                                 padding=s["padding"],
                                 use_bias=s["use_bias"], name=nm)(x)
                    x = _apply_act(x, s.get("activation"))
                elif k == "batchnorm":
                    x = fnn.BatchNorm(use_running_average=not train,
                                      momentum=s["momentum"],
                                      epsilon=s["eps"], name=nm)(x)
                elif k == "layernorm":
                    x = fnn.LayerNorm(epsilon=s["eps"], name=nm)(x)
                elif k == "dropout":
                    x = fnn.Dropout(rate=s["rate"], deterministic=not train,
                                    name=nm)(x)
                elif k == "flatten":
                    x = x.reshape(x.shape[0], -1)
                elif k == "maxpool":
                    x = fnn.max_pool(x, s["pool"], s["strides"], s["padding"])
                elif k == "avgpool":
                    x = fnn.avg_pool(x, s["pool"], s["strides"], s["padding"])
                elif k == "globalavgpool":
                    x = x.mean(axis=(1, 2))
                elif k == "embedding":
                    x = fnn.Embed(s["num"], s["dim"], name=nm)(
                        x.astype(jnp.int32))
                elif k == "act":
                    x = _apply_act(x, s["fn"])
            return x

    weights = {}
    for lyr in model.layers:
        try:
            weights[lyr.name] = [np.asarray(w) for w in lyr.get_weights()]
        except Exception:
            weights[lyr.name] = []

    def load_params(variables):
        import jax
        variables = jax.tree.map(np.asarray, jax.device_get(variables))
        params = dict(variables.get("params", {}))
        batch_stats = dict(variables.get("batch_stats", {}))
        for i, s in enumerate(specs):
            nm, k = f"op_{i}", s["kind"]
            w = weights.get(s["name"], [])
            if not w:
                continue
            if k == "dense":
                params[nm] = {"kernel": w[0]}
                if s["use_bias"] and len(w) > 1:
                    params[nm]["bias"] = w[1]
            elif k == "conv2d":
                params[nm] = {"kernel": w[0]}
                if s["use_bias"] and len(w) > 1:
                    params[nm]["bias"] = w[1]
            elif k == "batchnorm":
                params[nm] = {"scale": w[0], "bias": w[1]}
                batch_stats[nm] = {"mean": w[2], "var": w[3]}
            elif k == "layernorm":
                params[nm] = {"scale": w[0], "bias": w[1]}
            elif k == "embedding":
                params[nm] = {"embedding": w[0]}
        out = {"params": params}
        if batch_stats:
            out["batch_stats"] = batch_stats
        return out

    return KerasConverted(), load_params


def extract_compile_args(model) -> Tuple[Optional[str], Any, list]:
    """Pull loss/optimizer/metrics out of a compiled keras model."""
    loss = None
    optimizer = "adam"
    metrics: list = []
    k_loss = getattr(model, "loss", None)
    if isinstance(k_loss, str):
        loss = {"mse": "mse", "mean_squared_error": "mse",
                "mae": "mae", "mean_absolute_error": "mae",
                "binary_crossentropy": "binary_crossentropy",
                "categorical_crossentropy": "categorical_crossentropy",
                "sparse_categorical_crossentropy":
                    "sparse_categorical_crossentropy"}.get(k_loss, k_loss)
    elif k_loss is not None:
        loss = {"MeanSquaredError": "mse", "MeanAbsoluteError": "mae",
                "BinaryCrossentropy": "binary_crossentropy",
                "CategoricalCrossentropy": "categorical_crossentropy",
                "SparseCategoricalCrossentropy":
                    "sparse_categorical_crossentropy"}.get(
            type(k_loss).__name__)
    k_opt = getattr(model, "optimizer", None)
    if k_opt is not None:
        import optax
        name = type(k_opt).__name__.lower()
        try:
            lr = float(k_opt.learning_rate.numpy())
        except Exception:
            lr = 1e-3
        if "sgd" in name:
            try:
                mom = float(getattr(k_opt, "momentum", 0.0))
            except Exception:
                mom = 0.0
            optimizer = optax.sgd(lr, momentum=mom or None)
        elif "adamw" in name:
            optimizer = optax.adamw(lr)
        elif "adam" in name:
            optimizer = optax.adam(lr)
        elif "rmsprop" in name:
            optimizer = optax.rmsprop(lr)
        elif "adagrad" in name:
            optimizer = optax.adagrad(lr)
        else:
            optimizer = optax.adam(lr)
    raw_metrics = getattr(model, "_compile_metrics", None) or []
    names = []
    try:
        names = [m if isinstance(m, str) else getattr(m, "name", None)
                 for m in (raw_metrics if isinstance(raw_metrics, list)
                           else [])]
    except Exception:
        pass
    table = {"accuracy": "accuracy", "acc": "accuracy", "mae": "mae",
             "mse": "mse", "auc": "auc",
             "sparse_categorical_accuracy": "sparse_categorical_accuracy",
             "categorical_accuracy": "categorical_accuracy"}
    metrics = [table[n] for n in names if n in table]
    return loss, optimizer, metrics
