"""Triggers controlling when checkpoints/validation fire.

Mirrors the reference's trigger set (pyzoo/zoo/orca/learn/trigger.py:19-77 and
pyzoo/zoo/util/triggers.py:20-186: EveryEpoch, SeveralIteration, MaxEpoch,
MaxIteration, MaxScore, MinLoss, TriggerAnd, TriggerOr) as plain host-side
predicates over a TrainingState snapshot — no JVM ZooTrigger objects."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class TrainerState:
    epoch: int = 0           # completed epochs
    iteration: int = 0       # completed global steps
    epoch_finished: bool = False
    loss: Optional[float] = None
    score: Optional[float] = None
    records_processed: int = 0


class Trigger:
    def __call__(self, state: TrainerState) -> bool:
        raise NotImplementedError

    def arm(self, state: TrainerState) -> None:
        """Sync any internal marks to the run's starting state (the
        trainer calls this at fit() start). Default: stateless, no-op;
        composites forward to their children."""

    def fuse_cap(self):
        """Max steps the trainer may fuse per dispatch without coarsening
        this trigger's cadence (None = no constraint). Composites return
        the tightest child cap."""
        return None

    @staticmethod
    def convert_trigger(t) -> "Trigger":
        if isinstance(t, Trigger):
            return t
        if isinstance(t, str):
            if t == "every_epoch":
                return EveryEpoch()
            raise ValueError(f"unknown trigger '{t}'")
        raise ValueError(f"cannot convert {t!r} to a Trigger")


class EveryEpoch(Trigger):
    """Fires at each epoch boundary (reference: trigger.py:40)."""

    def __call__(self, state):
        return state.epoch_finished


class SeveralIteration(Trigger):
    """Fires every N iterations (reference: trigger.py:59).

    Implemented as an interval-bucket edge detector rather than a bare
    ``iteration % N == 0`` so it still fires when the trainer checks the
    trigger every k steps (the scan-fused dispatch loop advances iteration
    in groups): any check that crosses one or more N-boundaries fires once.
    """

    def __init__(self, interval: int):
        self.interval = int(interval)
        self._last_bucket = 0

    def arm(self, state):
        """Sync to the run's starting iteration (the trainer calls this at
        fit() start): a fresh trigger on a resumed run must not fire
        mid-interval, and a reused trigger on a fresh run must not stay
        dark until its old mark."""
        self._last_bucket = state.iteration // self.interval

    def fuse_cap(self):
        return self.interval

    def __call__(self, state):
        bucket = state.iteration // self.interval
        if bucket < self._last_bucket:
            # iteration went backwards without re-arming (restore rewound
            # the counter) — resync so the trigger keeps firing
            self._last_bucket = bucket
        if state.iteration > 0 and bucket > self._last_bucket:
            self._last_bucket = bucket
            return True
        return False


class MaxEpoch(Trigger):
    """End-trigger: true once `max` epochs completed (reference:
    util/triggers.py MaxEpoch)."""

    def __init__(self, max: int):
        self.max = int(max)

    def __call__(self, state):
        return state.epoch >= self.max


class MaxIteration(Trigger):
    def __init__(self, max: int):
        self.max = int(max)

    def __call__(self, state):
        return state.iteration >= self.max


class MaxScore(Trigger):
    def __init__(self, max: float):
        self.max = float(max)

    def __call__(self, state):
        return state.score is not None and state.score > self.max


class MinLoss(Trigger):
    def __init__(self, min: float):
        self.min = float(min)

    def __call__(self, state):
        return state.loss is not None and state.loss < self.min


class _Composite(Trigger):
    """Shared arm/fuse_cap forwarding for TriggerAnd/TriggerOr.

    Note on stateful children: SeveralIteration's bucket edge-detector
    consumes its interval edge when ITS __call__ fires, even if the
    composite as a whole evaluates false (e.g. TriggerAnd with a MinLoss
    that is not yet met) — the composite then won't fire again until the
    next interval boundary. This matches the reference's exact-step
    semantics (both conditions must hold at the boundary check)."""

    def __init__(self, first: Trigger, *others: Trigger):
        self.triggers = (first,) + others

    def arm(self, state):
        for t in self.triggers:
            t.arm(state)

    def fuse_cap(self):
        caps = [c for c in (t.fuse_cap() for t in self.triggers)
                if c is not None]
        return min(caps) if caps else None


class TriggerAnd(_Composite):
    def __call__(self, state):
        return all(t(state) for t in self.triggers)


class TriggerOr(_Composite):
    def __call__(self, state):
        return any(t(state) for t in self.triggers)
