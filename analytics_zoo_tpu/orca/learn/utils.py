"""Input-pipeline utilities: convert user data (dict-of-ndarray, XShards,
pandas shards, creator functions) into padded, mesh-sharded device batches.

Replaces the reference's per-backend data plumbing: arrays2dict/
dataframe_to_xshards (pyzoo/zoo/orca/learn/utils.py:191-311), TFDataset
per-core batching (pyzoo/zoo/tfpark/tf_dataset.py:117-160), and the Ray
LocalStore shuttle (pyzoo/zoo/orca/data/ray_xshards.py:67-94). TPU rule: the
global batch is sharded on the mesh's data axes; ragged tails are padded and
masked with a per-example weight so no record is dropped and no shape is
dynamic (SURVEY.md §7 hard-part #2).
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...native import transfer as xfer
from ...native.infeed import _MAX_DEPTH, PipelineStats, _default_workers
from ...utils import nest
from ..data.chunked import ChunkedArray, as_chunked
from ..data.shard import HostXShards

logger = logging.getLogger("analytics_zoo_tpu")


@dataclass
class Batch:
    """One global batch: tuples of feature/label arrays plus a mask weight."""
    x: Tuple[np.ndarray, ...]
    y: Optional[Tuple[np.ndarray, ...]]
    # (batch,) 1.0 for real rows, 0.0 for padding; None == all ones (the
    # jitted step synthesizes them on device — no transfer for full batches)
    w: Optional[np.ndarray]
    # >1: arrays are stacked (fused, batch, ...) superbatches for the
    # engine's scan-fused multi-step path (train_batch_group)
    fused: int = 1


def _as_tuple(v) -> Tuple:
    if v is None:
        return ()
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,)


def xshards_from_arrays(data: Any, feature_cols=None, label_cols=None,
                        num_shards: Optional[int] = None) -> HostXShards:
    """Normalize any supported input into XShards of {'x': tuple, 'y': tuple}."""
    if isinstance(data, HostXShards):
        return normalize_xshards(data, feature_cols, label_cols)
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            data = HostXShards([data])
            return normalize_xshards(data, feature_cols, label_cols)
    except ImportError:
        pass
    if isinstance(data, dict):
        x, y = data.get("x"), data.get("y")
    elif isinstance(data, tuple) and len(data) == 2:
        x, y = data
    else:
        x, y = data, None
    shard = {"x": _as_tuple(x)}
    if y is not None:
        shard["y"] = _as_tuple(y)
    n = num_shards or 1
    flat_len = len(nest.flatten(shard)[0])
    n = min(n, max(flat_len, 1))
    if n == 1:
        # single shard: keep the caller's arrays as-is — no index-copy
        return HostXShards([{k: tuple(np.asarray(a) for a in v)
                             for k, v in shard.items()}])
    return HostXShards([_slice_dict(shard, idx)
                        for idx in np.array_split(np.arange(flat_len), n)])


def _slice_dict(shard: Dict, idx: np.ndarray) -> Dict:
    out = {}
    for k, v in shard.items():
        out[k] = tuple(np.asarray(a)[idx] for a in v)
    return out


def normalize_xshards(shards: HostXShards, feature_cols=None,
                      label_cols=None) -> HostXShards:
    """Map pandas-DataFrame or raw-dict shards to {'x': tuple, 'y': tuple}
    (the reference's process_xshards_of_pandas_dataframe,
    orca/learn/utils.py:253-264)."""
    first = shards.collect()[0] if shards.num_partitions() else None

    def from_df(df):
        x = tuple(df[c].to_numpy() for c in feature_cols)
        out = {"x": x}
        if label_cols:
            out["y"] = tuple(df[c].to_numpy() for c in label_cols)
        return out

    def from_dict(d):
        if "x" in d:
            out = {"x": _as_tuple(d["x"])}
            if "y" in d and d["y"] is not None:
                out["y"] = _as_tuple(d["y"])
            return out
        # column-keyed dict shards (e.g. ParquetDataset.read_as_xshards):
        # feature_cols/label_cols select the tensors, like the reference's
        # dataframe-to-shard path
        if not feature_cols:
            raise ValueError(
                "shards are column dicts; pass feature_cols (and label_cols)"
                f" — available keys: {sorted(d.keys())}")
        out = {"x": tuple(np.asarray(d[c]) for c in feature_cols)}
        if label_cols:
            out["y"] = tuple(np.asarray(d[c]) for c in label_cols)
        return out

    try:
        import pandas as pd
        if isinstance(first, pd.DataFrame):
            if not feature_cols:
                raise ValueError(
                    "feature_cols is required for pandas-DataFrame XShards")
            return shards.transform_shard(from_df)
    except ImportError:
        pass
    if isinstance(first, dict):
        return shards.transform_shard(from_dict)
    raise ValueError(f"unsupported shard element type {type(first)}")


def concat_shards(shards: HostXShards) -> Dict[str, Tuple[np.ndarray, ...]]:
    """Merge shards into contiguous arrays — a full O(dataset) copy. Kept
    for callers that genuinely need one flat array (e.g. FeatureSet DRAM
    tiers); the training path uses :func:`chunk_shards` instead."""
    parts = shards.collect()
    if not parts:
        raise ValueError("empty XShards")
    keys = parts[0].keys()
    out = {}
    for k in keys:
        n = len(parts[0][k])
        out[k] = tuple(
            np.concatenate([np.asarray(p[k][i]) for p in parts])
            for i in range(n))
    return out


def chunk_shards(shards: HostXShards
                 ) -> Dict[str, Tuple[ChunkedArray, ...]]:
    """Zero-copy counterpart of :func:`concat_shards`: each leaf becomes a
    :class:`ChunkedArray` over the per-partition arrays. Row order is the
    partition concatenation order, so batch streams built on top are
    bit-identical to the merged path for the same seed."""
    parts = shards.collect()
    if not parts:
        raise ValueError("empty XShards")
    keys = parts[0].keys()
    out = {}
    for k in keys:
        n = len(parts[0][k])
        out[k] = tuple(
            ChunkedArray([p[k][i] for p in parts]) for i in range(n))
    return out


# peak dense bf16 FLOP/s per jax device (public TPU specs; v2/v3 devices
# are cores, v4+ devices are chips). Longest key wins so "v5p" beats "v5".
_PEAK_BF16 = {"v6": 918e12, "v5p": 459e12, "v5": 197e12, "v4": 275e12,
              "v3": 61.5e12, "v2": 23e12}
_PEAK_ORDER = sorted(_PEAK_BF16.items(), key=lambda kv: -len(kv[0]))

# typical training MFU assumed when converting cost-analysis FLOPs to a
# compute-time estimate (shared by the fuse gate and bench.py)
ASSUMED_TRAIN_MFU = 0.3

# ceiling for one stacked (fuse, batch, ...) superbatch — bounds HBM staging
# and host gather granularity for the scan-fused dispatch path
MAX_GROUP_BYTES = 256 << 20


def peak_bf16_flops(device) -> float:
    """Peak dense bf16 FLOP/s of a jax device, 0.0 if unknown (CPU)."""
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_ORDER:
        if key in kind:
            return val
    return 0.0


def estimate_step_compute_s(jitted, args, devices) -> Optional[float]:
    """Analytic per-step compute-time estimate: XLA's own cost-analysis
    FLOPs for the compiled step, divided by ASSUMED_TRAIN_MFU of the devices peak bf16
    rate (a typical training MFU). Used to decide whether a step is
    compute-dominated INDEPENDENT of wall-clock measurements, which on a
    shared/tunneled chip conflate dispatch overhead and contention with
    compute. Returns None when FLOPs or peak are unknown (e.g. CPU)."""
    try:
        cost = jitted.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0) or 0.0)
        # cost_analysis reports the PER-DEVICE program (post-SPMD
        # partitioning), so the denominator is ONE device's peak, not the
        # summed mesh peak — summing under-estimated compute time by the
        # device count, mis-classifying compute-dominated models as
        # dispatch-bound, scan-fusing them and coarsening their
        # checkpoint/preemption cadence
        peak = max((peak_bf16_flops(d) for d in devices), default=0.0)
        if flops > 0 and peak > 0:
            return flops / (ASSUMED_TRAIN_MFU * peak)
    except Exception as e:  # noqa: BLE001 — estimate is advisory
        # without the estimate the fuse gate degrades to measured step
        # time only — coarser checkpoint/preemption cadence, so say so
        logger.warning("step-compute estimate unavailable (%s: %s); "
                       "fuse gate falls back to measured step time",
                       type(e).__name__, e)
    return None


def auto_fuse_factor(step_time_s: float, steps_per_epoch: int,
                     batch_bytes: int = 0,
                     compute_s: Optional[float] = None,
                     target_s: float = 0.25, max_fuse: int = 128,
                     max_group_bytes: int = MAX_GROUP_BYTES) -> int:
    """How many train steps to fuse into one dispatch (lax.scan group).

    ``step_time_s`` is the pipelined per-step wall time of the dispatched
    train step — measure it as min-of-several runs of (m non-blocking calls
    + one fetch)/m, so contention spikes and the tail round trip wash out.
    ``compute_s`` is the analytic estimate from ``estimate_step_compute_s``;
    when available it decides the compute-dominated gate (≥10 ms → stay
    unfused: per-step triggers and infeed granularity are worth more than
    the <2% dispatch saving), so a contended or high-latency chip can't
    masquerade as a big model. k is then sized so one fused group runs
    ~``target_s``: if the measured time was mostly per-call dispatch
    overhead, that overhead shrinks k-fold; if it was mostly compute, the
    group just batches ~target_s of work. Either way the host leaves the
    hot path. ``batch_bytes`` caps k so a stacked superbatch stays under
    ``max_group_bytes``.
    """
    if steps_per_epoch < 2:
        return 1
    gate = compute_s if compute_s is not None else step_time_s
    if gate >= 0.01:
        return 1
    if compute_s is not None and compute_s < step_time_s:
        # the measured step is (overhead + compute) and the analytic part
        # says compute is the small piece. Sizing k off step_time alone is
        # too timid exactly when overhead is worst (contended/tunneled
        # chip); sizing off compute_s alone overshoots when the model runs
        # below the assumed MFU. The geometric mean hedges both: group wall
        # time lands within sqrt(step_time/compute) of target either way.
        denom = math.sqrt(max(compute_s, 1e-6) * step_time_s)
    else:
        denom = max(step_time_s, 1e-5)
    k = int(target_s / denom)
    if k <= 1:
        return 1
    k = 1 << (k - 1).bit_length()           # round UP to a power of two
    if batch_bytes > 0:
        k = min(k, max(max_group_bytes // batch_bytes, 1))
    return max(1, min(k, max_fuse, steps_per_epoch))


class BatchIterator:
    """Epoch iterator over host-local data producing padded global batches.

    The per-host arrays are treated as this process's stripe of the global
    dataset; ``batch_size`` is the *global* batch (the reference's TFDataset
    batch semantics, tf_dataset.py:135-149), so each host contributes
    batch_size / process_count rows per step.

    Wire format: source dtypes are preserved end-to-end — uint8 pixels and
    int32 labels ship as-is (cast/normalize belongs on device, see
    ``orca/learn/prologue.py``) and wide leaves (f64/i64) are narrowed
    per batch to their canonical device form (``narrow_wire`` — the cast
    ``device_put`` would perform anyway, paid on the batch instead of as a
    resident duplicate of the dataset). On the prefetch path, batch
    gathers go into a reusable :class:`StagingPool` ring instead of fresh
    allocations (non-CPU backends; see ``native/transfer.py``).
    """

    supports_fused = True       # capability flag: epoch(fuse=k) is available

    def __init__(self, data: Dict[str, Tuple[np.ndarray, ...]],
                 batch_size: int, mesh: Mesh, shuffle: bool = False,
                 seed: int = 0, pad_tail: bool = True,
                 stats: Optional[PipelineStats] = None,
                 prefetch_depth: int = 2,
                 prefetch_workers: Optional[int] = None):
        # leaves are ChunkedArrays: per-shard chunks stay separate and
        # batches gather across chunk boundaries (zero-copy views within a
        # chunk) — the dataset is never merged into one contiguous copy
        self.x = tuple(as_chunked(a) for a in data["x"])
        self.y = (tuple(as_chunked(a) for a in data["y"])
                  if data.get("y") is not None else None)
        self.n = len(self.x[0])
        self._staging = None        # lazily-built StagingPool (or False)
        self.stats = stats if stats is not None else PipelineStats()
        self.prefetch_depth = prefetch_depth
        self.prefetch_workers = prefetch_workers
        self.mesh = mesh
        nproc = jax.process_count()
        if batch_size % (nproc or 1):
            raise ValueError(
                f"global batch_size {batch_size} must divide across "
                f"{nproc} processes")
        self.local_bs = max(batch_size // max(nproc, 1), 1)
        # The sharded leading dim must divide by the local share of the data
        # axes (the reference instead hard-errors on batch % node*core != 0,
        # tf_dataset.py:135-149; padding+masking is strictly more permissive).
        data_axis = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        local_div = max(data_axis // max(nproc, 1), 1)
        if self.local_bs % local_div:
            self.local_bs = math.ceil(self.local_bs / local_div) * local_div
        self.global_bs = self.local_bs * max(nproc, 1)
        if self.global_bs != batch_size:
            logger.warning(
                "batch_size %d is not divisible by the %d-way data axes; "
                "training with effective global batch %d",
                batch_size, data_axis, self.global_bs)
        self.shuffle = shuffle
        self.seed = seed
        self.pad_tail = pad_tail
        self.steps_per_epoch = (
            math.ceil(self.n / self.local_bs) if pad_tail
            else self.n // self.local_bs)
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"dataset has {self.n} rows < local batch {self.local_bs}")
        self._epoch = 0
        self._sharding_cache: Dict[int, NamedSharding] = {}

    def _sharding(self, ndim: int, fused: bool = False) -> NamedSharding:
        key = (ndim, fused)
        if key not in self._sharding_cache:
            # fused superbatches carry a leading scan axis that must stay
            # unsharded; the batch axis (0 or 1) gets the data axes
            lead = (None,) if fused else ()
            spec = lead + (("dp", "fsdp"),) + (None,) * (ndim - len(lead) - 1)
            self._sharding_cache[key] = NamedSharding(self.mesh, P(*spec))
        return self._sharding_cache[key]

    def _device_put(self, arr: np.ndarray, fused: bool = False):
        """Place ONE array on the mesh (kept for callers staging single
        leaves; batches go through :meth:`_put_batch`)."""
        return xfer.sharded_put(arr, self._sharding(arr.ndim, fused))

    def _staging_pool(self):
        """Reusable host gather buffers for the prefetch path. Ring sized
        above the pump's WORST-CASE in-flight window — assembly workers,
        the adaptive lane ceiling, the adaptive delivery-depth ceiling
        (device_put may hold the host buffer until its async DMA
        completes), the consumer's batch, and margin — so a buffer is
        never rewritten while its batch may still be read. None when
        staging is off (CPU backend — its device_put may alias numpy
        buffers zero-copy; ``ZOO_HOST_STAGING`` overrides)."""
        if self._staging is None:
            if not xfer.staging_enabled():
                self._staging = False
            else:
                workers = self.prefetch_workers or _default_workers()
                self._staging = xfer.StagingPool(
                    ring=workers + xfer.MAX_H2D_LANES
                    + max(_MAX_DEPTH, self.prefetch_depth) + 4)
        return self._staging or None

    def _gather_leaf(self, a: ChunkedArray, idx: np.ndarray,
                     staged: bool) -> np.ndarray:
        # wide leaves bypass the ring: their narrow_wire astype allocates
        # anyway, so staging a wide intermediate would just double the
        # gathered bytes
        if staged and xfer.narrows_to(a.dtype) is None:
            pool = self._staging_pool()
            if pool is not None:
                out = pool.acquire((len(idx),) + a.shape[1:], a.dtype,
                                   tag=id(a))
                return a.gather(idx, out=out)
        return xfer.narrow_wire(a.gather(idx))

    def _assemble_group(self, idx: np.ndarray, fuse: int,
                        staged: bool = False) -> Batch:
        """One stacked (fuse, local_bs, ...) superbatch."""
        xs = tuple(
            self._gather_leaf(a, idx, staged).reshape(
                (fuse, self.local_bs) + a.shape[1:])
            for a in self.x)
        ys = (tuple(
            self._gather_leaf(a, idx, staged).reshape(
                (fuse, self.local_bs) + a.shape[1:])
            for a in self.y) if self.y is not None else None)
        return Batch(x=xs, y=ys, w=None, fused=fuse)

    def _assemble_batch(self, idx: np.ndarray, w: Optional[np.ndarray],
                        staged: bool = False) -> Batch:
        """One plain batch; chunk-aware gather (a contiguous in-chunk index
        run comes back as a zero-copy view)."""
        xs = tuple(self._gather_leaf(a, idx, staged) for a in self.x)
        ys = (tuple(self._gather_leaf(a, idx, staged) for a in self.y)
              if self.y is not None else None)
        return Batch(x=xs, y=ys, w=w)

    def _host_batch_tasks(self, shuffle: bool, fuse: int = 1,
                          staged: bool = False
                          ) -> Iterator[Callable[[], Batch]]:
        """Plan an epoch: yield zero-arg assembly tasks in batch order.

        The planner itself only slices the (native, off-GIL generated)
        shuffle order — cheap — while the gather work lives in the tasks,
        which the InfeedPump fans out over its assembly workers and
        re-orders. Running the tasks inline (``_host_batches``) is
        bit-identical: the epoch order is fixed here, not by scheduling.

        ``fuse`` > 1 groups that many consecutive FULL batches into ONE
        stacked superbatch (leaves ``(fuse, local_bs, ...)``) for the
        engine's scan-fused multi-step dispatch. The ragged tail falls back
        to ordinary single batches (last one padded + masked) — padding a
        whole superbatch would synthesize fully-empty steps whose zero-grad
        optimizer updates are NOT no-ops under momentum/Adam.
        """
        from functools import partial

        from analytics_zoo_tpu.native import shuffled_indices
        if shuffle:
            order = shuffled_indices(self.n, seed=self.seed + self._epoch)
        else:
            order = np.arange(self.n, dtype=np.int64)
        self._epoch += 1
        group = self.local_bs * max(fuse, 1)
        n_groups = self.n // group if fuse > 1 else 0
        for s in range(n_groups):
            yield partial(self._assemble_group,
                          order[s * group:(s + 1) * group], fuse,
                          staged)
        done = n_groups * group
        tail_steps = (math.ceil((self.n - done) / self.local_bs)
                      if self.pad_tail
                      else (self.n - done) // self.local_bs) \
            if fuse > 1 else self.steps_per_epoch
        for s in range(tail_steps):
            idx = order[done + s * self.local_bs:
                        done + (s + 1) * self.local_bs]
            real = len(idx)
            if real < self.local_bs:
                idx = np.concatenate(
                    [idx, np.zeros(self.local_bs - real, dtype=idx.dtype)])
                w = np.zeros(self.local_bs, dtype=np.float32)
                w[:real] = 1.0
            else:
                # full batch: weights are all ones — send None and let the
                # jitted step synthesize them, saving a per-step
                # host->device transfer (the infeed is the scarce resource)
                w = None
            yield partial(self._assemble_batch, idx, w, staged)

    def _host_batches(self, shuffle: bool, fuse: int = 1) -> Iterator[Batch]:
        """Assembled host batches, inline (single-threaded) — the
        non-prefetch path and the bench's direct-feed loops."""
        for task in self._host_batch_tasks(shuffle, fuse):
            yield task()

    def _put_batch(self, b: Batch) -> Batch:
        """Stage a whole batch pytree into HBM with per-leaf, batch-sharded
        placement (``native.transfer.put_tree``): each chip receives ONLY
        its slice of the batch, cut host-side — no full-batch replication
        ahead of slicing. Multihost rides the same helper
        (``make_array_from_process_local_data`` per leaf)."""
        fused = b.fused > 1
        leaves = list(b.x) + list(b.y or ()) + (
            [b.w] if b.w is not None else [])
        shardings = [self._sharding(a.ndim, fused) for a in leaves]
        put = xfer.put_tree(leaves, shardings)
        nx, ny = len(b.x), len(b.y or ())
        return Batch(
            x=tuple(put[:nx]),
            y=tuple(put[nx:nx + ny]) if b.y is not None else None,
            w=put[nx + ny] if b.w is not None else None,
            fused=b.fused)

    def epoch(self, shuffle: Optional[bool] = None,
              prefetch: bool = True, fuse: int = 1) -> Iterator[Batch]:
        """Yield device-resident batches. With prefetch, assembly tasks fan
        out over the pump's worker threads and an in-order H2D stage keeps
        the next batches staged in HBM while the current step runs
        (SURVEY.md §7 hard part #1 — infeed throughput). ``fuse`` > 1 yields
        stacked superbatches for ``TrainEngine.train_batch_group``."""
        shuffle = self.shuffle if shuffle is None else shuffle
        if not prefetch:
            for task in self._host_batch_tasks(shuffle, fuse):
                t0 = time.perf_counter()
                b = task()
                t1 = time.perf_counter()
                out = self._put_batch(b)
                t2 = time.perf_counter()
                self.stats.add("assemble", t1 - t0)
                self.stats.add("h2d", t2 - t1)
                yield out
            return
        from analytics_zoo_tpu.native.infeed import InfeedPump
        yield from InfeedPump(
            lambda: self._host_batch_tasks(shuffle, fuse, staged=True),
            device_put=self._put_batch,
            depth=self.prefetch_depth,
            workers=self.prefetch_workers,
            stats=self.stats)


def data_to_iterator(data: Any, batch_size: int, mesh: Mesh,
                     feature_cols=None, label_cols=None, shuffle=False,
                     seed: int = 0, pad_tail: bool = True,
                     config: Optional[dict] = None,
                     stats: Optional[PipelineStats] = None) -> BatchIterator:
    """Front door: any supported data form -> BatchIterator. The batches
    come straight out of the shard chunks (``chunk_shards``) — no merged
    dataset copy is ever built."""
    if hasattr(data, "epoch") and hasattr(data, "steps_per_epoch"):
        if stats is not None and hasattr(data, "stats"):
            data.stats = stats
        return data                 # already a batch iterator (duck-typed),
        # e.g. orca.data.image.imagenet.ImageNetPipeline streaming from disk
    if callable(data):  # data_creator(config, batch_size) like tf2/pytorch est.
        produced = data(config or {}, batch_size)
        return data_to_iterator(produced, batch_size, mesh, feature_cols,
                                label_cols, shuffle, seed, pad_tail,
                                config=config, stats=stats)
    shards = xshards_from_arrays(data, feature_cols, label_cols)
    chunked = chunk_shards(shards)
    cfg = config or {}
    return BatchIterator(chunked, batch_size, mesh, shuffle=shuffle,
                         seed=seed, pad_tail=pad_tail, stats=stats,
                         prefetch_depth=int(cfg.get("infeed_depth", 2)),
                         prefetch_workers=cfg.get("infeed_workers"))


def update_predict_xshards(xshards: HostXShards,
                           pred_shards: HostXShards) -> HostXShards:
    """Attach predictions to the original shards (reference:
    orca/learn/utils.py:116-125)."""
    def merge(pair):
        d, pred = pair
        out = dict(d) if isinstance(d, dict) else {"x": d}
        out["prediction"] = pred
        return out
    return xshards.zip(pred_shards).transform_shard(merge)


def find_latest_checkpoint(model_dir: str, model_type: str = "tpu"):
    """Locate the newest versioned checkpoint under model_dir (reference:
    orca/learn/utils.py:24-69 scans for model.<iter> files; here step
    dirs). One scanner — ``ckpt.format.loadable_step_dirs`` — decides
    candidacy for this, the plane and the hot-reload watcher: plane dirs
    count only when COMMITTED (a manifest without its COMMIT marker is a
    torn write and must never be the resume point); ``bare_ok`` keeps
    this function's historical acceptance of bare step dirs from
    pre-plane layouts."""
    from ...ckpt.format import loadable_step_dirs
    dirs = loadable_step_dirs(model_dir, bare_ok=True)
    if not dirs:
        return None, None
    step, path = dirs[-1]
    return path, step
