from .comms import BucketLayout, CommsConfig, CommsPlan
from .mesh import (batch_divisor, create_mesh, data_sharding,
                   mesh_axis_size, mesh_topology, nontrivial_axes,
                   parse_mesh_axes, pure_dp, replicated, resolve_axis_sizes)
from .sharding import FsdpPlan, SpecLayout
from .expert_parallel import (expert_sharding, moe_apply,
                              stack_expert_params)
from .pipeline_parallel import (pipeline_apply, stack_stage_params,
                                stage_sharding)
from .tensor_parallel import (TPDense, TPMLP, TPSelfAttention,
                              TPTransformerBlock)

__all__ = ["create_mesh", "data_sharding", "replicated", "resolve_axis_sizes",
           "mesh_axis_size", "batch_divisor", "pure_dp", "nontrivial_axes",
           "parse_mesh_axes", "mesh_topology", "BucketLayout",
           "CommsConfig", "CommsPlan", "SpecLayout", "FsdpPlan",
           "TPDense", "TPMLP", "TPSelfAttention", "TPTransformerBlock",
           "pipeline_apply", "stack_stage_params", "stage_sharding",
           "moe_apply", "stack_expert_params", "expert_sharding"]
