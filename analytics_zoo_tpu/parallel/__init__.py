from .comms import BucketLayout, CommsConfig, CommsPlan
from .mesh import (batch_divisor, create_mesh, data_sharding,
                   mesh_axis_size, pure_dp, replicated, resolve_axis_sizes)
from .expert_parallel import (expert_sharding, moe_apply,
                              stack_expert_params)
from .pipeline_parallel import (pipeline_apply, stack_stage_params,
                                stage_sharding)
from .tensor_parallel import (TPDense, TPMLP, TPSelfAttention,
                              TPTransformerBlock)

__all__ = ["create_mesh", "data_sharding", "replicated", "resolve_axis_sizes",
           "mesh_axis_size", "batch_divisor", "pure_dp", "BucketLayout",
           "CommsConfig", "CommsPlan", "TPDense", "TPMLP",
           "TPSelfAttention", "TPTransformerBlock", "pipeline_apply",
           "stack_stage_params", "stage_sharding", "moe_apply",
           "stack_expert_params", "expert_sharding"]
