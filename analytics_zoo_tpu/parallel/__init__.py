from .mesh import (batch_divisor, create_mesh, data_sharding,
                   mesh_axis_size, replicated, resolve_axis_sizes)
from .tensor_parallel import (TPDense, TPMLP, TPSelfAttention,
                              TPTransformerBlock)

__all__ = ["create_mesh", "data_sharding", "replicated", "resolve_axis_sizes",
           "mesh_axis_size", "batch_divisor", "TPDense", "TPMLP",
           "TPSelfAttention", "TPTransformerBlock"]
