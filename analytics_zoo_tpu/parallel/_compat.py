"""Version-tolerant jax surface for the parallelism layer.

jax >= 0.8 exports ``jax.shard_map`` (with the ``check_vma`` kwarg and vma
typing via ``jax.typeof``/``lax.pvary``); older releases ship
``jax.experimental.shard_map.shard_map`` with ``check_rep`` and no vma
machinery. The helpers here paper over both so the ep/pp/sp code paths
import and run on either generation.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax

try:                                        # jax >= 0.8 top-level export
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:                         # older jax: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` facade: maps ``check_vma`` onto whichever kwarg the
    installed jax understands. Usable as a decorator factory like the real
    thing (``shard_map(mesh=..., in_specs=..., out_specs=...)(f)``)."""
    if f is None:
        return partial(shard_map, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=check_vma)
    kw = {}
    if check_vma is not None:
        kw[_CHECK_KW] = check_vma
    elif _CHECK_KW == "check_rep":
        # old-jax replication checking has no rule for pallas_call (and
        # several other primitives these code paths use); new jax handles
        # them through vma typing. Default it off for parity.
        kw[_CHECK_KW] = False
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kw)


def axis_size(axis_name) -> int:
    """Size of a mapped mesh axis from inside shard_map. ``lax.axis_size``
    where it exists; on older jax the constant-folded ``psum(1, axis)``
    (returns a Python int, no collective is emitted)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def varying_axes(*arrays) -> tuple:
    """Union of the arrays' shard_map varying-axes sets; empty on jax
    builds without vma typing. (ops/attention.py keeps local equivalents
    — _vma_of/_input_vma — to avoid importing the parallel package from
    the ops layer; keep the None-guard below in sync with them.)"""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return ()
    out = frozenset()
    for a in arrays:
        # some builds expose .vma = None rather than omitting it
        out |= getattr(typeof(a), "vma", None) or frozenset()
    return tuple(out)


def mark_varying(x, vma: tuple):
    """Tag a device-invariant array as varying over ``vma`` (no-op where
    the installed jax has no vma typing)."""
    if not vma:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, vma, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, vma)
    return x
