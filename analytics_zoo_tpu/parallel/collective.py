"""Named-axis collective helpers used inside jitted steps.

The single replacement for the reference's five comm backends (SURVEY.md §2.4).
All of these lower to XLA collectives that ride ICI within a slice and DCN
across slices — there is no rendezvous, no parameter server, no block manager.
"""

from __future__ import annotations

from typing import Any, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def psum(tree: Any, axis: AxisName = "dp") -> Any:
    return lax.psum(tree, axis_name=axis)


def pmean(tree: Any, axis: AxisName = "dp") -> Any:
    return lax.pmean(tree, axis_name=axis)


def all_gather(x, axis: AxisName = "dp", *, axis_index_groups=None, tiled=True):
    return lax.all_gather(x, axis_name=axis, tiled=tiled,
                          axis_index_groups=axis_index_groups)


def reduce_scatter(x, axis: AxisName = "dp", *, scatter_dimension=0,
                   axis_index_groups=None):
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dimension, tiled=True,
                            axis_index_groups=axis_index_groups)


def ppermute_shift(x, axis: AxisName = "sp", shift: int = 1):
    """Ring shift along an axis — building block for ring attention."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: AxisName = "dp"):
    return lax.axis_index(axis)


def axis_size(axis: AxisName = "dp"):
    from ._compat import axis_size as _axis_size
    return _axis_size(axis)


def axis_bound(axis: str) -> bool:
    """True when ``axis`` is bound in the ambient mapped context (shard_map
    / pmap). Probing costs nothing: the size query constant-folds, and an
    unbound name raises instead of emitting a collective."""
    try:
        axis_size(axis)
        return True
    except (NameError, KeyError, ValueError, TypeError):
        return False


def grad_allreduce_mean(grads: Any, axes: Sequence[str] = ("dp", "fsdp")) -> Any:
    """Mean-reduce gradients over the data axes — the one-liner that replaces
    BigDL's AllReduceParameter push/pull cycle (reference:
    zoo/.../keras/models/Topology.scala:1203-1206, docs/docs/wp-bigdl.md:140-160).

    Axis names absent from the ambient mesh are skipped, so the default
    ``("dp", "fsdp")`` works unchanged inside a single-axis
    ``Mesh(devices, ("dp",))`` shard_map (reducing over an unbound name
    used to raise). Calling with NO bound axis at all still raises —
    silently returning unreduced gradients would let replicas diverge."""
    bound = [ax for ax in axes if axis_bound(ax)]
    if axes and not bound:
        raise NameError(
            f"grad_allreduce_mean: none of the axes {tuple(axes)} are "
            "bound in the ambient mesh — call it inside shard_map/pmap "
            "over at least one of them")
    out = grads
    for ax in bound:
        out = lax.pmean(out, axis_name=ax)
    return out
