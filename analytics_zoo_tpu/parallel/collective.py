"""Named-axis collective helpers used inside jitted steps.

The single replacement for the reference's five comm backends (SURVEY.md §2.4).
All of these lower to XLA collectives that ride ICI within a slice and DCN
across slices — there is no rendezvous, no parameter server, no block manager.
"""

from __future__ import annotations

from typing import Any, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def psum(tree: Any, axis: AxisName = "dp") -> Any:
    return lax.psum(tree, axis_name=axis)


def pmean(tree: Any, axis: AxisName = "dp") -> Any:
    return lax.pmean(tree, axis_name=axis)


def all_gather(x, axis: AxisName = "dp", *, axis_index_groups=None, tiled=True):
    return lax.all_gather(x, axis_name=axis, tiled=tiled,
                          axis_index_groups=axis_index_groups)


def reduce_scatter(x, axis: AxisName = "dp", *, scatter_dimension=0):
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dimension, tiled=True)


def ppermute_shift(x, axis: AxisName = "sp", shift: int = 1):
    """Ring shift along an axis — building block for ring attention."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: AxisName = "dp"):
    return lax.axis_index(axis)


def axis_size(axis: AxisName = "dp"):
    from ._compat import axis_size as _axis_size
    return _axis_size(axis)


def grad_allreduce_mean(grads: Any, axes: Sequence[str] = ("dp", "fsdp")) -> Any:
    """Mean-reduce gradients over the data axes — the one-liner that replaces
    BigDL's AllReduceParameter push/pull cycle (reference:
    zoo/.../keras/models/Topology.scala:1203-1206, docs/docs/wp-bigdl.md:140-160)."""
    out = grads
    for ax in axes:
        out = lax.pmean(out, axis_name=ax)
    return out
