"""Comms plane: bucketed gradient reduce-scatter, cross-replica sharded
weight update (ZeRO-1), and a quantized allreduce wire.

The data-parallel train step's gradient exchange is the one collective the
whole platform stands on (the reference pushed it through the Spark block
manager; here it rides ICI/DCN). This module makes that exchange an explicit,
tunable plane instead of whatever GSPMD happens to emit:

* **Bucketing** — the grad pytree is flattened, in deterministic leaf order,
  into contiguous fixed-size buckets (``ZOO_GRAD_BUCKET_MB``), so a model
  with hundreds of small leaves rides a handful of large collectives instead
  of one per leaf. The allreduce is decomposed as reduce-scatter +
  all-gather (bit-identical to ``pmean`` per element — each element is the
  same N-replica sum either way), which is also what makes ZeRO-1 free.

* **Sharded weight update (ZeRO-1)** — after the reduce-scatter each replica
  already holds 1/N of the summed gradient, so it keeps only 1/N of the
  optimizer state, applies the (elementwise) optax update to its parameter
  shard, and all-gathers the updated parameters. Optimizer HBM per replica
  shrinks by the dp degree; the update itself is bit-identical to the
  unsharded one ("Automatic Cross-Replica Sharding of Weight Update in
  Data-Parallel Training", arXiv:2004.13336).

* **Quantized wire** — block-scaled bf16/int8 gradient compression with an
  error-feedback residual (EQuARX, arXiv:2506.17615; EF-SGD): each step the
  residual of the previous step's quantization is added back before
  quantizing, so the compression error is corrected over time instead of
  accumulating. bf16 genuinely rides the collective; int8 is simulated-wire
  by default on this jax (values are dequantized before the reduce because
  XLA exposes no int8-accumulating allreduce) — byte accounting reports
  what a native int8 wire would move.

* **Native int8 ring** (``ZOO_COMMS_NATIVE_INT8``) — retires the simulated
  int8 wire: the bucket reduce-scatter is decomposed into a shard_map
  ``ppermute`` ring (EQuARX, arXiv:2506.17615 — block-scaled quantize,
  exchange of int8 payloads + f32 scales packed into ONE int8 operand per
  hop, dequant-accumulate on arrival). The local partial stays in a wide
  f32 accumulator and the outgoing chunk is quantized fresh each hop
  (bounded drift); error feedback is per chunk slot on the same residual
  shapes as the simulated wire. On the classic path the ring spans the dp
  axis; on the hierarchical wire it runs per DCN group — ICI stays exact
  f32, only the cross-host hops carry int8, so DCN genuinely moves ~4x
  fewer bytes than f32 (~2x vs bf16). Because the hops REALLY move int8,
  hlo_lint's byte accounting is byte-exact (no simulated-wire exemption),
  and the ring's different summation association means bit-identity with
  the psum_scatter wire holds only where the math is exact (integer-
  valued grads) — the EF drift bound is the contract, as for every
  quantized wire.

* **Hierarchical two-level wire** (``ZOO_COMMS_HIERARCHY``) — every leg
  above treats the dp axis as one flat ring, which is wrong at pod scale:
  inside a host the chips talk over ICI at TB/s, across hosts the wire is
  DCN at tens of GB/s, and a flat collective pays DCN price for the whole
  gradient (the MLPerf TPU-pod lesson, arXiv:1909.09756; Horovod's
  hierarchical allreduce, arXiv:1802.05799). The hierarchy factors the dp
  axis into ``(dcn, ici)`` sub-axes (``parallel/mesh.py:dp_topology`` —
  process locality on a real multihost mesh, ``ZOO_COMMS_DCN_AXIS`` as
  the simulated split) and decomposes each bucket's exchange as:
  reduce-scatter over the ICI group (full bucket rides the fast links,
  producing per-chip host-partial chunks), then allreduce — or, under
  ZeRO-1, reduce-scatter — of the already-reduced ``1/ici`` chunks over
  the DCN group, then all-gather back over ICI. DCN moves ``1/ici`` of
  the bytes a flat collective would push through it. Bucket boundaries
  stay aligned so no bucket straddles a host shard (every bucket divides
  by ``n_dev``, and for the int8 DCN wire by ``ici*block``). The
  quantized wire composes DCN-side by default (``ZOO_COMMS_QUANTIZE_DCN``):
  the ICI leg reduces exact f32 and only the cross-host leg — where bytes
  are expensive — carries bf16/int8 with the error-feedback residual now
  living on the chunk domain.

  Numerics: the two-level wire sums each element as (host-linear partial
  sums) then (linear across hosts) — a different floating-point
  association than the flat wire's single linear reduction, so
  hierarchical-vs-flat differs at the last-ulp level exactly like
  entering the plane shifts vs GSPMD (documented below). The bit-identity
  family holds *within* the two-level wire: single-bucket == bucketed ==
  overlapped == ZeRO-1-sharded are bit-identical on the f32 mesh (every
  variant computes the same per-element two-level sum), a ``dcn == 1``
  factorization collapses byte-for-byte onto the classic bucketed wire,
  and the whole decomposition is bit-exact against its numpy host twins
  (:func:`hier_reduce_scatter_np` et al.) — all test-asserted.

* **Overlapped backward–comms pipeline** (``ZOO_COMMS_OVERLAP``) — the
  bucketed wire above still assembles ONE padded flat vector from every
  grad leaf before the first reduce-scatter can launch: that concatenate
  is a synchronization barrier, so wire time adds to — instead of hides
  behind — backward compute (Horovod's tensor-fusion lesson,
  arXiv:1802.05799). In overlapped mode a :class:`SegmentPlan` stages the
  gradient wire into bucket-aligned segments assembled straight from the
  leaf slices that compose each bucket, so bucket k's reduce-scatter
  depends only on its own leaves' gradients — the moment reverse AD has
  produced them, the collective is schedulable while later segments (the
  earlier layers' backward) keep computing. XLA's latency-hiding
  scheduler needs exactly that dependence freedom to issue the async
  start early and sink the done; on the CPU-sim mesh the program is
  sequential, so the win is asserted structurally (per-bucket dependency
  cones, launch counts, byte-identical wire) and measured on hardware.
  ``ZOO_COMMS_SEGMENTS`` coarsens the pipeline: buckets grouped into N
  dependency islands (1 = the classic post-backward wire, the default 0 =
  one segment per bucket = maximum overlap). Values on the wire are the
  exact same elements in the exact same order as the flat-vector path, so
  the plane's bit-identity contract extends: flat == bucketed == sharded
  == overlapped, and total wire bytes are byte-for-byte unchanged.

Numerics contract (asserted by tests/test_comms_plane.py): within the comms
plane, bucketed == flat-psum bit-exactly and sharded == unsharded bit-exactly
on an f32 mesh. The plane itself is *opt-in*: with it off, the engine's
default GSPMD step is byte-for-byte the pre-plane program. (The explicit
shard_map step and GSPMD's auto-partitioned step differ at the last-ulp
level because GSPMD may re-associate backward matmul reductions — that is
a property of turning the plane on, not of any knob inside it.)
"""

from __future__ import annotations

import hashlib
import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import collective as C

__all__ = ["CommsConfig", "BucketLayout", "CommsPlan", "SegmentPlan",
           "build_layout", "hier_reduce_scatter_np", "hier_allreduce_np",
           "hier_mean_np", "group_sum_np", "quantize_wire",
           "quantize_blocks", "dequantize_blocks", "pack_wire",
           "unpack_wire", "native_ring_reduce_scatter_np"]

WIRE_DTYPES = ("f32", "bf16", "int8")
_WIRE_BYTES = {"f32": 4, "bf16": 2, "int8": 1}


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class CommsConfig:
    """Knobs of the comms plane. ``active`` is False in the all-default
    state — the engine then keeps its pre-plane GSPMD step untouched.

    bucket_mb    — target bucket size in MiB (``ZOO_GRAD_BUCKET_MB``).
                   0 = per-leaf flat psum (the reference wire, one
                   collective per grad leaf).
    sharded_update — ZeRO-1 cross-replica sharded optimizer update
                   (``ZOO_SHARDED_UPDATE`` / ``TPUEstimator(sharded_update=)``).
    wire_dtype   — "f32" (exact, default) | "bf16" | "int8"
                   (``ZOO_ALLREDUCE_DTYPE``); non-f32 enables the
                   error-feedback residual.
    block        — elements per int8 scale block (``ZOO_ALLREDUCE_BLOCK``).
    axis         — the data-parallel mesh axis the plane reduces over.
    explicit     — turn the plane on with every other knob at default
                   (config ``comms_plane`` / ``ZOO_COMMS_PLANE``): the
                   flat-psum reference wire, one collective per grad leaf.
                   This is the baseline bench_comms compares buckets
                   against.
    overlap      — overlapped backward–comms pipeline (``ZOO_COMMS_OVERLAP``
                   / config ``comms_overlap``): assemble each bucket
                   straight from its own leaf slices so its reduce-scatter
                   launches as soon as those gradients exist, instead of
                   behind a whole-tree flatten barrier.
    segments     — dependency-island override for the overlapped pipeline
                   (``ZOO_COMMS_SEGMENTS`` / config ``comms_segments``):
                   0 = one segment per bucket (maximum overlap), 1 = a
                   single segment (the classic post-backward wire shape),
                   N = buckets coalesced into N contiguous groups.
    hierarchy    — two-level ICI×DCN wire (``ZOO_COMMS_HIERARCHY`` /
                   config ``comms_hierarchy``): reduce-scatter inside the
                   host group, allreduce (ZeRO-1: reduce-scatter) of the
                   already-reduced chunks across hosts.
    dcn_size     — host-group count override (``ZOO_COMMS_DCN_AXIS`` /
                   config ``comms_dcn_axis``): 0 = probe the mesh's
                   process topology (``mesh.dp_topology``); N = factor
                   the dp axis into N simulated hosts — the tier-1 mesh's
                   stand-in for a real pod.
    quantize_dcn — with ``hierarchy`` and a non-f32 wire, quantize ONLY
                   the DCN leg (``ZOO_COMMS_QUANTIZE_DCN``, default on):
                   the ICI leg reduces exact f32; bytes shrink where they
                   are expensive. Off = the classic wire shape (bucket
                   quantized before the ICI leg; the DCN leg then moves
                   f32 host-partial sums).
    native_int8  — ``allreduce_impl="native_int8"``
                   (``ZOO_COMMS_NATIVE_INT8`` / config
                   ``comms_native_int8``): replace the simulated int8
                   exchange (dequantize, then f32 reduce) with a
                   shard_map ``ppermute`` ring reduce-scatter whose hops
                   really move int8 payloads + their f32 block scales.
                   Classic path: the full-axis ring replaces the bucket
                   reduce-scatter. Hierarchical path: the ICI leg stays
                   exact f32 and the ring runs over each DCN group, so
                   the cross-host exchange genuinely shrinks ~4x vs f32
                   (~2x vs bf16). Requires ``wire_dtype="int8"`` (and,
                   with ``hierarchy``, ``quantize_dcn`` on).
    """

    bucket_mb: float = 0.0
    sharded_update: bool = False
    wire_dtype: str = "f32"
    block: int = 256
    axis: str = "dp"
    explicit: bool = False
    overlap: bool = False
    segments: int = 0
    hierarchy: bool = False
    dcn_size: int = 0
    quantize_dcn: bool = True
    native_int8: bool = False

    DEFAULT_BUCKET_MB = 4.0

    def __post_init__(self):
        if self.wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"allreduce wire dtype {self.wire_dtype!r} not in "
                f"{WIRE_DTYPES}")
        if self.bucket_mb < 0:
            raise ValueError("grad_bucket_mb must be >= 0")
        if self.block < 1:
            raise ValueError("allreduce block must be >= 1")
        if self.segments < 0:
            raise ValueError("comms_segments must be >= 0")
        if self.dcn_size < 0:
            raise ValueError("comms_dcn_axis must be >= 0")
        if self.dcn_size > 0 and not self.hierarchy:
            raise ValueError(
                "comms_dcn_axis only applies to the hierarchical wire — "
                "set comms_hierarchy/ZOO_COMMS_HIERARCHY too")
        if self.native_int8 and self.wire_dtype != "int8":
            raise ValueError(
                "comms_native_int8/ZOO_COMMS_NATIVE_INT8 is the int8 "
                "wire's native implementation — set allreduce_dtype=int8 "
                f"(got {self.wire_dtype!r})")
        if self.native_int8 and self.hierarchy and not self.quantize_dcn:
            raise ValueError(
                "the native int8 ring rides the hierarchical wire's DCN "
                "leg only (quantize-where-expensive) — it requires "
                "comms_quantize_dcn on")

    @property
    def active(self) -> bool:
        return (self.sharded_update or self.bucket_mb > 0
                or self.wire_dtype != "f32" or self.explicit
                or self.overlap or self.hierarchy)

    @property
    def quantized(self) -> bool:
        return self.wire_dtype != "f32"

    @property
    def effective_bucket_mb(self) -> float:
        """Quantization and the sharded update both work bucket-wise, so an
        unset bucket size resolves to the default when either is on."""
        if self.bucket_mb > 0:
            return self.bucket_mb
        if (self.sharded_update or self.quantized or self.overlap
                or self.hierarchy):
            return self.DEFAULT_BUCKET_MB
        return 0.0

    def fingerprint(self) -> str:
        """Stable string for the compile plane's structural key — two
        engines whose comms knobs differ must never share an executable.
        The overlap flag and segment override are program shape (where the
        reduce-scatters sit in the dependence graph), so they salt the key
        exactly like the bucket layout does; the hierarchy knobs change
        every collective's replica groups and salt it the same way."""
        fp = (f"comms:bucket_mb={self.effective_bucket_mb}:"
              f"sharded={int(self.sharded_update)}:"
              f"wire={self.wire_dtype}:block={self.block}:"
              f"axis={self.axis}:overlap={int(self.overlap)}:"
              f"segments={self.segments}:"
              f"hier={int(self.hierarchy)}:dcn={self.dcn_size}:"
              f"qdcn={int(self.quantize_dcn)}")
        # appended only when on, so every pre-existing fingerprint (and the
        # executables cached under it) is byte-identical with the knob off
        if self.native_int8:
            fp += ":native=1"
        return fp

    @classmethod
    def resolve(cls, config: Optional[Dict] = None,
                sharded_update: Optional[bool] = None) -> "CommsConfig":
        """Resolve knobs: explicit argument > config dict > environment >
        default. Returns the inactive config when nothing is set."""
        cfg = config or {}

        def _env(name, default=None):
            v = os.environ.get(name, "")
            return v if v != "" else default

        if sharded_update is None:
            raw = cfg.get("sharded_update", _env("ZOO_SHARDED_UPDATE"))
            sharded_update = str(raw).lower() in ("1", "true", "yes", "on") \
                if raw is not None else False
        bucket_mb = float(cfg.get("grad_bucket_mb",
                                  _env("ZOO_GRAD_BUCKET_MB", 0.0)))
        wire = str(cfg.get("allreduce_dtype",
                           _env("ZOO_ALLREDUCE_DTYPE", "f32"))).lower()
        wire = {"float32": "f32", "bfloat16": "bf16"}.get(wire, wire)
        block = int(cfg.get("allreduce_block",
                            _env("ZOO_ALLREDUCE_BLOCK", 256)))
        raw_exp = cfg.get("comms_plane", _env("ZOO_COMMS_PLANE"))
        explicit = str(raw_exp).lower() in ("1", "true", "yes", "on") \
            if raw_exp is not None else False
        raw_ov = cfg.get("comms_overlap", _env("ZOO_COMMS_OVERLAP"))
        overlap = str(raw_ov).lower() in ("1", "true", "yes", "on") \
            if raw_ov is not None else False
        segments = int(cfg.get("comms_segments",
                               _env("ZOO_COMMS_SEGMENTS", 0)))
        raw_h = cfg.get("comms_hierarchy", _env("ZOO_COMMS_HIERARCHY"))
        hierarchy = str(raw_h).lower() in ("1", "true", "yes", "on") \
            if raw_h is not None else False
        dcn_size = int(cfg.get("comms_dcn_axis",
                               _env("ZOO_COMMS_DCN_AXIS", 0)))
        raw_q = cfg.get("comms_quantize_dcn",
                        _env("ZOO_COMMS_QUANTIZE_DCN"))
        quantize_dcn = str(raw_q).lower() in ("1", "true", "yes", "on") \
            if raw_q is not None else True
        raw_n = cfg.get("comms_native_int8", _env("ZOO_COMMS_NATIVE_INT8"))
        native_int8 = str(raw_n).lower() in ("1", "true", "yes", "on") \
            if raw_n is not None else False
        return cls(bucket_mb=bucket_mb, sharded_update=bool(sharded_update),
                   wire_dtype=wire, block=block, explicit=explicit,
                   overlap=overlap, segments=segments, hierarchy=hierarchy,
                   dcn_size=dcn_size, quantize_dcn=quantize_dcn,
                   native_int8=native_int8)


# ---------------------------------------------------------------------------
# bucket layout
# ---------------------------------------------------------------------------
@dataclass
class BucketLayout:
    """Static placement of a grad/param pytree inside a padded flat f32
    vector, plus its bucket boundaries and per-replica shard mapping.

    Leaf order is ``jax.tree_util.tree_flatten`` order — deterministic for
    a given tree structure (dict keys sort), and the SAME order every
    flatten/unflatten call uses, so assembly/disassembly round-trips
    bit-exactly.

    Two element orders exist:

    * **flat order** — leaves concatenated, zero-padded to ``padded_total``.
    * **scattered order** — chunk-major: chunk ``s`` of every bucket,
      concatenated, is the contiguous slice
      ``[s*shard_size, (s+1)*shard_size)``. On the flat wire replica ``s``
      owns chunk ``s``; on the hierarchical wire the two-level
      reduce-scatter hands device ``k = h*ici + i`` chunk
      ``σ(k) = i*dcn + h`` instead, so sharded optimizer state is stored
      **device-major** (row ``k`` = chunk ``σ(k)``; see
      :meth:`to_device_scattered_np`) and a plain ``P(axis)``
      NamedSharding still puts each replica's own 1/N on its own chip.
      Without hierarchy ``σ`` is the identity and device-major ==
      chunk-major, bit for bit.
    """

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]
    sizes: Tuple[int, ...]
    n_dev: int
    bucket_sizes: Tuple[int, ...]
    total: int
    padded_total: int
    shard_size: int
    wire_dtype: str = "f32"
    block: int = 256
    ici: int = 1            # devices per host group along the dp axis
    dcn: int = 1            # host groups (1 = flat single-level wire)
    quantize_dcn: bool = True
    native_int8: bool = False

    # -- construction --------------------------------------------------------
    @staticmethod
    def build(tree, n_dev: int, bucket_mb: float,
              wire_dtype: str = "f32", block: int = 256,
              ici: int = 1, dcn: int = 1,
              quantize_dcn: bool = True,
              native_int8: bool = False) -> "BucketLayout":
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            raise ValueError("comms plane: empty parameter tree")
        # metadata only — leaf .dtype/.shape, never np.asarray (which
        # would D2H-copy every on-device param just to read its header)
        def _dtype(l):
            dt = getattr(l, "dtype", None)
            return np.dtype(dt) if dt is not None else np.result_type(l)
        for l in leaves:
            # every contract the plane promises (flat==bucketed==sharded
            # bit-identity, lossless sharded opt-state round-trip, the EF
            # residual algebra) is stated — and tested — for f32 params;
            # a bf16/f16 leaf would silently truncate moments through the
            # f32 flat vector and break the bit-identity the tests gate on
            if _dtype(l) != np.dtype(np.float32):
                raise ValueError(
                    "comms plane: param/grad leaf of dtype "
                    f"{_dtype(l)} cannot ride the f32 wire (the plane's "
                    "bit-identity and sharded-checkpoint contracts are "
                    "f32-only; keep the plane off for non-f32 params)")
        shapes = tuple(tuple(int(d) for d in np.shape(l)) for l in leaves)
        dtypes = tuple(str(_dtype(l)) for l in leaves)
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        total = sum(sizes)
        ici, dcn = int(ici), int(dcn)
        if ici * dcn != int(n_dev) and dcn > 1:
            raise ValueError(
                f"hierarchical layout: ici({ici}) x dcn({dcn}) must equal "
                f"the dp axis size {n_dev}")
        # every bucket must split evenly over the axis (tiled reduce-scatter)
        # and, for int8, into whole scale blocks. The host-boundary rule:
        # divisibility by n_dev already means each bucket splits into ici
        # whole host chunks of dcn whole sub-chunks — no bucket straddles a
        # host shard. The int8 DCN-only wire quantizes the (bucket/ici)
        # chunk, so that chunk must also split into whole scale blocks.
        if wire_dtype != "int8":
            align = n_dev
        elif native_int8:
            # the ring quantizes per HOP CHUNK (bucket/n_dev on the classic
            # ring; the same bucket/(ici*dcn) sub-chunk on the DCN ring),
            # so every chunk — not just every bucket — must split into
            # whole scale blocks. n_dev*block is a multiple of both
            # legacy int8 alignments, so the stricter rule subsumes them.
            align = n_dev * block
        elif dcn > 1 and quantize_dcn:
            per_host = ici * block
            align = (n_dev * per_host) // math.gcd(n_dev, per_host)
        else:
            align = (n_dev * block) // math.gcd(n_dev, block)
        if bucket_mb and bucket_mb > 0:
            target = max(int(bucket_mb * (1 << 20)) // 4, align)
            b = (target // align) * align or align
            n_full = total // b
            rem = total - n_full * b
            bucket_sizes = [b] * n_full
            if rem or not bucket_sizes:
                bucket_sizes.append(-(-rem // align) * align or align)
        else:
            # no bucketing: one bucket spanning the whole vector (used by
            # the sharded update's shard mapping; the flat-psum wire never
            # touches buckets)
            bucket_sizes = [-(-total // align) * align]
        padded_total = sum(bucket_sizes)
        # a degenerate factorization collapses onto the classic flat wire:
        # dcn==1 (single host — no cross-host leg) and ici==1 (one chip
        # per host — no fast links to pre-reduce on, so the "ICI leg"
        # would be a no-op and the DCN groups would just be the full axis
        # wearing a hierarchical label)
        hier = dcn > 1 and ici > 1
        return BucketLayout(
            treedef=treedef, shapes=shapes, dtypes=dtypes, sizes=sizes,
            n_dev=int(n_dev), bucket_sizes=tuple(bucket_sizes), total=total,
            padded_total=padded_total,
            shard_size=padded_total // int(n_dev),
            wire_dtype=wire_dtype, block=int(block),
            ici=ici if hier else int(n_dev), dcn=dcn if hier else 1,
            quantize_dcn=bool(quantize_dcn),
            native_int8=bool(native_int8))

    def signature(self) -> str:
        """Content hash of everything that changes the step's program or
        the checkpointed sharded-state layout."""
        # extra fields are appended only when set, so every pre-existing
        # layout signature is unchanged with the native wire off
        extra = ("native_int8",) if self.native_int8 else ()
        h = hashlib.sha256(repr((
            self.shapes, self.dtypes, self.n_dev, self.bucket_sizes,
            self.wire_dtype, self.block, self.ici, self.dcn,
            self.quantize_dcn) + extra).encode())
        return h.hexdigest()[:16]

    # -- hierarchy -----------------------------------------------------------
    @property
    def hierarchical(self) -> bool:
        return self.dcn > 1

    @property
    def resid_elems(self) -> int:
        """Per-replica error-feedback residual length. The classic wire
        quantizes whole buckets (flat domain, ``padded_total``); the
        DCN-only quantized hierarchy quantizes the post-ICI
        ``bucket/ici`` chunks, so the residual lives on the chunk domain
        (``padded_total/ici``)."""
        if (self.hierarchical and self.quantize_dcn
                and self.wire_dtype != "f32"):
            return self.padded_total // self.ici
        return self.padded_total

    def chunk_sizes(self) -> Tuple[int, ...]:
        """Per-bucket post-ICI chunk lengths (``bucket/ici``) — the DCN
        operand sizes, and the bucket boundaries of the chunk-domain
        residual."""
        return tuple(b // self.ici for b in self.bucket_sizes)

    def chunk_buckets(self, chunk_flat) -> List:
        """Chunk-domain flat vector (``padded_total/ici``) -> per-bucket
        chunk slices (residual bookkeeping for the DCN-only wire)."""
        out, off = [], 0
        for c in self.chunk_sizes():
            out.append(chunk_flat[off:off + c])
            off += c
        return out

    def device_perm(self) -> np.ndarray:
        """``perm[k]`` = the scattered-order chunk index device ``k``
        owns after the two-level reduce-scatter: ``σ(k) = (k % ici) * dcn
        + k // ici``. Identity without hierarchy."""
        k = np.arange(self.n_dev)
        if not self.hierarchical:
            return k
        return (k % self.ici) * self.dcn + k // self.ici

    def to_device_scattered_np(self, flat: np.ndarray) -> np.ndarray:
        """Flat order -> device-major scattered order (row ``k`` = chunk
        ``σ(k)``) — the layout sharded optimizer state is stored in, so
        ``P(axis)`` places each device's own chunk. Equals
        :meth:`to_scattered_np` bit-for-bit without hierarchy."""
        rows = self.to_scattered_np(flat).reshape(self.n_dev,
                                                  self.shard_size)
        return rows[self.device_perm()].reshape(-1)

    def from_device_scattered_np(self, scat: np.ndarray) -> np.ndarray:
        rows = np.asarray(scat).reshape(self.n_dev, self.shard_size)
        inv = np.argsort(self.device_perm())
        return self.from_scattered_np(rows[inv].reshape(-1))

    # -- flat order ----------------------------------------------------------
    def flatten(self, tree):
        """Pytree -> padded flat f32 vector (bit-exact per element)."""
        leaves = jax.tree_util.tree_leaves(tree)
        flat = jnp.concatenate(
            [l.reshape(-1).astype(jnp.float32) for l in leaves])
        return jnp.pad(flat, (0, self.padded_total - self.total))

    def unflatten(self, flat):
        """Padded flat vector -> pytree (inverse of :meth:`flatten`)."""
        out, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            out.append(flat[off:off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def flatten_np(self, tree) -> np.ndarray:
        leaves = jax.tree_util.tree_leaves(tree)
        flat = np.concatenate(
            [np.asarray(l).reshape(-1).astype(np.float32) for l in leaves])
        return np.pad(flat, (0, self.padded_total - self.total))

    def unflatten_np(self, flat: np.ndarray):
        out, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            out.append(np.asarray(flat[off:off + size]).reshape(shape)
                       .astype(dtype))
            off += size
        return jax.tree_util.tree_unflatten(self.treedef, out)

    # -- buckets -------------------------------------------------------------
    def buckets(self, flat) -> List:
        out, off = [], 0
        for b in self.bucket_sizes:
            out.append(flat[off:off + b])
            off += b
        return out

    def unbuckets(self, buckets: List):
        return jnp.concatenate(buckets)

    # -- scattered (replica-major) order -------------------------------------
    def to_scattered(self, flat):
        """Flat order -> scattered order: replica i's chunk of every bucket
        becomes the contiguous slice ``[i*shard_size, (i+1)*shard_size)``."""
        cols = [b.reshape(self.n_dev, -1) for b in self.buckets(flat)]
        return jnp.concatenate(cols, axis=1).reshape(-1)

    def from_scattered(self, scat):
        rows = scat.reshape(self.n_dev, self.shard_size)
        out, off = [], 0
        for b in self.bucket_sizes:
            chunk = b // self.n_dev
            out.append(rows[:, off:off + chunk].reshape(-1))
            off += chunk
        return jnp.concatenate(out)

    def to_scattered_np(self, flat: np.ndarray) -> np.ndarray:
        cols, off = [], 0
        for b in self.bucket_sizes:
            cols.append(np.asarray(flat[off:off + b]).reshape(self.n_dev, -1))
            off += b
        return np.concatenate(cols, axis=1).reshape(-1)

    def from_scattered_np(self, scat: np.ndarray) -> np.ndarray:
        rows = np.asarray(scat).reshape(self.n_dev, self.shard_size)
        out, off = [], 0
        for b in self.bucket_sizes:
            chunk = b // self.n_dev
            out.append(rows[:, off:off + chunk].reshape(-1))
            off += chunk
        return np.concatenate(out)

    # -- wire accounting -----------------------------------------------------
    def wire_bytes_per_step(self) -> int:
        """Gradient bytes one replica puts on the wire per step (the
        reduce-scatter/exchange legs; the param all-gather is accounted
        separately). int8 includes its per-block f32 scales. For the
        hierarchical wire this is the ICI + DCN leg total — the per-axis
        split is :meth:`ici_wire_bytes_per_step` /
        :meth:`dcn_wire_bytes_per_step`."""
        if self.hierarchical:
            return (self.ici_wire_bytes_per_step()
                    + self.dcn_wire_bytes_per_step())
        if self.native_int8:
            # the ring's hops are the wire: per bucket, n_dev-1 ppermutes
            # of one packed (int8 payload + f32 block scales) hop chunk.
            # Byte-EXACT against the lowered module — each hop is a
            # collective_permute whose operand is exactly this packed
            # chunk, no simulated-wire convention left.
            return sum((self.n_dev - 1) * self.native_hop_chunk_bytes(b)
                       for b in self.bucket_sizes)
        per_elem = _WIRE_BYTES[self.wire_dtype]
        n = self.padded_total * per_elem
        if self.wire_dtype == "int8":
            n += (self.padded_total // self.block) * 4
        return n

    def ici_wire_bytes_per_step(self) -> int:
        """Bytes the ICI reduce-scatter leg moves per replica per step.
        DCN-only quantization keeps this leg exact f32; the classic-wire
        variant (``quantize_dcn=False``) quantizes before the ICI leg."""
        if not self.hierarchical:
            return 0
        if self.wire_dtype == "f32" or self.quantize_dcn:
            return self.padded_total * 4
        n = self.padded_total * _WIRE_BYTES[self.wire_dtype]
        if self.wire_dtype == "int8":
            n += (self.padded_total // self.block) * 4
        return n

    def dcn_wire_bytes_per_step(self) -> int:
        """Bytes the cross-host (DCN) exchange moves per replica per step
        — the number the hierarchy exists to shrink: ``1/ici`` of what a
        flat dp collective would push through the slow links (the
        ``(hosts-1)/hosts`` ring factor applies to both alike and is
        deliberately not modeled; operand bytes are the convention every
        other leg accounts in)."""
        if not self.hierarchical:
            return 0
        if self.native_int8:
            # DCN-group ring: per bucket, dcn-1 ppermutes of one packed
            # hop chunk (byte-exact, see wire_bytes_per_step)
            return sum((self.dcn - 1) * self.native_hop_chunk_bytes(b)
                       for b in self.bucket_sizes)
        chunk_total = self.padded_total // self.ici
        if self.wire_dtype == "f32" or not self.quantize_dcn:
            return chunk_total * 4
        n = chunk_total * _WIRE_BYTES[self.wire_dtype]
        if self.wire_dtype == "int8":
            n += (chunk_total // self.block) * 4
        return n

    def native_hop_chunk_bytes(self, bucket_size: int) -> int:
        """Bytes one native-int8 ring hop moves for one bucket: the
        ``bucket/n_dev`` hop chunk as int8 plus its f32 block scales,
        packed into a single int8 ppermute operand. The classic ring
        (full dp axis) and the DCN-group ring exchange the SAME chunk
        size — the DCN ring's operand is the post-ICI ``bucket/ici``
        chunk split ``dcn`` ways: ``bucket/(ici*dcn) == bucket/n_dev``."""
        chunk = bucket_size // self.n_dev
        return chunk + (chunk // self.block) * 4

    def native_hops_per_step(self) -> int:
        """collective_permute launches per step of the native int8 wire:
        ring-size-1 hops per bucket (ring = the dp axis on the classic
        wire, each DCN group on the hierarchical wire)."""
        if not self.native_int8:
            return 0
        ring = self.dcn if self.hierarchical else self.n_dev
        return len(self.bucket_sizes) * (ring - 1)

    def grad_bytes_f32(self) -> int:
        return self.total * 4


def build_layout(tree, n_dev: int, cfg: CommsConfig,
                 ici: int = 1, dcn: int = 1) -> BucketLayout:
    return BucketLayout.build(tree, n_dev, cfg.effective_bucket_mb,
                              wire_dtype=cfg.wire_dtype, block=cfg.block,
                              ici=ici, dcn=dcn,
                              quantize_dcn=cfg.quantize_dcn,
                              native_int8=cfg.native_int8)


# ---------------------------------------------------------------------------
# segment plan — the overlapped pipeline's dependence structure
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class LeafPiece:
    """One contiguous run of a leaf's flattened elements inside a bucket."""

    leaf: int       # index into the layout's tree_flatten leaf order
    start: int      # first element of the leaf (flat view) in this piece
    stop: int       # one past the last element


@dataclass(frozen=True)
class SegmentPlan:
    """Bucket-aligned staging of the gradient wire for the overlapped
    backward–comms pipeline.

    The classic bucketed path pads-and-concatenates EVERY grad leaf into
    one flat vector and slices buckets out of it — so in the lowered
    program every bucket's reduce-scatter transitively depends on every
    leaf, and no collective can issue until the whole backward pass has
    finished. This plan records, per bucket, exactly which leaf slices
    compose it (:class:`LeafPiece` runs, plus trailing zero padding on the
    final bucket only), and groups buckets into contiguous *segments* —
    independent dependency islands. :meth:`bucket_values` assembles each
    segment straight from its own leaves, so bucket k's reduce-scatter is
    schedulable the moment reverse AD has produced leaves
    ``pieces[k]`` — while the remaining segments' backward still runs.

    Element order inside every bucket is identical to
    ``layout.buckets(layout.flatten(tree))`` — same values, same order,
    bit for bit — only the dependence structure changes. ``n_segments``:
    0 = one segment per bucket (maximum overlap, the default), 1 = one
    segment spanning everything (the classic post-backward shape), N =
    buckets coalesced into N contiguous groups.
    """

    bucket_pieces: Tuple[Tuple[LeafPiece, ...], ...]
    bucket_pad: Tuple[int, ...]          # trailing zeros per bucket
    segments: Tuple[Tuple[int, ...], ...]  # bucket indices per segment
    bucket_sizes: Tuple[int, ...]

    @staticmethod
    def build(layout: "BucketLayout",
              n_segments: int = 0) -> "SegmentPlan":
        pieces: List[Tuple[LeafPiece, ...]] = []
        pads: List[int] = []
        leaf, off = 0, 0                 # cursor into the flat leaf order
        for b in layout.bucket_sizes:
            need, got = b, []
            while need > 0 and leaf < len(layout.sizes):
                take = min(need, layout.sizes[leaf] - off)
                got.append(LeafPiece(leaf, off, off + take))
                off += take
                need -= take
                if off == layout.sizes[leaf]:
                    leaf, off = leaf + 1, 0
            pieces.append(tuple(got))
            pads.append(need)            # only the tail bucket pads
        if n_segments <= 0 or n_segments >= len(layout.bucket_sizes):
            groups = tuple((k,) for k in range(len(layout.bucket_sizes)))
        else:
            # contiguous groups, balanced by bucket count (bucket sizes are
            # already uniform apart from the tail)
            n_b = len(layout.bucket_sizes)
            bounds = [round(i * n_b / n_segments)
                      for i in range(n_segments + 1)]
            groups = tuple(tuple(range(lo, hi))
                           for lo, hi in zip(bounds, bounds[1:]) if hi > lo)
        return SegmentPlan(bucket_pieces=tuple(pieces),
                           bucket_pad=tuple(pads), segments=groups,
                           bucket_sizes=tuple(layout.bucket_sizes))

    @property
    def n_segments(self) -> int:
        return len(self.segments)

    def _assemble(self, leaves: List, seg: Tuple[int, ...], np_mod):
        """Concatenate one segment's leaf pieces (+ tail padding)."""
        parts = []
        for k in seg:
            for p in self.bucket_pieces[k]:
                flat = leaves[p.leaf].reshape(-1)
                parts.append(flat[p.start:p.stop])
            if self.bucket_pad[k]:
                parts.append(np_mod.zeros((self.bucket_pad[k],),
                                          np_mod.float32))
        return parts[0] if len(parts) == 1 else np_mod.concatenate(parts)

    def bucket_values(self, grads) -> List:
        """Grad pytree -> per-bucket f32 vectors, assembled segment-wise so
        each bucket's dependence cone is exactly its own leaves. Bit-exact
        to ``layout.buckets(layout.flatten(grads))``."""
        leaves = [l.reshape(-1).astype(jnp.float32)
                  for l in jax.tree_util.tree_leaves(grads)]
        out: List = [None] * len(self.bucket_sizes)
        for seg in self.segments:
            seg_flat = self._assemble(leaves, seg, jnp)
            if len(seg) == 1:
                out[seg[0]] = seg_flat
            else:
                o = 0
                for k in seg:
                    out[k] = seg_flat[o:o + self.bucket_sizes[k]]
                    o += self.bucket_sizes[k]
        return out

    def bucket_values_np(self, grads) -> List[np.ndarray]:
        """Numpy host twin of :meth:`bucket_values` (tests, tooling)."""
        leaves = [np.asarray(l).reshape(-1).astype(np.float32)
                  for l in jax.tree_util.tree_leaves(grads)]
        out: List[np.ndarray] = [None] * len(self.bucket_sizes)
        for seg in self.segments:
            seg_flat = np.asarray(self._assemble(leaves, seg, np))
            o = 0
            for k in seg:
                out[k] = seg_flat[o:o + self.bucket_sizes[k]]
                o += self.bucket_sizes[k]
        return out


# ---------------------------------------------------------------------------
# quantized wire
# ---------------------------------------------------------------------------
def quantize_wire(x, wire_dtype: str, block: int):
    """Quantize one bucket for the wire; returns the dequantized f32 values
    the receiving side reconstructs (what actually enters the reduce).

    bf16: plain round-trip cast — this genuinely rides the collective as
    bf16 (the caller reduces the bf16 array). int8: symmetric per-block
    scales (max-abs / 127); dequantized before the reduce because XLA has
    no int8-accumulating allreduce — the byte accounting still reports the
    native int8 wire cost.
    """
    if wire_dtype == "f32":
        return x
    if wire_dtype == "bf16":
        return x.astype(jnp.bfloat16).astype(jnp.float32)
    blocks = x.reshape(-1, block)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(blocks / safe), -127, 127).astype(jnp.int8)
    return (q.astype(jnp.float32) * safe).reshape(x.shape)


def quantize_blocks(x, block: int):
    """Block-scaled int8 quantization SPLIT for the native wire: returns
    ``(q int8 (n,), scales f32 (n/block,))`` instead of the dequantized
    f32 values — the pair that actually travels. Same math as
    :func:`quantize_wire`'s int8 branch (max-abs/127 symmetric scales,
    round-half-even, zero blocks carry scale 1.0 so nothing divides by
    zero and padding dequantizes to exact 0.0):
    ``dequantize_blocks(*quantize_blocks(x, b), b) ==
    quantize_wire(x, "int8", b)`` bit for bit."""
    blocks = x.reshape(-1, block)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(blocks / safe), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape), safe[:, 0]


def dequantize_blocks(q, scales, block: int):
    """Inverse of :func:`quantize_blocks` up to quantization error."""
    return (q.astype(jnp.float32).reshape(-1, block)
            * scales[:, None]).reshape(-1)


def pack_wire(q, scales):
    """(int8 payload, f32 block scales) -> ONE flat int8 hop operand:
    the scales are bitcast to 4 int8 bytes each and appended, so every
    ring hop is a single ``collective_permute`` whose operand dtype and
    byte count ARE the declared wire cost — what hlo_lint's byte-exact
    accounting measures."""
    sb = lax.bitcast_convert_type(scales, jnp.int8).reshape(-1)
    return jnp.concatenate([q, sb])


def unpack_wire(packed, n_elems: int, block: int):
    """Inverse of :func:`pack_wire` for a hop chunk of ``n_elems``."""
    q = packed[:n_elems]
    scales = lax.bitcast_convert_type(
        packed[n_elems:].reshape(-1, 4), jnp.float32)
    return q, scales


def quantize_blocks_np(x: np.ndarray, block: int):
    """Numpy host twin of :func:`quantize_blocks` — bit-exact (np.round
    and jnp.round both round half to even)."""
    blocks = np.asarray(x, np.float32).reshape(-1, block)
    scale = np.abs(blocks).max(axis=1, keepdims=True) / np.float32(127.0)
    safe = np.where(scale > 0, scale, np.float32(1.0)).astype(np.float32)
    q = np.clip(np.round(blocks / safe), -127, 127).astype(np.int8)
    return q.reshape(-1), safe[:, 0]


def dequantize_blocks_np(q: np.ndarray, scales: np.ndarray, block: int):
    return (q.astype(np.float32).reshape(-1, block)
            * scales[:, None].astype(np.float32)).reshape(-1)


# ---------------------------------------------------------------------------
# numpy host twins of the two-level wire (tests, tooling, and the contract
# that the decomposition's MATH is checkable on any host — including ones
# whose jaxlib lacks multiprocess CPU collectives, where the two-process
# harness has to skip execution)
# ---------------------------------------------------------------------------
def group_sum_np(stacked: np.ndarray, groups) -> np.ndarray:
    """Per-group sum of per-device rows, accumulated LINEARLY in group
    participant order — the same association XLA's emulated collectives
    use (verified bitwise by the tests), so these twins reproduce device
    results bit for bit, not just approximately. Returns one summed row
    per group, in group order."""
    out = []
    for g in groups:
        s = np.asarray(stacked[g[0]], np.float32).copy()
        for k in g[1:]:
            s = s + np.asarray(stacked[k], np.float32)
        out.append(s)
    return np.stack(out)


def _hier_groups(n_dev: int, ici: int, dcn: int):
    ici_groups = [[h * ici + i for i in range(ici)] for h in range(dcn)]
    dcn_groups = [[h * ici + i for h in range(dcn)] for i in range(ici)]
    return ici_groups, dcn_groups


def hier_reduce_scatter_np(stacked: np.ndarray, ici: int, dcn: int
                           ) -> np.ndarray:
    """Host twin of the two-level reduce-scatter over one bucket:
    ``stacked`` is ``(n_dev, b)`` per-device values; returns ``(n_dev,
    b/n_dev)`` — the unique global-sum shard each device holds (device
    ``k = h*ici + i`` owns chunk ``σ(k) = i*dcn + h``), computed as ICI
    reduce-scatter (host-linear partial sums) then DCN reduce-scatter
    (linear across hosts)."""
    n = ici * dcn
    b = stacked.shape[1]
    ici_groups, dcn_groups = _hier_groups(n, ici, dcn)
    host = group_sum_np(stacked, ici_groups)          # (dcn, b)
    chunks = np.zeros((n, b // ici), np.float32)
    for h in range(dcn):
        for i in range(ici):
            chunks[h * ici + i] = host[h].reshape(ici, -1)[i]
    shards = np.zeros((n, b // n), np.float32)
    for gi, g in enumerate(dcn_groups):
        s = group_sum_np(chunks, [g])[0]              # global chunk gi
        for h, k in enumerate(g):
            shards[k] = s.reshape(dcn, -1)[h]
    return shards


def hier_allreduce_np(stacked: np.ndarray, ici: int, dcn: int
                      ) -> np.ndarray:
    """Host twin of the two-level allreduce over one bucket: ICI
    reduce-scatter, DCN allreduce of the chunks, ICI all-gather. Returns
    ``(n_dev, b)`` — every device's reassembled global sum (identical
    rows; kept per-device so tests can compare against each replica's
    shard_map output)."""
    n = ici * dcn
    b = stacked.shape[1]
    ici_groups, dcn_groups = _hier_groups(n, ici, dcn)
    host = group_sum_np(stacked, ici_groups)          # (dcn, b)
    chunks = np.zeros((n, b // ici), np.float32)
    for h in range(dcn):
        for i in range(ici):
            chunks[h * ici + i] = host[h].reshape(ici, -1)[i]
    summed = group_sum_np(chunks, dcn_groups)         # (ici, b/ici)
    full = summed.reshape(-1)                         # flat order
    return np.broadcast_to(full, (n, b)).copy()


def hier_mean_np(stacked: np.ndarray, ici: int, dcn: int) -> np.ndarray:
    """Two-level global MEAN of per-device values — the gradient the
    unsharded hierarchical update applies. ``(n_dev, b) -> (b,)``."""
    return hier_allreduce_np(stacked, ici, dcn)[0] / (ici * dcn)


def native_ring_reduce_scatter_np(stacked: np.ndarray, block: int,
                                  resid: Optional[np.ndarray] = None,
                                  groups=None):
    """Host twin of the native int8 ring reduce-scatter: same quantize
    math, same accumulation order, wide-f32 local accumulate, fresh
    quantize per hop, per-chunk-slot error feedback. BIT-exact against
    the shard_map ``ppermute`` implementation wherever the quantization
    is exact (block-constant ``127*k`` values, zero blocks, the planted
    exact cases the tests pin); for generic floats the device may
    contract the dequant multiply into the accumulate as one FMA — a
    rounding numpy cannot reproduce — so the twin agrees to within an
    ulp per hop there, not bitwise.

    ``stacked`` is ``(n_dev, L)`` per-device operand rows; ``groups`` is
    the list of rings (global device ids in ring order; default one ring
    spanning all rows); ``resid`` is the optional ``(n_dev, L)``
    per-chunk-slot EF residual. Returns ``(owned, new_resid)`` where
    ``owned`` is ``(n_dev, L // ring_size)`` — ring position ``p`` ends
    holding the full sum of chunk ``p``, the same ownership as the tiled
    ``psum_scatter`` it replaces."""
    stacked = np.asarray(stacked, np.float32)
    n_dev, length = stacked.shape
    if groups is None:
        groups = [list(range(n_dev))]
    n = len(groups[0])
    csize = length // n
    owned = np.zeros((n_dev, csize), np.float32)
    new_resid = np.zeros_like(stacked) if resid is not None else None

    def chunk(vec, c):
        return vec[c * csize:(c + 1) * csize]

    for g in groups:
        if n == 1:               # degenerate ring: nothing moves
            owned[g[0]] = stacked[g[0]]
            continue

        def quant_send(p, c, value):
            pre = value if resid is None \
                else value + chunk(np.asarray(resid[g[p]], np.float32), c)
            q, scales = quantize_blocks_np(pre, block)
            wire = dequantize_blocks_np(q, scales, block)
            if new_resid is not None:
                new_resid[g[p], c * csize:(c + 1) * csize] = pre - wire
            return q, scales

        send = [quant_send(p, (p - 1) % n, chunk(stacked[g[p]], (p - 1) % n))
                for p in range(n)]
        for t in range(1, n):
            recv = [send[(p - 1) % n] for p in range(n)]
            nxt = [None] * n
            for p in range(n):
                q, scales = recv[p]
                v = dequantize_blocks_np(q, scales, block)
                c = (p - 1 - t) % n
                acc = v + chunk(stacked[g[p]], c)
                if t < n - 1:
                    nxt[p] = quant_send(p, c, acc)
                else:
                    owned[g[p]] = acc
            send = nxt
    return owned, new_resid


# ---------------------------------------------------------------------------
# the plan — everything the traced step needs, all shapes static
# ---------------------------------------------------------------------------
class CommsPlan:
    """One engine's comms strategy: a :class:`CommsConfig` bound to the
    bucket layout of its parameter tree. The ``reduce_*`` methods run INSIDE
    ``shard_map`` (per-replica view); the ``opt_*``/``resid_*`` methods run
    on host arrays (checkpoint conversion)."""

    def __init__(self, cfg: CommsConfig, layout: BucketLayout):
        self.cfg = cfg
        self.layout = layout
        self.axis = cfg.axis
        # overlapped pipeline: the bucket-aligned segment plan that lets
        # each bucket's reduce-scatter depend only on its own leaves
        self.segplan: Optional[SegmentPlan] = (
            SegmentPlan.build(layout, cfg.segments) if cfg.overlap
            else None)
        # two-level wire: replica groups for the ICI (intra-host) and DCN
        # (cross-host) legs. A dcn==1 factorization (single host, or an
        # interleaved device order the probe refused) collapses the plan
        # onto the classic single-level wire — same program, same bits.
        if layout.hierarchical:
            self.ici_groups, self.dcn_groups = _hier_groups(
                layout.n_dev, layout.ici, layout.dcn)
        else:
            self.ici_groups = self.dcn_groups = None

    @property
    def hierarchical(self) -> bool:
        return self.layout.hierarchical

    # -- telemetry -----------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        lo, cfg = self.layout, self.cfg
        bucketed = cfg.effective_bucket_mb > 0
        n_b = len(lo.bucket_sizes)
        hops = lo.native_hops_per_step()
        if lo.hierarchical:
            if cfg.native_int8:
                # per bucket: ICI reduce-scatter + dcn-1 ring hops; the
                # unsharded DCN "allreduce" decomposes as ring + per-bucket
                # DCN all-gather before the ICI all-gather
                collectives = (n_b + hops + 2 if cfg.sharded_update
                               else n_b + hops + 2 * n_b)
            else:
                # per bucket: ICI reduce-scatter + DCN exchange (allreduce,
                # or reduce-scatter under ZeRO-1) + (unsharded) ICI
                # all-gather; the sharded update replaces the per-bucket
                # gathers with the two-stage (DCN then ICI) param
                # all-gather
                collectives = (2 * n_b + 2 if cfg.sharded_update
                               else 3 * n_b)
        elif bucketed:
            if cfg.native_int8:
                # n_dev-1 ring hops replace each bucket's reduce-scatter
                collectives = (hops + 1 if cfg.sharded_update
                               else hops + n_b)
            else:
                # one reduce-scatter + one all-gather per bucket (the
                # sharded update folds the grad all-gather into the param
                # all-gather)
                collectives = (2 * n_b if not cfg.sharded_update
                               else n_b + 1)
        else:
            collectives = len(lo.sizes)      # one psum per grad leaf
        out = {
            "sharded_update": cfg.sharded_update,
            "wire_dtype": cfg.wire_dtype,
            "bucket_mb": cfg.effective_bucket_mb,
            "buckets": n_b if bucketed else 0,
            "grad_leaves": len(lo.sizes),
            "collectives_per_step": collectives,
            "wire_bytes_per_step": lo.wire_bytes_per_step(),
            "grad_bytes_f32": lo.grad_bytes_f32(),
            "opt_shard_elems": lo.shard_size,
            "opt_full_elems": lo.padded_total,
            "overlap": cfg.overlap,
            "segments": self.segplan.n_segments if self.segplan else 0,
        }
        if cfg.native_int8:
            # present only when the native wire is on, so every existing
            # summary (and the goldens pinning them) is unchanged
            out["native_int8"] = True
            out["native_hops"] = hops
        if cfg.hierarchy:
            out["hierarchy"] = {
                "ici_axis": lo.ici, "dcn_axis": lo.dcn,
                "active": lo.hierarchical,
                "quantize_dcn": lo.quantize_dcn,
                "ici_wire_bytes_per_step": lo.ici_wire_bytes_per_step(),
                "dcn_wire_bytes_per_step": lo.dcn_wire_bytes_per_step(),
            }
        return out

    # -- in-step collectives (per-replica view) ------------------------------
    def reduce_leafwise_mean(self, grads):
        """Flat-psum reference wire: one pmean per grad leaf."""
        return jax.tree.map(lambda g: lax.pmean(g, self.axis), grads)

    def reduce_scatter_bucket_list(self, bucket_vals):
        """Quantize (optional) + reduce-scatter every bucket of an
        already assembled bucket list. Returns (list of per-bucket summed
        f32 shards, list of f32 wire values as the receiver reconstructs
        them) — the wire values feed the caller's error-feedback
        residual. The caller chooses the assembly: ``layout.buckets``
        slices of the whole-tree flat vector (classic), or
        :meth:`SegmentPlan.bucket_values` (overlapped — each launch keeps
        its own dependence cone).

        bf16 REALLY rides the collective: the reduce-scatter operand is
        bf16, so each element moves 2 bytes on ICI/DCN. Note the EF
        residual feeds back only this replica's LOCAL f32->bf16 cast
        error (``bucket - wire``); rounding introduced inside the bf16
        reduction's accumulation is not observable per replica and is NOT
        corrected — at large dp degrees, where accumulation error can
        dominate cast error, expect drift beyond the cast-error bound.
        int8 has no accumulating allreduce in XLA, so its values are
        dequantized before an f32 reduce and only the byte accounting
        reflects the native int8 cost."""
        shards, wires = [], []
        for bucket in bucket_vals:
            if self.cfg.wire_dtype == "bf16":
                wire16 = bucket.astype(jnp.bfloat16)
                shards.append(C.reduce_scatter(wire16, self.axis)
                              .astype(jnp.float32))
                wires.append(wire16.astype(jnp.float32))
            else:
                wire = quantize_wire(bucket, self.cfg.wire_dtype,
                                     self.cfg.block)
                shards.append(C.reduce_scatter(wire, self.axis))
                wires.append(wire)
        return shards, wires

    # -- native int8 ring (per-replica view) ---------------------------------
    def _native_exchange(self, x, resid_seg, perm, n_ring, pos):
        """One operand's native int8 ring reduce-scatter: ``n_ring - 1``
        ``ppermute`` hops, each really moving one packed (int8 payload +
        f32 block scales) hop chunk. ``perm`` is the global-index ring
        (pairs within each group ride that group's ring), ``pos`` this
        replica's ring position. Returns ``(owned, new_resid_seg)`` —
        position ``p`` ends holding the full sum of chunk ``p``, the same
        ownership as the tiled ``psum_scatter`` it replaces.

        Variant choice (documented in docs/performance_notes.md): the
        local partial is kept in a WIDE f32 accumulator and the outgoing
        chunk is quantized fresh from it each hop — per-hop drift is one
        quantization of the running sum, bounded like the simulated
        wire's, instead of compounding requantize-of-requantized error.
        Error feedback is per chunk SLOT: each replica's residual slice
        ``c`` carries the error of its last quantization while forwarding
        chunk ``c``, added back the next time it quantizes that slot —
        the same EF-SGD telescoping as the flat wire, on the same
        residual shape."""
        cfg = self.cfg
        length = x.shape[0]
        csize = length // n_ring
        block = cfg.block
        if n_ring == 1:              # degenerate ring: nothing moves
            return x, (jnp.zeros_like(resid_seg)
                       if resid_seg is not None else None)

        def seg(vec, c):
            return lax.dynamic_slice(vec, (c * csize,), (csize,))

        new_resid = (jnp.zeros_like(resid_seg)
                     if resid_seg is not None else None)

        def quant_send(c, value):
            nonlocal new_resid
            pre = value if resid_seg is None else value + seg(resid_seg, c)
            q, scales = quantize_blocks(pre, block)
            if new_resid is not None:
                wire = dequantize_blocks(q, scales, block)
                new_resid = lax.dynamic_update_slice(
                    new_resid, pre - wire, (c * csize,))
            return pack_wire(q, scales)

        c = (pos - 1) % n_ring
        packed = quant_send(c, seg(x, c))
        acc = None
        for t in range(1, n_ring):
            arrived = lax.ppermute(packed, self.axis, perm=perm)
            q, scales = unpack_wire(arrived, csize, block)
            v = dequantize_blocks(q, scales, block)
            c = (pos - 1 - t) % n_ring
            acc = v + seg(x, c)      # wide f32 local accumulate
            if t < n_ring - 1:
                packed = quant_send(c, acc)
        return acc, new_resid

    def native_reduce_scatter_bucket_list(self, bucket_vals, resid_row):
        """Classic-path native int8 wire: a full-dp-axis ring per bucket
        replaces :meth:`reduce_scatter_bucket_list`'s quantize +
        ``psum_scatter``. ``resid_row`` is this replica's flat-domain
        (``padded_total``) EF residual — the ring handles the add-back
        and error capture per chunk slot, so the caller must NOT pre-add
        it. Returns ``(shards, new_resid_row)``."""
        lo = self.layout
        n = lo.n_dev
        perm = [(i, (i + 1) % n) for i in range(n)]
        pos = C.axis_index(self.axis)
        resid_bs = (lo.buckets(resid_row) if resid_row is not None
                    else [None] * len(bucket_vals))
        shards, new_resids = [], []
        for bucket, r in zip(bucket_vals, resid_bs):
            owned, nr = self._native_exchange(bucket, r, perm, n, pos)
            shards.append(owned)
            if nr is not None:
                new_resids.append(nr)
        new_resid_row = (jnp.concatenate(new_resids) if new_resids
                         else None)
        return shards, new_resid_row

    def gather_buckets(self, shards) -> Any:
        """Per-bucket summed shards -> full flat summed vector."""
        return self.layout.unbuckets(
            [C.all_gather(s, self.axis) for s in shards])

    def shard_of(self, flat, index):
        """The shard replica ``index`` OWNS, sliced from a flat-order
        vector: chunk ``index`` of every bucket on the flat wire, chunk
        ``σ(index) = (index % ici) * dcn + index // ici`` on the
        hierarchical wire (the chunk the two-level reduce-scatter lands
        on device ``index``).

        Sliced per bucket directly from the flat vector — never
        materializing the full ``(padded_total,)`` scattered intermediate
        on every replica (a param-sized transient per step that XLA
        cannot fold away because ``index`` is traced)."""
        lo = self.layout
        if lo.hierarchical:
            index = (index % lo.ici) * lo.dcn + index // lo.ici
        chunks, off = [], 0
        for b in lo.bucket_sizes:
            chunk = b // lo.n_dev
            chunks.append(lax.dynamic_slice(
                flat, (off + index * chunk,), (chunk,)))
            off += b
        return jnp.concatenate(chunks) if len(chunks) > 1 else chunks[0]

    def unscatter(self, gathered_scat):
        """All-gathered scattered-order vector -> flat order. The
        hierarchical two-stage param gather (:meth:`hier_gather_params`)
        lands in the SAME chunk-major order a flat-axis gather of
        chunk-ordered shards does — position ``s`` of the two-stage
        result is the shard of the device owning chunk ``s`` — so one
        inverse serves both wires."""
        return self.layout.from_scattered(gathered_scat)

    # -- hierarchical two-level wire (per-replica view) ----------------------
    def hier_reduce(self, bucket_vals, resid_row):
        """Two-level exchange of assembled buckets: reduce-scatter over
        the ICI group (full bucket on the fast links -> per-replica
        ``bucket/ici`` host-partial chunks), then the DCN leg over the
        already-reduced chunks — reduce-scatter under ZeRO-1 (each
        replica keeps its unique ``bucket/n_dev`` global shard),
        allreduce otherwise (every replica of a host group holds the
        full global chunk).

        Quantization defaults to the DCN leg only
        (``cfg.quantize_dcn``): the ICI leg reduces exact f32, the
        cross-host operand carries bf16 (really riding the collective)
        or block-scaled int8 (simulated wire, as on the classic path),
        and the error-feedback residual ``resid_row`` lives on the chunk
        domain. The classic-wire variant (``quantize_dcn=False``)
        quantizes the buckets HERE, before the ICI leg — the caller only
        adds its flat-domain residual to ``bucket_vals`` beforehand and
        computes the new residual from the returned ``flat_wires``
        (quantizing caller-side too would double-quantize the ICI leg).

        Returns ``(out_list, new_resid_row, flat_wires)`` — per-bucket
        global-sum shards (sharded) or chunks (unsharded); the updated
        chunk-domain residual (DCN-only quantization, else None); and the
        f32 wire values of the classic-wire variant for the caller's
        flat-domain EF bookkeeping (None otherwise)."""
        lo, cfg = self.layout, self.cfg
        flat_wires = None
        if cfg.quantized and not lo.quantize_dcn:
            # classic wire shape under the two-level exchange: quantize
            # the assembled buckets (flat-domain residual already added
            # by the caller) before the ICI leg; bf16 genuinely rides
            # the ICI collective, the DCN leg then moves f32 host sums
            if cfg.wire_dtype == "bf16":
                w16 = [b.astype(jnp.bfloat16) for b in bucket_vals]
                flat_wires = [w.astype(jnp.float32) for w in w16]
                ici_in = w16
            else:
                flat_wires = [quantize_wire(b, cfg.wire_dtype, cfg.block)
                              for b in bucket_vals]
                ici_in = flat_wires
        else:
            ici_in = bucket_vals
        ici_chunks = [C.reduce_scatter(b, self.axis,
                                       axis_index_groups=self.ici_groups)
                      for b in ici_in]
        if flat_wires is not None and cfg.wire_dtype == "bf16":
            ici_chunks = [c.astype(jnp.float32) for c in ici_chunks]
        new_resid_row = None
        if cfg.native_int8:
            # native int8 DCN leg: the ICI leg above reduced exact f32;
            # each bucket's post-ICI chunk now rides a ppermute ring over
            # its DCN group — dcn-1 hops of genuine int8 payload + f32
            # block scales, per-chunk-slot EF on the chunk-domain
            # residual. Unsharded mode reassembles the global chunk with
            # a per-bucket DCN-group all-gather of the exact f32 ring
            # sums (gather legs stay exact, as everywhere in the plane).
            perm = [(g[j], g[(j + 1) % lo.dcn]) for g in self.dcn_groups
                    for j in range(lo.dcn)]
            pos = C.axis_index(self.axis) // lo.ici
            chunk_resids = (lo.chunk_buckets(resid_row)
                            if resid_row is not None
                            else [None] * len(ici_chunks))
            out, new_rs = [], []
            for chunk, r in zip(ici_chunks, chunk_resids):
                owned, nr = self._native_exchange(chunk, r, perm,
                                                  lo.dcn, pos)
                if not cfg.sharded_update:
                    owned = C.all_gather(owned, self.axis,
                                         axis_index_groups=self.dcn_groups)
                out.append(owned)
                if nr is not None:
                    new_rs.append(nr)
            if new_rs:
                new_resid_row = jnp.concatenate(new_rs)
            return out, new_resid_row, None
        if cfg.quantized and lo.quantize_dcn:
            pre = (ici_chunks if resid_row is None else
                   [c + r for c, r in zip(ici_chunks,
                                          lo.chunk_buckets(resid_row))])
            if cfg.wire_dtype == "bf16":
                dcn_in = [p.astype(jnp.bfloat16) for p in pre]
                wires = [w.astype(jnp.float32) for w in dcn_in]
            else:
                wires = [quantize_wire(p, cfg.wire_dtype, cfg.block)
                         for p in pre]
                dcn_in = wires
            if resid_row is not None:
                new_resid_row = jnp.concatenate(
                    [p - w for p, w in zip(pre, wires)])
        else:
            dcn_in = ici_chunks
        quant_dcn = dcn_in is not ici_chunks and cfg.wire_dtype == "bf16"
        if cfg.sharded_update:
            out = [C.reduce_scatter(c, self.axis,
                                    axis_index_groups=self.dcn_groups)
                   for c in dcn_in]
        else:
            out = [lax.psum(c, self.axis,
                            axis_index_groups=self.dcn_groups)
                   for c in dcn_in]
        if quant_dcn:
            out = [o.astype(jnp.float32) for o in out]
        return out, new_resid_row, flat_wires

    def hier_unique_shards(self, chunks, index):
        """Unsharded hierarchical update: slice each replica's UNIQUE
        sub-chunk (``h = index // ici``) out of the DCN-allreduced
        global chunks, so the norm-clip scale is computed from exactly
        the same unique-ownership pieces — same values, same association
        — the ZeRO-1 path reduces over; sharding can't move the clip
        threshold by an ulp."""
        lo = self.layout
        h = index // lo.ici
        out = []
        for c, b in zip(chunks, lo.bucket_sizes):
            sub = b // lo.n_dev
            out.append(lax.dynamic_slice(c, (h * sub,), (sub,)))
        return out

    def hier_gather_buckets(self, chunks) -> Any:
        """DCN-allreduced per-bucket global chunks -> full flat summed
        vector: one ICI all-gather per bucket (tiled group gather inverts
        the tiled ICI scatter, so flat order falls straight out)."""
        return self.layout.unbuckets(
            [C.all_gather(c, self.axis,
                          axis_index_groups=self.ici_groups)
             for c in chunks])

    def hier_gather_params(self, shard):
        """ZeRO-1 param all-gather on the two-level wire: gather the
        updated ``padded/n_dev`` shards across hosts first (DCN moves
        only ``1/n_dev`` per peer), then across the host group over ICI.
        The result is the chunk-major scattered order — feed
        :meth:`unscatter`."""
        g1 = C.all_gather(shard, self.axis,
                          axis_index_groups=self.dcn_groups)
        return C.all_gather(g1, self.axis,
                            axis_index_groups=self.ici_groups)

    # -- sharded optimizer state conversion (host side) ----------------------
    def _is_moment(self, leaf) -> bool:
        return (getattr(leaf, "ndim", None) == 1
                and leaf.shape[0] == self.layout.padded_total)

    def opt_flat_to_tree(self, flat_state):
        """Sharded-run optimizer state (moment leaves are device-major
        scattered ``(padded_total,)`` vectors — chunk-major on the flat
        wire, where the orders coincide) -> the tree form
        ``tx.init(params)`` would produce — the one checkpoint format,
        readable by sharded and unsharded runs alike, whichever wire
        wrote it. Padding slots carry zeros (zero grads keep zero
        moments), so the conversion is lossless."""
        return jax.tree.map(
            lambda l: self.layout.unflatten_np(
                self.layout.from_device_scattered_np(np.asarray(l)))
            if self._is_moment(l) else l, flat_state)

    def opt_tree_to_flat(self, tree_state, flat_template):
        """Inverse of :meth:`opt_flat_to_tree`. ``flat_template`` is
        ``tx.init(flat_params)`` — its structure tells which positions are
        flattened moments vs pass-through scalars."""
        return jax.tree.map(
            lambda tmpl, node: self.layout.to_device_scattered_np(
                self.layout.flatten_np(node))
            if self._is_moment(tmpl) else node,
            flat_template, tree_state)
