"""Comms plane: bucketed gradient reduce-scatter, cross-replica sharded
weight update (ZeRO-1), and a quantized allreduce wire.

The data-parallel train step's gradient exchange is the one collective the
whole platform stands on (the reference pushed it through the Spark block
manager; here it rides ICI/DCN). This module makes that exchange an explicit,
tunable plane instead of whatever GSPMD happens to emit:

* **Bucketing** — the grad pytree is flattened, in deterministic leaf order,
  into contiguous fixed-size buckets (``ZOO_GRAD_BUCKET_MB``), so a model
  with hundreds of small leaves rides a handful of large collectives instead
  of one per leaf. The allreduce is decomposed as reduce-scatter +
  all-gather (bit-identical to ``pmean`` per element — each element is the
  same N-replica sum either way), which is also what makes ZeRO-1 free.

* **Sharded weight update (ZeRO-1)** — after the reduce-scatter each replica
  already holds 1/N of the summed gradient, so it keeps only 1/N of the
  optimizer state, applies the (elementwise) optax update to its parameter
  shard, and all-gathers the updated parameters. Optimizer HBM per replica
  shrinks by the dp degree; the update itself is bit-identical to the
  unsharded one ("Automatic Cross-Replica Sharding of Weight Update in
  Data-Parallel Training", arXiv:2004.13336).

* **Quantized wire** — block-scaled bf16/int8 gradient compression with an
  error-feedback residual (EQuARX, arXiv:2506.17615; EF-SGD): each step the
  residual of the previous step's quantization is added back before
  quantizing, so the compression error is corrected over time instead of
  accumulating. bf16 genuinely rides the collective; int8 is simulated-wire
  on this jax (values are dequantized before the reduce because XLA exposes
  no int8-accumulating allreduce) — byte accounting reports what a native
  int8 wire would move.

* **Overlapped backward–comms pipeline** (``ZOO_COMMS_OVERLAP``) — the
  bucketed wire above still assembles ONE padded flat vector from every
  grad leaf before the first reduce-scatter can launch: that concatenate
  is a synchronization barrier, so wire time adds to — instead of hides
  behind — backward compute (Horovod's tensor-fusion lesson,
  arXiv:1802.05799). In overlapped mode a :class:`SegmentPlan` stages the
  gradient wire into bucket-aligned segments assembled straight from the
  leaf slices that compose each bucket, so bucket k's reduce-scatter
  depends only on its own leaves' gradients — the moment reverse AD has
  produced them, the collective is schedulable while later segments (the
  earlier layers' backward) keep computing. XLA's latency-hiding
  scheduler needs exactly that dependence freedom to issue the async
  start early and sink the done; on the CPU-sim mesh the program is
  sequential, so the win is asserted structurally (per-bucket dependency
  cones, launch counts, byte-identical wire) and measured on hardware.
  ``ZOO_COMMS_SEGMENTS`` coarsens the pipeline: buckets grouped into N
  dependency islands (1 = the classic post-backward wire, the default 0 =
  one segment per bucket = maximum overlap). Values on the wire are the
  exact same elements in the exact same order as the flat-vector path, so
  the plane's bit-identity contract extends: flat == bucketed == sharded
  == overlapped, and total wire bytes are byte-for-byte unchanged.

Numerics contract (asserted by tests/test_comms_plane.py): within the comms
plane, bucketed == flat-psum bit-exactly and sharded == unsharded bit-exactly
on an f32 mesh. The plane itself is *opt-in*: with it off, the engine's
default GSPMD step is byte-for-byte the pre-plane program. (The explicit
shard_map step and GSPMD's auto-partitioned step differ at the last-ulp
level because GSPMD may re-associate backward matmul reductions — that is
a property of turning the plane on, not of any knob inside it.)
"""

from __future__ import annotations

import hashlib
import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import collective as C

__all__ = ["CommsConfig", "BucketLayout", "CommsPlan", "SegmentPlan",
           "build_layout"]

WIRE_DTYPES = ("f32", "bf16", "int8")
_WIRE_BYTES = {"f32": 4, "bf16": 2, "int8": 1}


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class CommsConfig:
    """Knobs of the comms plane. ``active`` is False in the all-default
    state — the engine then keeps its pre-plane GSPMD step untouched.

    bucket_mb    — target bucket size in MiB (``ZOO_GRAD_BUCKET_MB``).
                   0 = per-leaf flat psum (the reference wire, one
                   collective per grad leaf).
    sharded_update — ZeRO-1 cross-replica sharded optimizer update
                   (``ZOO_SHARDED_UPDATE`` / ``TPUEstimator(sharded_update=)``).
    wire_dtype   — "f32" (exact, default) | "bf16" | "int8"
                   (``ZOO_ALLREDUCE_DTYPE``); non-f32 enables the
                   error-feedback residual.
    block        — elements per int8 scale block (``ZOO_ALLREDUCE_BLOCK``).
    axis         — the data-parallel mesh axis the plane reduces over.
    explicit     — turn the plane on with every other knob at default
                   (config ``comms_plane`` / ``ZOO_COMMS_PLANE``): the
                   flat-psum reference wire, one collective per grad leaf.
                   This is the baseline bench_comms compares buckets
                   against.
    overlap      — overlapped backward–comms pipeline (``ZOO_COMMS_OVERLAP``
                   / config ``comms_overlap``): assemble each bucket
                   straight from its own leaf slices so its reduce-scatter
                   launches as soon as those gradients exist, instead of
                   behind a whole-tree flatten barrier.
    segments     — dependency-island override for the overlapped pipeline
                   (``ZOO_COMMS_SEGMENTS`` / config ``comms_segments``):
                   0 = one segment per bucket (maximum overlap), 1 = a
                   single segment (the classic post-backward wire shape),
                   N = buckets coalesced into N contiguous groups.
    """

    bucket_mb: float = 0.0
    sharded_update: bool = False
    wire_dtype: str = "f32"
    block: int = 256
    axis: str = "dp"
    explicit: bool = False
    overlap: bool = False
    segments: int = 0

    DEFAULT_BUCKET_MB = 4.0

    def __post_init__(self):
        if self.wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"allreduce wire dtype {self.wire_dtype!r} not in "
                f"{WIRE_DTYPES}")
        if self.bucket_mb < 0:
            raise ValueError("grad_bucket_mb must be >= 0")
        if self.block < 1:
            raise ValueError("allreduce block must be >= 1")
        if self.segments < 0:
            raise ValueError("comms_segments must be >= 0")

    @property
    def active(self) -> bool:
        return (self.sharded_update or self.bucket_mb > 0
                or self.wire_dtype != "f32" or self.explicit
                or self.overlap)

    @property
    def quantized(self) -> bool:
        return self.wire_dtype != "f32"

    @property
    def effective_bucket_mb(self) -> float:
        """Quantization and the sharded update both work bucket-wise, so an
        unset bucket size resolves to the default when either is on."""
        if self.bucket_mb > 0:
            return self.bucket_mb
        if self.sharded_update or self.quantized or self.overlap:
            return self.DEFAULT_BUCKET_MB
        return 0.0

    def fingerprint(self) -> str:
        """Stable string for the compile plane's structural key — two
        engines whose comms knobs differ must never share an executable.
        The overlap flag and segment override are program shape (where the
        reduce-scatters sit in the dependence graph), so they salt the key
        exactly like the bucket layout does."""
        return (f"comms:bucket_mb={self.effective_bucket_mb}:"
                f"sharded={int(self.sharded_update)}:"
                f"wire={self.wire_dtype}:block={self.block}:"
                f"axis={self.axis}:overlap={int(self.overlap)}:"
                f"segments={self.segments}")

    @classmethod
    def resolve(cls, config: Optional[Dict] = None,
                sharded_update: Optional[bool] = None) -> "CommsConfig":
        """Resolve knobs: explicit argument > config dict > environment >
        default. Returns the inactive config when nothing is set."""
        cfg = config or {}

        def _env(name, default=None):
            v = os.environ.get(name, "")
            return v if v != "" else default

        if sharded_update is None:
            raw = cfg.get("sharded_update", _env("ZOO_SHARDED_UPDATE"))
            sharded_update = str(raw).lower() in ("1", "true", "yes", "on") \
                if raw is not None else False
        bucket_mb = float(cfg.get("grad_bucket_mb",
                                  _env("ZOO_GRAD_BUCKET_MB", 0.0)))
        wire = str(cfg.get("allreduce_dtype",
                           _env("ZOO_ALLREDUCE_DTYPE", "f32"))).lower()
        wire = {"float32": "f32", "bfloat16": "bf16"}.get(wire, wire)
        block = int(cfg.get("allreduce_block",
                            _env("ZOO_ALLREDUCE_BLOCK", 256)))
        raw_exp = cfg.get("comms_plane", _env("ZOO_COMMS_PLANE"))
        explicit = str(raw_exp).lower() in ("1", "true", "yes", "on") \
            if raw_exp is not None else False
        raw_ov = cfg.get("comms_overlap", _env("ZOO_COMMS_OVERLAP"))
        overlap = str(raw_ov).lower() in ("1", "true", "yes", "on") \
            if raw_ov is not None else False
        segments = int(cfg.get("comms_segments",
                               _env("ZOO_COMMS_SEGMENTS", 0)))
        return cls(bucket_mb=bucket_mb, sharded_update=bool(sharded_update),
                   wire_dtype=wire, block=block, explicit=explicit,
                   overlap=overlap, segments=segments)


# ---------------------------------------------------------------------------
# bucket layout
# ---------------------------------------------------------------------------
@dataclass
class BucketLayout:
    """Static placement of a grad/param pytree inside a padded flat f32
    vector, plus its bucket boundaries and per-replica shard mapping.

    Leaf order is ``jax.tree_util.tree_flatten`` order — deterministic for
    a given tree structure (dict keys sort), and the SAME order every
    flatten/unflatten call uses, so assembly/disassembly round-trips
    bit-exactly.

    Two element orders exist:

    * **flat order** — leaves concatenated, zero-padded to ``padded_total``.
    * **scattered order** — replica-major: replica i's reduce-scatter output
      (its chunk of every bucket, concatenated) is the contiguous slice
      ``[i*shard_size, (i+1)*shard_size)``. Sharded optimizer state is
      stored in this order so a plain ``P(axis)`` NamedSharding puts each
      replica's 1/N on its own chip.
    """

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]
    sizes: Tuple[int, ...]
    n_dev: int
    bucket_sizes: Tuple[int, ...]
    total: int
    padded_total: int
    shard_size: int
    wire_dtype: str = "f32"
    block: int = 256

    # -- construction --------------------------------------------------------
    @staticmethod
    def build(tree, n_dev: int, bucket_mb: float,
              wire_dtype: str = "f32", block: int = 256) -> "BucketLayout":
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if not leaves:
            raise ValueError("comms plane: empty parameter tree")
        # metadata only — leaf .dtype/.shape, never np.asarray (which
        # would D2H-copy every on-device param just to read its header)
        def _dtype(l):
            dt = getattr(l, "dtype", None)
            return np.dtype(dt) if dt is not None else np.result_type(l)
        for l in leaves:
            # every contract the plane promises (flat==bucketed==sharded
            # bit-identity, lossless sharded opt-state round-trip, the EF
            # residual algebra) is stated — and tested — for f32 params;
            # a bf16/f16 leaf would silently truncate moments through the
            # f32 flat vector and break the bit-identity the tests gate on
            if _dtype(l) != np.dtype(np.float32):
                raise ValueError(
                    "comms plane: param/grad leaf of dtype "
                    f"{_dtype(l)} cannot ride the f32 wire (the plane's "
                    "bit-identity and sharded-checkpoint contracts are "
                    "f32-only; keep the plane off for non-f32 params)")
        shapes = tuple(tuple(int(d) for d in np.shape(l)) for l in leaves)
        dtypes = tuple(str(_dtype(l)) for l in leaves)
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        total = sum(sizes)
        # every bucket must split evenly over the axis (tiled reduce-scatter)
        # and, for int8, into whole scale blocks
        align = n_dev if wire_dtype != "int8" else \
            (n_dev * block) // math.gcd(n_dev, block)
        if bucket_mb and bucket_mb > 0:
            target = max(int(bucket_mb * (1 << 20)) // 4, align)
            b = (target // align) * align or align
            n_full = total // b
            rem = total - n_full * b
            bucket_sizes = [b] * n_full
            if rem or not bucket_sizes:
                bucket_sizes.append(-(-rem // align) * align or align)
        else:
            # no bucketing: one bucket spanning the whole vector (used by
            # the sharded update's shard mapping; the flat-psum wire never
            # touches buckets)
            bucket_sizes = [-(-total // align) * align]
        padded_total = sum(bucket_sizes)
        return BucketLayout(
            treedef=treedef, shapes=shapes, dtypes=dtypes, sizes=sizes,
            n_dev=int(n_dev), bucket_sizes=tuple(bucket_sizes), total=total,
            padded_total=padded_total,
            shard_size=padded_total // int(n_dev),
            wire_dtype=wire_dtype, block=int(block))

    def signature(self) -> str:
        """Content hash of everything that changes the step's program or
        the checkpointed sharded-state layout."""
        h = hashlib.sha256(repr((
            self.shapes, self.dtypes, self.n_dev, self.bucket_sizes,
            self.wire_dtype, self.block)).encode())
        return h.hexdigest()[:16]

    # -- flat order ----------------------------------------------------------
    def flatten(self, tree):
        """Pytree -> padded flat f32 vector (bit-exact per element)."""
        leaves = jax.tree_util.tree_leaves(tree)
        flat = jnp.concatenate(
            [l.reshape(-1).astype(jnp.float32) for l in leaves])
        return jnp.pad(flat, (0, self.padded_total - self.total))

    def unflatten(self, flat):
        """Padded flat vector -> pytree (inverse of :meth:`flatten`)."""
        out, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            out.append(flat[off:off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def flatten_np(self, tree) -> np.ndarray:
        leaves = jax.tree_util.tree_leaves(tree)
        flat = np.concatenate(
            [np.asarray(l).reshape(-1).astype(np.float32) for l in leaves])
        return np.pad(flat, (0, self.padded_total - self.total))

    def unflatten_np(self, flat: np.ndarray):
        out, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            out.append(np.asarray(flat[off:off + size]).reshape(shape)
                       .astype(dtype))
            off += size
        return jax.tree_util.tree_unflatten(self.treedef, out)

    # -- buckets -------------------------------------------------------------
    def buckets(self, flat) -> List:
        out, off = [], 0
        for b in self.bucket_sizes:
            out.append(flat[off:off + b])
            off += b
        return out

    def unbuckets(self, buckets: List):
        return jnp.concatenate(buckets)

    # -- scattered (replica-major) order -------------------------------------
    def to_scattered(self, flat):
        """Flat order -> scattered order: replica i's chunk of every bucket
        becomes the contiguous slice ``[i*shard_size, (i+1)*shard_size)``."""
        cols = [b.reshape(self.n_dev, -1) for b in self.buckets(flat)]
        return jnp.concatenate(cols, axis=1).reshape(-1)

    def from_scattered(self, scat):
        rows = scat.reshape(self.n_dev, self.shard_size)
        out, off = [], 0
        for b in self.bucket_sizes:
            chunk = b // self.n_dev
            out.append(rows[:, off:off + chunk].reshape(-1))
            off += chunk
        return jnp.concatenate(out)

    def to_scattered_np(self, flat: np.ndarray) -> np.ndarray:
        cols, off = [], 0
        for b in self.bucket_sizes:
            cols.append(np.asarray(flat[off:off + b]).reshape(self.n_dev, -1))
            off += b
        return np.concatenate(cols, axis=1).reshape(-1)

    def from_scattered_np(self, scat: np.ndarray) -> np.ndarray:
        rows = np.asarray(scat).reshape(self.n_dev, self.shard_size)
        out, off = [], 0
        for b in self.bucket_sizes:
            chunk = b // self.n_dev
            out.append(rows[:, off:off + chunk].reshape(-1))
            off += chunk
        return np.concatenate(out)

    # -- wire accounting -----------------------------------------------------
    def wire_bytes_per_step(self) -> int:
        """Gradient bytes one replica puts on the wire per step (the
        reduce-scatter leg; the param all-gather is accounted separately).
        int8 includes its per-block f32 scales."""
        per_elem = _WIRE_BYTES[self.wire_dtype]
        n = self.padded_total * per_elem
        if self.wire_dtype == "int8":
            n += (self.padded_total // self.block) * 4
        return n

    def grad_bytes_f32(self) -> int:
        return self.total * 4


def build_layout(tree, n_dev: int, cfg: CommsConfig) -> BucketLayout:
    return BucketLayout.build(tree, n_dev, cfg.effective_bucket_mb,
                              wire_dtype=cfg.wire_dtype, block=cfg.block)


# ---------------------------------------------------------------------------
# segment plan — the overlapped pipeline's dependence structure
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class LeafPiece:
    """One contiguous run of a leaf's flattened elements inside a bucket."""

    leaf: int       # index into the layout's tree_flatten leaf order
    start: int      # first element of the leaf (flat view) in this piece
    stop: int       # one past the last element


@dataclass(frozen=True)
class SegmentPlan:
    """Bucket-aligned staging of the gradient wire for the overlapped
    backward–comms pipeline.

    The classic bucketed path pads-and-concatenates EVERY grad leaf into
    one flat vector and slices buckets out of it — so in the lowered
    program every bucket's reduce-scatter transitively depends on every
    leaf, and no collective can issue until the whole backward pass has
    finished. This plan records, per bucket, exactly which leaf slices
    compose it (:class:`LeafPiece` runs, plus trailing zero padding on the
    final bucket only), and groups buckets into contiguous *segments* —
    independent dependency islands. :meth:`bucket_values` assembles each
    segment straight from its own leaves, so bucket k's reduce-scatter is
    schedulable the moment reverse AD has produced leaves
    ``pieces[k]`` — while the remaining segments' backward still runs.

    Element order inside every bucket is identical to
    ``layout.buckets(layout.flatten(tree))`` — same values, same order,
    bit for bit — only the dependence structure changes. ``n_segments``:
    0 = one segment per bucket (maximum overlap, the default), 1 = one
    segment spanning everything (the classic post-backward shape), N =
    buckets coalesced into N contiguous groups.
    """

    bucket_pieces: Tuple[Tuple[LeafPiece, ...], ...]
    bucket_pad: Tuple[int, ...]          # trailing zeros per bucket
    segments: Tuple[Tuple[int, ...], ...]  # bucket indices per segment
    bucket_sizes: Tuple[int, ...]

    @staticmethod
    def build(layout: "BucketLayout",
              n_segments: int = 0) -> "SegmentPlan":
        pieces: List[Tuple[LeafPiece, ...]] = []
        pads: List[int] = []
        leaf, off = 0, 0                 # cursor into the flat leaf order
        for b in layout.bucket_sizes:
            need, got = b, []
            while need > 0 and leaf < len(layout.sizes):
                take = min(need, layout.sizes[leaf] - off)
                got.append(LeafPiece(leaf, off, off + take))
                off += take
                need -= take
                if off == layout.sizes[leaf]:
                    leaf, off = leaf + 1, 0
            pieces.append(tuple(got))
            pads.append(need)            # only the tail bucket pads
        if n_segments <= 0 or n_segments >= len(layout.bucket_sizes):
            groups = tuple((k,) for k in range(len(layout.bucket_sizes)))
        else:
            # contiguous groups, balanced by bucket count (bucket sizes are
            # already uniform apart from the tail)
            n_b = len(layout.bucket_sizes)
            bounds = [round(i * n_b / n_segments)
                      for i in range(n_segments + 1)]
            groups = tuple(tuple(range(lo, hi))
                           for lo, hi in zip(bounds, bounds[1:]) if hi > lo)
        return SegmentPlan(bucket_pieces=tuple(pieces),
                           bucket_pad=tuple(pads), segments=groups,
                           bucket_sizes=tuple(layout.bucket_sizes))

    @property
    def n_segments(self) -> int:
        return len(self.segments)

    def _assemble(self, leaves: List, seg: Tuple[int, ...], np_mod):
        """Concatenate one segment's leaf pieces (+ tail padding)."""
        parts = []
        for k in seg:
            for p in self.bucket_pieces[k]:
                flat = leaves[p.leaf].reshape(-1)
                parts.append(flat[p.start:p.stop])
            if self.bucket_pad[k]:
                parts.append(np_mod.zeros((self.bucket_pad[k],),
                                          np_mod.float32))
        return parts[0] if len(parts) == 1 else np_mod.concatenate(parts)

    def bucket_values(self, grads) -> List:
        """Grad pytree -> per-bucket f32 vectors, assembled segment-wise so
        each bucket's dependence cone is exactly its own leaves. Bit-exact
        to ``layout.buckets(layout.flatten(grads))``."""
        leaves = [l.reshape(-1).astype(jnp.float32)
                  for l in jax.tree_util.tree_leaves(grads)]
        out: List = [None] * len(self.bucket_sizes)
        for seg in self.segments:
            seg_flat = self._assemble(leaves, seg, jnp)
            if len(seg) == 1:
                out[seg[0]] = seg_flat
            else:
                o = 0
                for k in seg:
                    out[k] = seg_flat[o:o + self.bucket_sizes[k]]
                    o += self.bucket_sizes[k]
        return out

    def bucket_values_np(self, grads) -> List[np.ndarray]:
        """Numpy host twin of :meth:`bucket_values` (tests, tooling)."""
        leaves = [np.asarray(l).reshape(-1).astype(np.float32)
                  for l in jax.tree_util.tree_leaves(grads)]
        out: List[np.ndarray] = [None] * len(self.bucket_sizes)
        for seg in self.segments:
            seg_flat = np.asarray(self._assemble(leaves, seg, np))
            o = 0
            for k in seg:
                out[k] = seg_flat[o:o + self.bucket_sizes[k]]
                o += self.bucket_sizes[k]
        return out


# ---------------------------------------------------------------------------
# quantized wire
# ---------------------------------------------------------------------------
def quantize_wire(x, wire_dtype: str, block: int):
    """Quantize one bucket for the wire; returns the dequantized f32 values
    the receiving side reconstructs (what actually enters the reduce).

    bf16: plain round-trip cast — this genuinely rides the collective as
    bf16 (the caller reduces the bf16 array). int8: symmetric per-block
    scales (max-abs / 127); dequantized before the reduce because XLA has
    no int8-accumulating allreduce — the byte accounting still reports the
    native int8 wire cost.
    """
    if wire_dtype == "f32":
        return x
    if wire_dtype == "bf16":
        return x.astype(jnp.bfloat16).astype(jnp.float32)
    blocks = x.reshape(-1, block)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(blocks / safe), -127, 127).astype(jnp.int8)
    return (q.astype(jnp.float32) * safe).reshape(x.shape)


# ---------------------------------------------------------------------------
# the plan — everything the traced step needs, all shapes static
# ---------------------------------------------------------------------------
class CommsPlan:
    """One engine's comms strategy: a :class:`CommsConfig` bound to the
    bucket layout of its parameter tree. The ``reduce_*`` methods run INSIDE
    ``shard_map`` (per-replica view); the ``opt_*``/``resid_*`` methods run
    on host arrays (checkpoint conversion)."""

    def __init__(self, cfg: CommsConfig, layout: BucketLayout):
        self.cfg = cfg
        self.layout = layout
        self.axis = cfg.axis
        # overlapped pipeline: the bucket-aligned segment plan that lets
        # each bucket's reduce-scatter depend only on its own leaves
        self.segplan: Optional[SegmentPlan] = (
            SegmentPlan.build(layout, cfg.segments) if cfg.overlap
            else None)

    # -- telemetry -----------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        lo, cfg = self.layout, self.cfg
        bucketed = cfg.effective_bucket_mb > 0
        if bucketed:
            # one reduce-scatter + one all-gather per bucket (the sharded
            # update folds the grad all-gather into the param all-gather)
            collectives = (2 * len(lo.bucket_sizes)
                           if not cfg.sharded_update
                           else len(lo.bucket_sizes) + 1)
        else:
            collectives = len(lo.sizes)      # one psum per grad leaf
        return {
            "sharded_update": cfg.sharded_update,
            "wire_dtype": cfg.wire_dtype,
            "bucket_mb": cfg.effective_bucket_mb,
            "buckets": len(lo.bucket_sizes) if bucketed else 0,
            "grad_leaves": len(lo.sizes),
            "collectives_per_step": collectives,
            "wire_bytes_per_step": lo.wire_bytes_per_step(),
            "grad_bytes_f32": lo.grad_bytes_f32(),
            "opt_shard_elems": lo.shard_size,
            "opt_full_elems": lo.padded_total,
            "overlap": cfg.overlap,
            "segments": self.segplan.n_segments if self.segplan else 0,
        }

    # -- in-step collectives (per-replica view) ------------------------------
    def reduce_leafwise_mean(self, grads):
        """Flat-psum reference wire: one pmean per grad leaf."""
        return jax.tree.map(lambda g: lax.pmean(g, self.axis), grads)

    def reduce_scatter_bucket_list(self, bucket_vals):
        """Quantize (optional) + reduce-scatter every bucket of an
        already assembled bucket list. Returns (list of per-bucket summed
        f32 shards, list of f32 wire values as the receiver reconstructs
        them) — the wire values feed the caller's error-feedback
        residual. The caller chooses the assembly: ``layout.buckets``
        slices of the whole-tree flat vector (classic), or
        :meth:`SegmentPlan.bucket_values` (overlapped — each launch keeps
        its own dependence cone).

        bf16 REALLY rides the collective: the reduce-scatter operand is
        bf16, so each element moves 2 bytes on ICI/DCN. Note the EF
        residual feeds back only this replica's LOCAL f32->bf16 cast
        error (``bucket - wire``); rounding introduced inside the bf16
        reduction's accumulation is not observable per replica and is NOT
        corrected — at large dp degrees, where accumulation error can
        dominate cast error, expect drift beyond the cast-error bound.
        int8 has no accumulating allreduce in XLA, so its values are
        dequantized before an f32 reduce and only the byte accounting
        reflects the native int8 cost."""
        shards, wires = [], []
        for bucket in bucket_vals:
            if self.cfg.wire_dtype == "bf16":
                wire16 = bucket.astype(jnp.bfloat16)
                shards.append(C.reduce_scatter(wire16, self.axis)
                              .astype(jnp.float32))
                wires.append(wire16.astype(jnp.float32))
            else:
                wire = quantize_wire(bucket, self.cfg.wire_dtype,
                                     self.cfg.block)
                shards.append(C.reduce_scatter(wire, self.axis))
                wires.append(wire)
        return shards, wires

    def gather_buckets(self, shards) -> Any:
        """Per-bucket summed shards -> full flat summed vector."""
        return self.layout.unbuckets(
            [C.all_gather(s, self.axis) for s in shards])

    def shard_of(self, flat, index):
        """This replica's scattered-order slice of a flat-order vector.

        Scattered row ``i`` is by construction the concatenation of each
        bucket's i-th chunk, so the shard is sliced per bucket directly
        from the flat vector — never materializing the full
        ``(padded_total,)`` scattered intermediate on every replica (a
        param-sized transient per step that XLA cannot fold away because
        ``index`` is traced)."""
        lo = self.layout
        chunks, off = [], 0
        for b in lo.bucket_sizes:
            chunk = b // lo.n_dev
            chunks.append(lax.dynamic_slice(
                flat, (off + index * chunk,), (chunk,)))
            off += b
        return jnp.concatenate(chunks) if len(chunks) > 1 else chunks[0]

    def unscatter(self, gathered_scat):
        """All-gathered scattered-order vector -> flat order."""
        return self.layout.from_scattered(gathered_scat)

    # -- sharded optimizer state conversion (host side) ----------------------
    def _is_moment(self, leaf) -> bool:
        return (getattr(leaf, "ndim", None) == 1
                and leaf.shape[0] == self.layout.padded_total)

    def opt_flat_to_tree(self, flat_state):
        """Sharded-run optimizer state (moment leaves are scattered-order
        ``(padded_total,)`` vectors) -> the tree form ``tx.init(params)``
        would produce — the one checkpoint format, readable by sharded and
        unsharded runs alike. Padding slots carry zeros (zero grads keep
        zero moments), so the conversion is lossless."""
        return jax.tree.map(
            lambda l: self.layout.unflatten_np(
                self.layout.from_scattered_np(np.asarray(l)))
            if self._is_moment(l) else l, flat_state)

    def opt_tree_to_flat(self, tree_state, flat_template):
        """Inverse of :meth:`opt_flat_to_tree`. ``flat_template`` is
        ``tx.init(flat_params)`` — its structure tells which positions are
        flattened moments vs pass-through scalars."""
        return jax.tree.map(
            lambda tmpl, node: self.layout.to_scattered_np(
                self.layout.flatten_np(node))
            if self._is_moment(tmpl) else node,
            flat_template, tree_state)
