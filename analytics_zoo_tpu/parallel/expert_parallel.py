"""Expert parallelism: Switch-style top-1 MoE with all-to-all dispatch
over an ``ep`` mesh axis.

Beyond-parity axis (the reference is data-parallel only, SURVEY §2.3).
The GShard/Switch recipe, TPU-native: tokens are data-sharded over
``ep``; a replicated router picks one expert per token; each rank packs
its tokens into an (E, C, d) capacity buffer, one ``lax.all_to_all``
rotates expert-major buffers so each rank receives exactly the tokens
routed to ITS expert, the local expert FFN runs on them, and a second
``all_to_all`` returns outputs to their source ranks where the gate
probability scales them. Tokens beyond an expert's capacity C are
dropped (standard Switch behaviour) — with ``capacity_factor`` high
enough nothing drops and the layer equals the dense
gather-per-token-through-its-expert computation exactly
(tests/test_expert_parallel.py).

Everything is differentiable: the router trains through the gate
scaling, experts through the dispatched tokens; the Switch load-balance
auxiliary loss is returned alongside the output.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map                # jax >= 0.8


def stack_expert_params(per_expert) -> Any:
    """[expert_pytree, ...] -> one pytree with a leading expert axis."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *per_expert)


def expert_sharding(mesh: Mesh, stacked: Any, axis: str = "ep") -> Any:
    return jax.tree_util.tree_map(
        lambda l: NamedSharding(mesh, P(axis, *([None] * (l.ndim - 1)))),
        stacked)


def moe_apply(expert_fn: Callable, expert_params: Any,
              router_weights: jax.Array, x: jax.Array, *, mesh: Mesh,
              capacity_factor: float = 1.25,
              axis: str = "ep") -> Tuple[jax.Array, jax.Array]:
    """Top-1 (Switch) mixture of experts.

    expert_fn(params_one_expert, tokens) -> tokens (shape-preserving);
    expert_params: stacked with leading axis E == mesh.shape[axis];
    router_weights: (d, E), replicated; x: (N, d) with N % ep == 0,
    sharded (or shardable) over ``axis`` on dim 0.

    Returns (y, aux_loss): y (N, d); aux_loss is the Switch load-balance
    term (E * sum_e fraction_e * mean_prob_e), which is 1.0 at perfect
    balance — add ``alpha * aux_loss`` to the training loss.
    """
    e_count = mesh.shape[axis]
    leading = {l.shape[0]
               for l in jax.tree_util.tree_leaves(expert_params)}
    if leading != {e_count}:
        raise ValueError(
            f"stacked expert params' leading axis {sorted(leading)} must "
            f"equal the '{axis}' mesh axis size {e_count}")
    if router_weights.shape[-1] != e_count:
        raise ValueError(
            f"router_weights last dim {router_weights.shape[-1]} must "
            f"equal the '{axis}' mesh axis size {e_count} (one logit per "
            "expert)")
    n, d = x.shape
    if n % e_count:
        raise ValueError(f"token count {n} not divisible by ep={e_count}")
    local_n = n // e_count
    capacity = max(1, int(math.ceil(
        capacity_factor * local_n / e_count)))

    def ep_body(params, router_w, x_local):
        params = jax.tree_util.tree_map(lambda l: l[0], params)

        logits = x_local @ router_w                     # (ln, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)         # (ln,)
        gate = jnp.take_along_axis(probs, expert_idx[:, None],
                                   axis=-1)[:, 0]       # (ln,)

        # position of each token within its expert's capacity buffer
        onehot = jax.nn.one_hot(expert_idx, e_count, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) * onehot       # 1-based ranks
        pos = jnp.sum(pos, axis=-1) - 1                 # (ln,) 0-based
        keep = pos < capacity                           # overflow drops

        # scatter tokens into the (E, C, d) dispatch buffer
        buf = jnp.zeros((e_count, capacity, d), x_local.dtype)
        buf = buf.at[expert_idx, jnp.clip(pos, 0, capacity - 1)].add(
            jnp.where(keep[:, None], x_local, 0.0))

        # exchange: expert-major -> source-rank-major on the owning rank
        recv = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                              tiled=True)               # (ep*C, d) groups
        recv = recv.reshape(e_count * capacity, d)
        out = expert_fn(params, recv)                   # local expert
        out = out.reshape(e_count, capacity, d)
        back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                              tiled=True)               # (E, C, d) home

        # gather each surviving token's output; dropped tokens pass
        # through as zeros (standard Switch residual handles them)
        y = back[expert_idx, jnp.clip(pos, 0, capacity - 1)]
        y = jnp.where(keep[:, None], y * gate[:, None], 0.0)

        # Switch load-balance aux: fraction of tokens per expert x mean
        # router prob per expert, both averaged GLOBALLY over ep
        frac = lax.pmean(jnp.mean(
            jax.nn.one_hot(expert_idx, e_count, dtype=x_local.dtype),
            axis=0), axis)
        mean_p = lax.pmean(jnp.mean(probs, axis=0), axis)
        aux = e_count * jnp.sum(frac * mean_p)
        return y, aux

    param_specs = jax.tree_util.tree_map(
        lambda l: P(axis, *([None] * (l.ndim - 1))), expert_params)
    fn = shard_map(ep_body, mesh=mesh,
                   in_specs=(param_specs, P(), P(axis)),
                   out_specs=(P(axis), P()),
                   check_vma=False)
    y, aux = fn(expert_params, router_weights, x)
    return y, aux
