"""Expert parallelism: Switch/GShard-style MoE with all-to-all dispatch
over an ``ep`` mesh axis.

Beyond-parity axis (the reference is data-parallel only, SURVEY §2.3).
The GShard/Switch recipe, TPU-native: tokens are data-sharded over
``ep``; a replicated router picks ``top_k`` experts per token from E
total experts (E = m × ep, m experts resident per rank); each rank packs
its token-choices into an (E, C, d) capacity buffer, one
``lax.all_to_all`` rotates expert-major buffers so each rank receives
exactly the tokens routed to ITS m experts, the local experts run
(vmapped over m), and a second ``all_to_all`` returns outputs to their
source ranks where the gate probabilities scale them. Tokens beyond an
expert's capacity C are dropped (standard Switch behaviour) — with
``capacity_factor`` high enough nothing drops and the layer equals the
dense gather-per-token-through-its-experts computation exactly
(tests/test_expert_parallel.py).

Routing: ``top_k=1`` is Switch (gate = raw top-1 prob); ``top_k=2`` is
GShard-style with the chosen experts' gates renormalized to sum to 1.

Everything is differentiable: the router trains through the gate
scaling, experts through the dispatched tokens; the Switch load-balance
auxiliary loss (over first-choice assignments) is returned alongside the
output.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._compat import shard_map           # jax-version-tolerant facade


def stack_expert_params(per_expert) -> Any:
    """[expert_pytree, ...] -> one pytree with a leading expert axis."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *per_expert)


def expert_sharding(mesh: Mesh, stacked: Any, axis: str = "ep") -> Any:
    return jax.tree_util.tree_map(
        lambda l: NamedSharding(mesh, P(axis, *([None] * (l.ndim - 1)))),
        stacked)


def moe_apply(expert_fn: Callable, expert_params: Any,
              router_weights: jax.Array, x: jax.Array, *, mesh: Mesh,
              capacity_factor: float = 1.25, top_k: int = 1,
              axis: str = "ep") -> Tuple[jax.Array, jax.Array]:
    """Top-k mixture of experts over ``ep``.

    expert_fn(params_one_expert, tokens) -> tokens (shape-preserving);
    expert_params: stacked with leading axis E, where E is a multiple of
    mesh.shape[axis] (E // ep experts live on each rank — contiguous
    blocks, matching ``expert_sharding``'s leading-axis layout);
    router_weights: (d, E), replicated; x: (N, d) with N % ep == 0,
    sharded (or shardable) over ``axis`` on dim 0; top_k in (1, 2).

    Returns (y, aux_loss): y (N, d); aux_loss is the Switch load-balance
    term over first-choice assignments (E * sum_e fraction_e *
    mean_prob_e), which is 1.0 at perfect balance — add
    ``alpha * aux_loss`` to the training loss.
    """
    ep = mesh.shape[axis]
    leading = {l.shape[0]
               for l in jax.tree_util.tree_leaves(expert_params)}
    if len(leading) != 1:
        raise ValueError(
            f"stacked expert params disagree on the expert axis: {leading}")
    e_count = leading.pop()
    if e_count % ep:
        raise ValueError(
            f"expert count {e_count} must be a multiple of the '{axis}' "
            f"mesh axis size {ep}")
    m = e_count // ep                       # experts per rank
    if router_weights.shape[-1] != e_count:
        raise ValueError(
            f"router_weights last dim {router_weights.shape[-1]} must "
            f"equal the expert count {e_count} (one logit per expert)")
    if top_k not in (1, 2):
        raise ValueError(f"top_k must be 1 (Switch) or 2 (GShard), "
                         f"got {top_k}")
    if top_k > e_count:
        raise ValueError(f"top_k={top_k} with only {e_count} experts")
    n, d = x.shape
    if n % ep:
        raise ValueError(f"token count {n} not divisible by ep={ep}")
    local_n = n // ep
    # expected tokens per expert = top_k * local_n * ep / E = top_k *
    # local_n / m per rank-expert... capacity is per (expert, source rank)
    capacity = max(1, int(math.ceil(
        capacity_factor * top_k * local_n / e_count)))

    def ep_body(params, router_w, x_local):
        # this rank's m experts (contiguous leading slice)
        logits = x_local @ router_w                     # (ln, E)
        probs = jax.nn.softmax(logits, axis=-1)

        if top_k == 1:
            expert_idx = jnp.argmax(probs, axis=-1)[None]       # (1, ln)
            gates = jnp.take_along_axis(
                probs, expert_idx[0][:, None], axis=-1).T        # (1, ln)
        else:
            topv, topi = lax.top_k(probs, 2)            # (ln, 2)
            denom = jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
            gates = (topv / denom).T                    # (2, ln) renorm
            expert_idx = topi.T                         # (2, ln)

        # flatten the (choice, token) pairs into one virtual token stream
        # so capacity ranks are assigned jointly across choices
        flat_idx = expert_idx.reshape(-1)               # (k*ln,)
        flat_gate = gates.reshape(-1)
        onehot = jax.nn.one_hot(flat_idx, e_count, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) * onehot       # 1-based ranks
        pos = jnp.sum(pos, axis=-1) - 1                 # (k*ln,) 0-based
        keep = pos < capacity                           # overflow drops
        pos_c = jnp.clip(pos, 0, capacity - 1)

        # scatter token-choices into the (E, C, d) dispatch buffer
        xk = jnp.broadcast_to(x_local, (top_k,) + x_local.shape)
        xk = xk.reshape(-1, d)                          # (k*ln, d)
        buf = jnp.zeros((e_count, capacity, d), x_local.dtype)
        buf = buf.at[flat_idx, pos_c].add(
            jnp.where(keep[:, None], xk, 0.0))

        # exchange: expert-major -> source-rank-major on the owning rank.
        # buf (E, C, d) = (ep, m, C, d) groups; tiled all_to_all over dim0
        # hands rank r every other rank's (m, C, d) block for r's experts
        recv = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                              tiled=True)               # (ep*m, C, d)
        recv = recv.reshape(ep, m, capacity, d)
        recv = jnp.moveaxis(recv, 1, 0)                 # (m, ep, C, d)
        recv = recv.reshape(m, ep * capacity, d)
        out = jax.vmap(expert_fn)(params, recv)         # m local experts
        out = out.reshape(m, ep, capacity, d)
        out = jnp.moveaxis(out, 0, 1)                   # (ep, m, C, d)
        out = out.reshape(e_count, capacity, d)
        back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                              tiled=True)               # (E, C, d) home

        # gather each surviving token-choice's output, gate-scale, and
        # sum the k choices; dropped choices contribute zero (standard
        # Switch residual handles them)
        yk = back[flat_idx, pos_c]
        yk = jnp.where(keep[:, None], yk * flat_gate[:, None], 0.0)
        y = yk.reshape(top_k, -1, d).sum(0)             # (ln, d)

        # Switch load-balance aux over FIRST choices: fraction of tokens
        # per expert x mean router prob per expert, averaged GLOBALLY
        frac = lax.pmean(jnp.mean(
            jax.nn.one_hot(expert_idx[0], e_count, dtype=x_local.dtype),
            axis=0), axis)
        mean_p = lax.pmean(jnp.mean(probs, axis=0), axis)
        aux = e_count * jnp.sum(frac * mean_p)
        return y, aux

    param_specs = jax.tree_util.tree_map(
        lambda l: P(axis, *([None] * (l.ndim - 1))), expert_params)
    fn = shard_map(ep_body, mesh=mesh,
                   in_specs=(param_specs, P(), P(axis)),
                   out_specs=(P(axis), P()),
                   check_vma=False)
    y, aux = fn(expert_params, router_weights, x)
    return y, aux
