"""Device-mesh construction: the TPU-native replacement for the reference's
five communication backends (SURVEY.md §2.4; reference: Spark block-manager
AllReduce at zoo/.../pipeline/api/keras/models/Topology.scala:1203-1206, Gloo at
pyzoo/zoo/orca/learn/horovod/horovod_ray_runner.py:119, DDP-gloo at
pyzoo/zoo/orca/learn/pytorch/torch_runner.py:136-140).

One mesh, named axes, XLA collectives over ICI/DCN. Axis conventions:

* ``dp``   — data parallel (gradient psum rides ICI; across hosts, DCN)
* ``fsdp`` — parameter/optimizer sharding (ZeRO-style, all_gather/reduce_scatter)
* ``tp``   — tensor parallel (matmul sharding)
* ``sp``   — sequence/context parallel (ring attention / all-to-all)

Axes of size 1 are free; estimators default to pure DP but every train step is
jitted over the full mesh so tp/sp/fsdp can be enabled by config alone.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dp", "fsdp", "tp", "sp")
# optional axes appended to the mesh only when requested: pipeline stages
# (parallel/pipeline_parallel.py) and MoE experts (expert_parallel.py)
OPTIONAL_AXES = ("pp", "ep")


def resolve_axis_sizes(n_devices: int, axes: Dict[str, int]) -> Dict[str, int]:
    """Resolve ``-1`` wildcards so that the product of axis sizes == n_devices.

    At most one axis may be -1. Missing canonical axes get size 1;
    unknown axis names raise (a silently-dropped axis previously crashed
    later with an opaque reshape error).
    """
    unknown = set(axes) - set(AXIS_ORDER) - set(OPTIONAL_AXES)
    if unknown:
        raise ValueError(
            f"unknown mesh axes {sorted(unknown)} — known: "
            f"{AXIS_ORDER + OPTIONAL_AXES}")
    sizes = {a: int(axes.get(a, 1)) for a in AXIS_ORDER}
    for a in OPTIONAL_AXES:
        if a in axes:
            sizes[a] = int(axes[a])
    wild = [a for a, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {wild}")
    fixed = math.prod(v for v in sizes.values() if v != -1)
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"cannot fill axis {wild[0]}: {n_devices} devices not divisible "
                f"by fixed product {fixed}")
        sizes[wild[0]] = n_devices // fixed
    elif fixed != n_devices:
        raise ValueError(
            f"mesh axes {sizes} use {fixed} devices but {n_devices} available")
    return sizes


def create_mesh(axes: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named-axis Mesh over all (or given) devices.

    Uses ``mesh_utils.create_device_mesh`` when possible so the dp axis is
    laid out along ICI rings on real TPU topologies; falls back to a plain
    reshape for virtual/CPU devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = resolve_axis_sizes(len(devices), axes or {"dp": -1})
    # drop trailing size-1 axes? No — keep all four so PartitionSpecs are
    # stable; optional pp/ep axes append only when requested
    names = AXIS_ORDER + tuple(a for a in OPTIONAL_AXES if a in sizes)
    shape = tuple(sizes[a] for a in names)
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def data_sharding(mesh: Mesh, ndim: int, batch_axes: Tuple[str, ...] = ("dp", "fsdp")
                  ) -> NamedSharding:
    """Sharding for a host batch: leading dim split across dp (and fsdp, which
    acts as an extra data axis for activations when ZeRO-sharding params)."""
    axes: Tuple = (batch_axes,) + (None,) * (ndim - 1)
    return NamedSharding(mesh, P(*axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_mesh_devices(mesh: Mesh) -> List[jax.Device]:
    pid = jax.process_index()
    return [d for d in mesh.devices.flat if d.process_index == pid]


def mesh_axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def nontrivial_axes(mesh: Mesh, exclude: Tuple[str, ...] = ()
                    ) -> Tuple[str, ...]:
    """Mesh axes with size > 1, in mesh order — the axis-aware form the
    pure-dp guards check against (an error can then NAME the offending
    axes instead of just failing a boolean)."""
    return tuple(name for name, size in mesh.shape.items()
                 if size > 1 and name not in exclude)


def pure_dp(mesh: Mesh, axis: str = "dp") -> bool:
    """True when ``axis`` is the only non-trivial mesh axis — the regime
    the comms plane (parallel/comms.py) owns: params replicated, batch
    split over ``axis``, every collective explicit. Multi-axis (fsdp/tp)
    meshes belong to the sharding plane (parallel/sharding.py)."""
    return not nontrivial_axes(mesh, exclude=(axis,))


def parse_mesh_axes(spec: str) -> Dict[str, int]:
    """Parse a ``ZOO_MESH_AXES`` string — ``"dp=2,fsdp=2,tp=2"`` (one axis
    may be ``-1`` to absorb the remaining devices) — into the axes dict
    ``create_mesh``/``init_orca_context`` take. Validates axis names
    against the canonical + optional sets so a typo fails here, not as an
    opaque reshape error later."""
    axes: Dict[str, int] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"ZOO_MESH_AXES entry {part!r} is not name=size "
                "(expected e.g. 'dp=2,fsdp=2,tp=2')")
        name, _, size = part.partition("=")
        name = name.strip()
        if name not in AXIS_ORDER + OPTIONAL_AXES:
            raise ValueError(
                f"ZOO_MESH_AXES axis {name!r} unknown — known: "
                f"{AXIS_ORDER + OPTIONAL_AXES}")
        axes[name] = int(size)
    if not axes:
        raise ValueError(f"ZOO_MESH_AXES {spec!r} names no axes")
    return axes


def mesh_topology(mesh: Mesh) -> Dict[str, Any]:
    """Factor the mesh into its named axes plus the two-level (dcn, ici)
    split of the data axis — the one dict snapshots/benches record about
    device topology (extends ``dp_topology``, which factors only the dp
    axis, to the multi-axis meshes the sharding plane runs on)."""
    dcn, ici = dp_topology(mesh)
    return {"axes": {name: int(size) for name, size in mesh.shape.items()},
            "nontrivial": list(nontrivial_axes(mesh)),
            "n_devices": int(np.prod(list(mesh.shape.values()))),
            "dp_dcn": dcn, "dp_ici": ici}


def batch_divisor(mesh: Mesh) -> int:
    """Global batch must be a multiple of this (the TPU analogue of the
    reference's node_num*core_num rule, pyzoo/zoo/tfpark/tf_dataset.py:135-149)."""
    return mesh_axis_size(mesh, "dp") * mesh_axis_size(mesh, "fsdp")


def dp_topology(mesh: Mesh, axis: str = "dp",
                dcn_override: Optional[int] = None) -> Tuple[int, int]:
    """Factor the data-parallel axis into ``(dcn, ici)`` sub-axes — the
    two-level wire the hierarchical comms plane reduces over
    (parallel/comms.py): fast intra-host links (ICI) inside each group of
    ``ici`` consecutive devices, slow cross-host links (DCN) between the
    ``dcn`` groups.

    The factorization comes from device process locality: when the
    devices along ``axis`` are *process-contiguous* (every process
    contributes one equal-sized consecutive block — what
    ``mesh_utils``/multihost init produce for a pure-dp mesh), ``dcn`` is
    the process count and ``ici`` the per-process device count. A
    single-process mesh (the 8-device simulated CPU slice) has no real
    host boundary, so it factors ``(1, n)`` unless ``dcn_override``
    (``ZOO_COMMS_DCN_AXIS`` / config ``comms_dcn_axis``) imposes a
    simulated split — the knob the tier-1 mesh uses to stand in for a
    2-host pod.

    An interleaved device order (process boundaries not contiguous along
    ``axis``) cannot host the two-level wire — a "host group" would span
    DCN — so it deliberately degrades to ``(1, n)`` rather than build
    groups that are hierarchical in name only.
    """
    n = mesh_axis_size(mesh, axis)
    if dcn_override is not None and int(dcn_override) > 0:
        dcn = int(dcn_override)
        if n % dcn != 0:
            raise ValueError(
                f"comms_dcn_axis={dcn} does not divide the {axis} axis "
                f"size {n}")
        return dcn, n // dcn
    if axis not in mesh.shape:
        return 1, n
    # devices laid out along `axis`, everything else collapsed: for the
    # pure-dp meshes the comms plane owns, this is just the flat order
    axes = list(mesh.axis_names)
    dev = np.moveaxis(mesh.devices, axes.index(axis), 0)
    dev = dev.reshape(n, -1)
    procs = [getattr(d, "process_index", 0) for d in dev[:, 0]]
    nproc = len(set(procs))
    if nproc <= 1 or n % nproc != 0:
        return 1, n
    ici = n // nproc
    blocks = [procs[h * ici:(h + 1) * ici] for h in range(nproc)]
    contiguous = (all(len(set(b)) == 1 for b in blocks)
                  and len({b[0] for b in blocks}) == nproc)
    if not contiguous:
        return 1, n
    return nproc, ici
