"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

Beyond-parity axis (the reference scales only in the batch dimension,
SURVEY §2.3): a stack of S homogeneous stages (e.g. transformer blocks)
is sharded one-stage-per-pp-rank, the batch is split into M microbatches,
and activations flow stage→stage over ICI via ``ppermute`` inside a
``lax.scan`` of M + S - 1 ticks (the classic GPipe schedule; bubble
fraction (S-1)/(M+S-1)). Everything is differentiable — ``ppermute``'s
transpose is the reverse rotation — so one ``jax.grad`` over the pipelined
forward trains all stages.

Functional surface (flax-module-agnostic):

    stacked = stack_stage_params([init_stage(rng_i) for i in range(S)])
    y = pipeline_apply(stage_fn, stacked, x, mesh=mesh, microbatches=M)

``stage_fn(params_one_stage, x_mb) -> y_mb`` must be shape-preserving in
the batch dims (the pipeline carries a single activation buffer).
``stacked`` has a leading stage axis sharded over ``pp``; everything else
(input, output) is replicated across ``pp`` and may be sharded over
``dp``/``tp`` by the caller's outer machinery as usual.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map               # jax >= 0.8 (check_vma kwarg)


def stack_stage_params(per_stage: List[Any]) -> Any:
    """[stage_pytree, ...] -> one pytree with a leading stage axis."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *per_stage)


def stage_sharding(mesh: Mesh, stacked: Any, axis: str = "pp") -> Any:
    """NamedShardings placing the leading stage axis on ``axis``."""
    def shard(leaf):
        spec = P(axis, *([None] * (leaf.ndim - 1)))
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(shard, stacked)


def pipeline_apply(stage_fn: Callable, stacked_params: Any, x: jax.Array,
                   *, mesh: Mesh, microbatches: int,
                   axis: str = "pp") -> jax.Array:
    """Run ``x`` through S pipelined stages; returns the final stage's
    output, replicated across the ``pp`` axis.

    x: (B, ...) with B % microbatches == 0. Stage count S = mesh.shape
    [axis]; the stacked params' leading axis must equal S.
    """
    s_count = mesh.shape[axis]
    leading = {l.shape[0] for l in jax.tree_util.tree_leaves(stacked_params)}
    if leading != {s_count}:
        raise ValueError(
            f"stacked params' leading stage axis {sorted(leading)} must "
            f"equal the '{axis}' mesh axis size {s_count} — shard_map "
            "would otherwise silently slice away stages")
    b = x.shape[0]
    if b % microbatches:
        raise ValueError(f"batch {b} not divisible by microbatches "
                         f"{microbatches}")
    mb = b // microbatches
    xs = x.reshape(microbatches, mb, *x.shape[1:])

    def pp_body(params, xs_local):
        # params: this rank's stage slice, leading axis 1 -> squeeze
        params = jax.tree_util.tree_map(lambda l: l[0], params)
        rank = lax.axis_index(axis)
        ticks = microbatches + s_count - 1
        zero = jnp.zeros_like(xs_local[0])

        def tick(carry, t):
            recv, outs = carry
            # stage 0 injects microbatch t (while t < M); later stages
            # consume what the previous stage sent last tick
            feed_idx = jnp.minimum(t, microbatches - 1)
            inject = lax.dynamic_index_in_dim(xs_local, feed_idx, 0,
                                              keepdims=False)
            inp = jnp.where(rank == 0,
                            jnp.where(t < microbatches, inject, zero),
                            recv)
            out = stage_fn(params, inp)
            # rotate activations one stage forward
            perm = [(i, (i + 1) % s_count) for i in range(s_count)]
            recv_next = lax.ppermute(out, axis, perm)
            # last stage banks microbatch t-(S-1) when it's live
            out_idx = t - (s_count - 1)
            live = jnp.logical_and(rank == s_count - 1, out_idx >= 0)
            outs = lax.cond(
                live,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, out, jnp.maximum(out_idx, 0), 0),
                lambda o: o, outs)
            return (recv_next, outs), None

        init = (zero, jnp.zeros_like(xs_local))
        (_, outs), _ = lax.scan(tick, init, jnp.arange(ticks))
        # replicate the last stage's banked outputs across pp: every other
        # rank holds zeros, so a psum broadcasts without a gather
        mask = jnp.where(lax.axis_index(axis) == s_count - 1, 1.0, 0.0)
        return lax.psum(outs * mask.astype(outs.dtype), axis)

    param_specs = jax.tree_util.tree_map(
        lambda l: P(axis, *([None] * (l.ndim - 1))), stacked_params)
    # activations are replicated across pp (P()); dp/tp sharding of the
    # batch composes at the caller's jit level as usual
    fn = shard_map(
        pp_body, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False)
    outs = fn(stacked_params, xs)
    return outs.reshape(b, *x.shape[1:])
