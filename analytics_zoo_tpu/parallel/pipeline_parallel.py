"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

Beyond-parity axis (the reference scales only in the batch dimension,
SURVEY §2.3): a stack of S homogeneous stages (e.g. transformer blocks)
is sharded over pp ranks — S may be a MULTIPLE of the pp size, in which
case each rank runs its contiguous block of S/pp stages back to back per
tick — the batch is split into M microbatches, and activations flow
rank→rank over ICI via ``ppermute`` inside a ``lax.scan`` of
M + pp - 1 ticks (the classic GPipe schedule; bubble fraction
(pp-1)/(M+pp-1)). Everything is differentiable — ``ppermute``'s
transpose is the reverse rotation — so one ``jax.grad`` over the
pipelined forward trains all stages.

Schedule note (GPipe vs 1F1B): reverse-mode AD of the scanned forward
yields GPipe's all-forwards-then-all-backwards order, whose peak
activation memory grows with M. ``remat=True`` (default) wraps each
stage application in ``jax.checkpoint`` so the scan stores only
stage INPUTS and recomputes internals during the backward — the GPipe
paper's own configuration, bringing residuals to O(M) microbatch
activations per rank. A true 1F1B schedule would cap that at O(pp)
in-flight microbatches instead of O(M) — but under XLA's SPMD model it
is a net loss here: every rank executes one traced program, so the
per-tick "this rank does a forward OR a backward" choice lowers to
predicated execution of BOTH branches; a hand-scheduled 1F1B scan
(2(M+pp-1) ticks × predicated fwd+vjp per tick) costs ~1.5x the FLOPs
of GPipe+remat to save ~(M/pp)x on activations alone, while params +
optimizer state dominate memory at scale. GPipe+remat is therefore this
framework's training schedule by design, not omission; the remaining
tradeoff is: bubble (pp-1)/(M+pp-1) shrinks with M while activation
residuals grow with M.

Functional surface (flax-module-agnostic):

    stacked = stack_stage_params([init_stage(rng_i) for i in range(S)])
    y = pipeline_apply(stage_fn, stacked, x, mesh=mesh, microbatches=M)

``stage_fn(params_one_stage, x_mb) -> y_mb`` must be shape-preserving in
the batch dims (the pipeline carries a single activation buffer).
``stacked`` has a leading stage axis sharded over ``pp``; everything else
(input, output) is replicated across ``pp`` and may be sharded over
``dp``/``tp`` by the caller's outer machinery as usual.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ._compat import shard_map          # jax-version-tolerant facade


def stack_stage_params(per_stage: List[Any]) -> Any:
    """[stage_pytree, ...] -> one pytree with a leading stage axis."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *per_stage)


def stage_sharding(mesh: Mesh, stacked: Any, axis: str = "pp") -> Any:
    """NamedShardings placing the leading stage axis on ``axis``."""
    def shard(leaf):
        spec = P(axis, *([None] * (leaf.ndim - 1)))
        return NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(shard, stacked)


def pipeline_apply(stage_fn: Callable, stacked_params: Any, x: jax.Array,
                   *, mesh: Mesh, microbatches: int,
                   axis: str = "pp", remat: bool = True) -> jax.Array:
    """Run ``x`` through S pipelined stages; returns the final stage's
    output, replicated across the ``pp`` axis.

    x: (B, ...) with B % microbatches == 0. The stacked params' leading
    stage axis S must be a multiple of mesh.shape[axis]; each rank runs
    its contiguous block of S/pp stages sequentially per tick.
    ``remat=True`` checkpoints each stage application so the backward
    recomputes stage internals instead of storing them (see module
    docstring for the schedule/memory tradeoff)."""
    pp = mesh.shape[axis]
    leading = {l.shape[0] for l in jax.tree_util.tree_leaves(stacked_params)}
    if len(leading) != 1:
        raise ValueError(
            f"stacked params disagree on the stage axis: {sorted(leading)}")
    s_total = leading.pop()
    if s_total % pp:
        raise ValueError(
            f"stage count {s_total} must be a multiple of the '{axis}' "
            f"mesh axis size {pp} — shard_map would otherwise silently "
            "slice away stages")
    b = x.shape[0]
    if b % microbatches:
        raise ValueError(f"batch {b} not divisible by microbatches "
                         f"{microbatches}")
    mb = b // microbatches
    xs = x.reshape(microbatches, mb, *x.shape[1:])
    apply_stage = jax.checkpoint(stage_fn) if remat else stage_fn

    def pp_body(params, xs_local):
        # params: this rank's contiguous block of S/pp stages
        rank = lax.axis_index(axis)
        ticks = microbatches + pp - 1
        zero = jnp.zeros_like(xs_local[0])

        def run_block(p_block, inp):
            # apply this rank's stages in order (scan over the leading
            # per-rank stage axis; a single stage still goes through it)
            def body(c, p):
                return apply_stage(p, c), None
            out, _ = lax.scan(body, inp, p_block)
            return out

        def tick(carry, t):
            recv, outs = carry
            # rank 0 injects microbatch t (while t < M); later ranks
            # consume what the previous rank sent last tick
            feed_idx = jnp.minimum(t, microbatches - 1)
            inject = lax.dynamic_index_in_dim(xs_local, feed_idx, 0,
                                              keepdims=False)
            inp = jnp.where(rank == 0,
                            jnp.where(t < microbatches, inject, zero),
                            recv)
            out = run_block(params, inp)
            # rotate activations one rank forward
            perm = [(i, (i + 1) % pp) for i in range(pp)]
            recv_next = lax.ppermute(out, axis, perm)
            # last rank banks microbatch t-(pp-1) when it's live
            out_idx = t - (pp - 1)
            live = jnp.logical_and(rank == pp - 1, out_idx >= 0)
            outs = lax.cond(
                live,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, out, jnp.maximum(out_idx, 0), 0),
                lambda o: o, outs)
            return (recv_next, outs), None

        init = (zero, jnp.zeros_like(xs_local))
        (_, outs), _ = lax.scan(tick, init, jnp.arange(ticks))
        # replicate the last rank's banked outputs across pp: every other
        # rank holds zeros, so a psum broadcasts without a gather
        mask = jnp.where(lax.axis_index(axis) == pp - 1, 1.0, 0.0)
        return lax.psum(outs * mask.astype(outs.dtype), axis)

    param_specs = jax.tree_util.tree_map(
        lambda l: P(axis, *([None] * (l.ndim - 1))), stacked_params)
    # activations are replicated across pp (P()); dp/tp sharding of the
    # batch composes at the caller's jit level as usual
    fn = shard_map(
        pp_body, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False)
    outs = fn(stacked_params, xs)
    return outs.reshape(b, *x.shape[1:])
