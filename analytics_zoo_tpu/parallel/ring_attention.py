"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has no long-context path at all — its max-sequence handling is
plain attention inside BERT/Transformer layers and scale-out is batch-dim only
(SURVEY.md §2.3, §5; reference: pyzoo/.../layers/self_attention.py:386,
zoo/.../keras/layers/BERT.scala:402). Here sequence parallelism is first-class:
the ``sp`` mesh axis shards the sequence dimension, and these two strategies
turn a local S/sp shard into exact global attention:

* **ring attention** — K/V shards rotate around the sp ring via ``ppermute``
  (one ICI hop per step) while each device folds every visiting block into an
  online-softmax accumulator (ops/attention.py:blockwise_update). Peak memory
  is O(S_local) per device; comm is overlapped by XLA's async collectives.
* **Ulysses** — ``all_to_all`` re-shards from sequence-sharded to head-sharded,
  runs ordinary (flash) attention on full sequences for H/sp heads, and
  re-shards back. Cheaper comm volume when heads >= sp.

Both are pure jnp + lax collectives inside the jitted step, so they are
differentiable end-to-end (ppermute/all_to_all have transpose rules) and XLA
schedules the collectives on ICI.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from analytics_zoo_tpu.ops.attention import (
    blockwise_finalize, blockwise_update, flash_attention, mha_reference)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = "sp", causal: bool = False,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Exact global attention over sequence shards. Must run under an
    ``axis_name`` mapped axis (shard_map / jit-with-mesh). q,k,v are the local
    shards (B, S_local, H, D); the global sequence is the sp-axis concat.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    from ._compat import axis_size
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape

    q_positions = idx * s_local + jnp.arange(s_local)
    # Accumulators must carry the inputs' varying-axes type (jax >= 0.9
    # shard_map vma typing) or the scan carry is rejected; _compat marks the
    # device-invariant zeros as varying over every manual axis in scope (a
    # no-op on jax builds without vma typing).
    from ._compat import mark_varying, varying_axes
    vma = varying_axes(q, k)
    _vary = partial(mark_varying, vma=vma)
    acc = _vary(jnp.zeros((b, s_local, h, d), jnp.float32))
    m = _vary(jnp.full((b, s_local, h), -jnp.inf, jnp.float32))
    l = _vary(jnp.zeros((b, s_local, h), jnp.float32))

    def step(carry, i):
        k_blk, v_blk, acc, m, l = carry
        # After i forward rotations each device holds the shard that
        # originated on rank (idx - i) mod n.
        src = jnp.mod(idx - i, n)
        k_positions = src * s_local + jnp.arange(s_local)
        acc, m, l = blockwise_update(
            q, k_blk, v_blk, acc, m, l, sm_scale=sm_scale,
            q_positions=q_positions, k_positions=k_positions, causal=causal)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, acc, m, l), None

    (_, _, acc, m, l), _ = lax.scan(step, (k, v, acc, m, l),
                                    jnp.arange(n))
    return blockwise_finalize(acc, l).astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str = "sp", causal: bool = False,
                      sm_scale: Optional[float] = None,
                      use_flash: bool = True) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style): re-shard
    (B, S/sp, H, D) -> (B, S, H/sp, D), attend locally, re-shard back.
    Requires H % sp_size == 0."""
    from ._compat import axis_size
    n = axis_size(axis_name)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by sp size ({n})")
    # split heads across the axis, gather sequence
    a2a = partial(lax.all_to_all, axis_name=axis_name, split_axis=2,
                  concat_axis=1, tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    attend = flash_attention if use_flash else mha_reference
    out = attend(qg, kg, vg, causal=causal, sm_scale=sm_scale)
    # split sequence back, gather heads
    return lax.all_to_all(out, axis_name=axis_name, split_axis=1,
                          concat_axis=2, tiled=True)


def sequence_sharded_attention(mesh: Mesh, q, k, v, *, strategy: str = "ring",
                               causal: bool = False,
                               sm_scale: Optional[float] = None) -> jax.Array:
    """Convenience wrapper: shard (B, S, H, D) along the mesh's sp axis on the
    sequence dim (and dp on batch) and run the chosen strategy via shard_map.
    Inside a model's jitted train step, call ring_attention/ulysses_attention
    directly under the step's shard_map instead."""
    if strategy not in ("ring", "ulysses"):
        raise ValueError(f"unknown sequence-parallel strategy {strategy!r}")
    fn = ring_attention if strategy == "ring" else ulysses_attention
    spec = P("dp", "sp", None, None)
    from ._compat import shard_map

    @shard_map(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    def _run(ql, kl, vl):
        return fn(ql, kl, vl, axis_name="sp", causal=causal,
                  sm_scale=sm_scale)

    return _run(q, k, v)
