"""Sharding plane: canonical per-layer PartitionSpecs over a (dp, fsdp, tp)
mesh — models bigger than one chip.

Two pieces:

* :class:`SpecLayout` — a small registry mapping parameter paths to
  PartitionSpecs over the named mesh axes (Megatron-style tp columns/rows,
  fsdp×tp embedding tables), plus the batch-axis convention. It is the ONE
  object the estimator, engine, serving (``InferenceModel``) and tests agree
  on, the way ``CommsConfig`` is for the dp wire. Modules that declare their
  own specs via ``nn.with_partitioning`` (parallel/tensor_parallel.py) win;
  SpecLayout rules fill the rest.

* :class:`FsdpPlan` — parameter sharding over the ``fsdp`` axis riding the
  comms plane's :class:`~analytics_zoo_tpu.parallel.comms.BucketLayout`
  machinery: params whose spec is trivial live as a padded flat f32 vector
  split into buckets, each bucket stored ``P("fsdp")`` (1/N per device).
  Inside the jitted step every bucket passes through
  ``with_sharding_constraint(bucket, P())`` — GSPMD emits exactly ONE
  all-gather per bucket (operand = the 1/N shard), the forward consumes the
  gathered params and drops them, and the gradient constraint back to
  ``P("fsdp")`` makes XLA combine grads over the fsdp groups (grouped
  all-reduce / reduce-scatter + slice, backend's choice). This is the param
  extension of ZeRO-1 weight-update sharding (arXiv:2004.13336): PR 8
  sharded the *optimizer moments* over the flat vector; the same flat-vector
  layout now holds the *parameters* too, so per-device param+moment bytes
  scale as 1/fsdp and the largest trainable model is the mesh's HBM, not one
  chip's.

Why buckets and not per-leaf sharding: one all-gather per parameter leaf is
a launch-bound wire (hundreds of small collectives); per-bucket gathers are
few, large, and individually schedulable against the forward's compute —
the mirror image of PR 11's per-bucket reduce-scatter in the backward.

The composite param pytree
--------------------------
When an :class:`FsdpPlan` is active the engine's ``params`` (and therefore
the optax state, which inherits the structure) is the *composite* form::

    {"__fsdp_flat__": {"b000": f32[bucket0], "b001": ...},   # P("fsdp")
     "__fsdp_held__": {"h000": leaf, ...}}                    # tp/explicit

It is a plain pytree, so every existing code path — ``lax.scan`` multi-step,
``optax`` updates, ``global_norm`` clipping (padding slots hold zero grads),
buffer donation, ``snapshot()`` — works unchanged; only ``_apply`` assembles
the full tree (gather), and checkpoints always store the CANONICAL tree form
(:meth:`FsdpPlan.composite_to_tree`), so fsdp-sharded ↔ replicated restores
are bit-exact in both directions — the same contract the comms plane's
sharded optimizer state keeps (PR 8/12).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .comms import BucketLayout

# canonical rules: embedding tables shard rows over fsdp and columns over tp
# (the friesian/NCF pod-scale recommender layout — one table bigger than any
# chip splits over BOTH model axes); everything else is either declared by
# the module (tensor_parallel.py layers) or rides the fsdp flat vector.
DEFAULT_RULES: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = (
    ("*embed_table*", ("fsdp", "tp")),
    ("*embedding*", ("fsdp", "tp")),
)


def _path_names(path) -> Tuple:
    return tuple(getattr(k, "key", getattr(k, "name", getattr(k, "idx",
                                                              None)))
                 for k in path)


def _path_str(path) -> str:
    return "/".join(str(n) for n in _path_names(path))


def _is_spec_leaf(x) -> bool:
    return x is None or isinstance(x, P)


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical sharding layout: which mesh axis each parameter dimension
    lives on, and how the batch splits.

    ``rules`` map glob patterns (matched against the ``"/"``-joined param
    path) to a tuple of mesh-axis names, one per leading dimension
    (``None`` = replicated dim; shorter tuples leave trailing dims
    replicated). First match wins. Axes missing from the mesh, of size 1,
    or not dividing the dimension are dropped per-leaf — a layout written
    for an 8-dev pod degrades cleanly on a 1-dev laptop mesh.

    ``fsdp=True`` additionally shards every *unmatched* big f32 param over
    the ``fsdp`` axis: in the train engine through an :class:`FsdpPlan`
    (bucketed flat vector, explicit per-bucket gathers); in serving
    (``InferenceModel``) per-leaf on the largest divisible dim (no update
    step, so the bucket machinery buys nothing there).
    """

    fsdp: bool = True
    bucket_mb: float = 4.0
    data_axis: str = "dp"
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"
    rules: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = DEFAULT_RULES
    # leaves smaller than 2*axis_size never shard (a shard under one
    # element per device is padding, not parallelism)

    active = True

    # -- resolution ----------------------------------------------------------
    @classmethod
    def resolve(cls, config: Dict[str, Any], arg=None
                ) -> Optional["SpecLayout"]:
        """One resolution path for the estimator/serving kwarg + config +
        env knobs (mirrors ``CommsConfig.resolve``):

        * ``arg`` a SpecLayout → use it; ``arg False`` → plane off.
        * ``arg True`` / config ``sharding: true`` / ``ZOO_SHARDING_PLANE=1``
          → default layout; config ``sharding: {...}`` → field overrides.
        * ``ZOO_FSDP_BUCKET_MB`` overrides the gather bucket size.
        Returns None when the plane is off (the engine then runs the
        untouched replicated program).
        """
        from ..common.knobs import get as _knob
        if isinstance(arg, SpecLayout):
            return arg
        if arg is False:
            return None
        cfg = (config or {}).get("sharding")
        if arg is None and cfg is None:
            cfg = _knob("ZOO_SHARDING_PLANE")
        if not cfg and arg is not True:
            return None
        fields = dict(cfg) if isinstance(cfg, dict) else {}
        if "rules" in fields:
            fields["rules"] = tuple(
                (str(pat), tuple(spec)) for pat, spec in fields["rules"])
        bucket_mb = _knob("ZOO_FSDP_BUCKET_MB")
        if bucket_mb is not None and "bucket_mb" not in fields:
            fields["bucket_mb"] = float(bucket_mb)
        return cls(**fields)

    # -- per-leaf specs ------------------------------------------------------
    def spec_for(self, path_names: Sequence, shape: Sequence[int],
                 mesh: Optional[Mesh] = None) -> P:
        """Rule-matched PartitionSpec for one param (``P()`` when no rule
        matches). With a mesh, non-dividing / absent / size-1 axes drop."""
        key = "/".join(str(n) for n in path_names)
        for pat, axes in self.rules:
            if fnmatch.fnmatchcase(key, pat):
                spec = list(axes[:len(shape)])
                spec += [None] * (len(shape) - len(spec))
                if mesh is not None:
                    for d, a in enumerate(spec):
                        if a is None:
                            continue
                        size = mesh.shape.get(a, 1)
                        if size <= 1 or int(shape[d]) % size != 0:
                            spec[d] = None
                return P(*spec)
        return P()

    def merge_specs(self, params, declared, mesh: Mesh):
        """Spec tree aligned with ``params``: module-declared specs (flax
        ``nn.with_partitioning`` metadata, already captured by the engine)
        win; SpecLayout rules fill the trivial slots. Every leaf gets a
        PartitionSpec (``P()`` = no explicit spec → fsdp/replicated)."""
        decl = {}
        if declared is not None:
            decl = {_path_names(p): s for p, s in
                    jax.tree_util.tree_flatten_with_path(
                        declared, is_leaf=_is_spec_leaf)[0]}

        def rule(path, leaf):
            names = _path_names(path)
            d = decl.get(names)
            if d is not None and any(a is not None for a in d):
                return P(*d)
            return self.spec_for(names, getattr(leaf, "shape", ()), mesh)

        return jax.tree_util.tree_map_with_path(rule, params)

    def _fsdp_leaf_spec(self, leaf, mesh: Mesh) -> P:
        """Per-leaf fsdp fallback (serving / non-bucketed consumers): split
        the trailing dim of >=2-dim leaves (the output-feature dim of
        dense/conv kernels) or dim 0 of vectors (bias adds are elementwise
        over features). Never an inner dim: splitting a *contraction* dim
        makes GSPMD compute partial sums + all-reduce, changing the
        matmul's reduction order and breaking serving bit-identity with
        the replicated layout. Non-dividing / tiny leaves replicate."""
        size = mesh.shape.get(self.fsdp_axis, 1)
        shape = getattr(leaf, "shape", ())
        if (not self.fsdp or size <= 1 or not shape
                or int(np.prod(shape)) < 2 * size):
            return P()
        d = len(shape) - 1
        if shape[d] % size == 0:
            spec = [None] * len(shape)
            spec[d] = self.fsdp_axis
            return P(*spec)
        return P()

    def param_shardings(self, mesh: Mesh, params, declared=None):
        """NamedSharding tree for a param/variable tree — the serving-side
        entry (``InferenceModel``): rule/declared specs first, then the
        per-leaf fsdp split, then replication. The train engine instead
        routes unmatched leaves through an :class:`FsdpPlan` (bucketed
        gathers); both leave every device holding ~1/fsdp of the params."""
        specs = self.merge_specs(params, declared, mesh)

        def rule(leaf, spec):
            if spec is not None and any(a is not None for a in spec):
                return NamedSharding(mesh, spec)
            return NamedSharding(mesh, self._fsdp_leaf_spec(leaf, mesh))

        # tree_map flattens only down to `params`' leaves, so the P()
        # entries of `specs` ride through as opaque values
        return jax.tree.map(rule, params, specs)

    # -- batch convention ----------------------------------------------------
    def batch_axes(self, mesh: Mesh) -> Tuple[str, ...]:
        """Mesh axes the batch dim splits over: dp plus fsdp (which acts as
        an extra data axis for activations — same convention as
        ``mesh.data_sharding``); tp ranks see the FULL local batch."""
        axes = tuple(a for a in (self.data_axis, self.fsdp_axis)
                     if mesh.shape.get(a, 1) > 1)
        return axes or (self.data_axis,)

    def batch_spec(self, mesh: Mesh, ndim: int) -> P:
        return P(self.batch_axes(mesh), *([None] * (ndim - 1)))

    # -- identity ------------------------------------------------------------
    def fingerprint(self) -> str:
        """Stable content hash — salts the compile plane's structural key
        (two engines with different layouts must never share an
        executable) and keys the declared hlo_lint accounting."""
        h = hashlib.sha256(repr(
            (self.fsdp, float(self.bucket_mb), self.data_axis,
             self.fsdp_axis, self.tp_axis, self.rules)).encode())
        return (f"sharding:fsdp={int(self.fsdp)}:"
                f"bucket_mb={float(self.bucket_mb)}:{h.hexdigest()[:16]}")


class FsdpPlan:
    """Bucketed fsdp parameter sharding bound to one param tree.

    Built once per engine from the param tree + merged spec tree: every f32
    leaf with a trivial spec and >= 2*fsdp elements *rides* the flat vector
    (:class:`BucketLayout` over the fsdp axis — the same padding/bucketing
    arithmetic the dp comms plane uses, so flatten/unflatten round-trips
    are bit-exact by the already-tested contract); everything else is
    *held* aside with its own (tp/explicit) sharding.
    """

    FLAT_KEY = "__fsdp_flat__"
    HELD_KEY = "__fsdp_held__"

    def __init__(self, mesh: Mesh, axis: str, layout: BucketLayout,
                 treedef, ride_mask: Tuple[bool, ...],
                 held_specs: Tuple[P, ...], bucket_mb: float):
        self.mesh = mesh
        self.axis = axis
        self.layout = layout
        self.treedef = treedef              # FULL param tree structure
        self.ride_mask = ride_mask
        self.held_specs = held_specs
        self.bucket_mb = float(bucket_mb)
        self.n_dev = layout.n_dev
        self.bucket_keys = tuple(f"b{i:03d}"
                                 for i in range(len(layout.bucket_sizes)))
        self.held_keys = tuple(f"h{i:03d}"
                               for i in range(len(held_specs)))

    # -- construction --------------------------------------------------------
    @staticmethod
    def build(params, specs, mesh: Mesh, axis: str = "fsdp",
              bucket_mb: float = 4.0) -> Optional["FsdpPlan"]:
        """None when nothing rides (axis size 1, or every leaf is sharded
        by spec / too small / non-f32) — the engine then falls back to
        plain spec shardings and the program is untouched."""
        n = mesh.shape.get(axis, 1)
        if n <= 1:
            return None
        leaves, treedef = jax.tree_util.tree_flatten(params)
        if not leaves:
            return None
        if specs is None:
            spec_leaves = [P()] * len(leaves)
        else:
            spec_leaves = [s for s in jax.tree_util.tree_leaves(
                specs, is_leaf=_is_spec_leaf)]
        if len(spec_leaves) != len(leaves):
            raise ValueError(
                f"sharding plane: spec tree has {len(spec_leaves)} leaves "
                f"for {len(leaves)} params")

        def rides(leaf, spec) -> bool:
            if spec is not None and any(a is not None for a in spec):
                return False
            dt = getattr(leaf, "dtype", None)
            if np.dtype(dt if dt is not None
                        else np.result_type(leaf)) != np.float32:
                return False
            return int(np.prod(np.shape(leaf)) or 1) >= 2 * n

        mask = tuple(rides(l, s) for l, s in zip(leaves, spec_leaves))
        if not any(mask):
            return None
        ridden = [l for l, m in zip(leaves, mask) if m]
        held_specs = tuple((s if s is not None else P())
                           for s, m in zip(spec_leaves, mask) if not m)
        layout = BucketLayout.build(ridden, n, bucket_mb)
        return FsdpPlan(mesh, axis, layout, treedef, mask, held_specs,
                        bucket_mb)

    # -- composite form ------------------------------------------------------
    @staticmethod
    def is_composite(node) -> bool:
        return (isinstance(node, dict)
                and set(node.keys()) == {FsdpPlan.FLAT_KEY,
                                         FsdpPlan.HELD_KEY})

    def _split(self, tree) -> Tuple[List, List]:
        leaves = jax.tree_util.tree_leaves(tree)
        if len(leaves) != len(self.ride_mask):
            raise ValueError(
                f"sharding plane: tree has {len(leaves)} leaves, plan was "
                f"built for {len(self.ride_mask)}")
        ridden = [l for l, m in zip(leaves, self.ride_mask) if m]
        held = [l for l, m in zip(leaves, self.ride_mask) if not m]
        return ridden, held

    def _join(self, ridden: List, held: List):
        it_r, it_h = iter(ridden), iter(held)
        leaves = [next(it_r) if m else next(it_h) for m in self.ride_mask]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def to_composite(self, tree) -> Dict:
        """Canonical tree form -> composite (host-side, numpy): flatten the
        ridden leaves into the padded flat vector and slice per-bucket.
        Bit-exact inverse of :meth:`composite_to_tree` (padding is zeros)."""
        ridden, held = self._split(tree)
        flat = self.layout.flatten_np(ridden)
        buckets, off = {}, 0
        for k, b in zip(self.bucket_keys, self.layout.bucket_sizes):
            buckets[k] = np.asarray(flat[off:off + b])
            off += b
        return {self.FLAT_KEY: buckets,
                self.HELD_KEY: dict(zip(self.held_keys,
                                        [np.asarray(h) for h in held]))}

    def composite_to_tree(self, comp: Dict):
        """Composite -> canonical tree form (host-side, numpy) — what
        checkpoints store, so fsdp-sharded and replicated runs read each
        other's state without either knowing about the other."""
        flat = np.concatenate([np.asarray(comp[self.FLAT_KEY][k]).reshape(-1)
                               for k in self.bucket_keys])
        ridden = jax.tree_util.tree_leaves(self.layout.unflatten_np(flat))
        held = [np.asarray(comp[self.HELD_KEY][k]) for k in self.held_keys]
        return self._join(ridden, held)

    # -- in-program assembly (the gathers) -----------------------------------
    def assemble(self, comp: Dict):
        """Composite -> full param tree INSIDE the jitted step. Each bucket
        is constrained to replicated — GSPMD emits one all-gather per
        bucket, operand = this device's 1/N shard — then the flat vector
        unflattens and interleaves with the held (tp-sharded) leaves.
        The gathered tree is a temporary of the forward: XLA frees it
        after use, so HBM high-water stays ~shard-sized plus the largest
        live activations, which is the whole point."""
        repl = NamedSharding(self.mesh, P())
        buckets = [jax.lax.with_sharding_constraint(comp[self.FLAT_KEY][k],
                                                    repl)
                   for k in self.bucket_keys]
        flat = jnp.concatenate(buckets)
        ridden = jax.tree_util.tree_leaves(self.layout.unflatten(flat))
        held = [comp[self.HELD_KEY][k] for k in self.held_keys]
        return self._join(ridden, held)

    def constrain_shards(self, comp: Dict) -> Dict:
        """Constrain a composite-shaped tree (grads, updated params) back
        onto its resting shardings: buckets ``P(fsdp)`` — on grads this is
        what makes XLA combine over the fsdp groups and keep only the
        local shard — held leaves their declared specs."""
        fs = NamedSharding(self.mesh, P(self.axis))
        flat = {k: jax.lax.with_sharding_constraint(comp[self.FLAT_KEY][k],
                                                    fs)
                for k in self.bucket_keys}
        held = {k: jax.lax.with_sharding_constraint(
            comp[self.HELD_KEY][k], NamedSharding(self.mesh, s))
            for k, s in zip(self.held_keys, self.held_specs)}
        return {self.FLAT_KEY: flat, self.HELD_KEY: held}

    def composite_shardings(self) -> Dict:
        fs = NamedSharding(self.mesh, P(self.axis))
        return {self.FLAT_KEY: {k: fs for k in self.bucket_keys},
                self.HELD_KEY: {k: NamedSharding(self.mesh, s)
                                for k, s in zip(self.held_keys,
                                                self.held_specs)}}

    # -- optimizer-state canonicalization ------------------------------------
    def state_to_tree(self, opt_state):
        """Optimizer state over composite params (moment nodes ARE
        composites — optax inherits the param structure) -> canonical
        tree form for checkpoints. Padding slots hold zeros (zero grads
        keep zero moments), so the conversion is lossless — same argument
        as the comms plane's ``opt_flat_to_tree``."""
        return jax.tree.map(
            lambda node: (self.composite_to_tree(node)
                          if self.is_composite(node) else node),
            opt_state, is_leaf=self.is_composite)

    def tree_to_state(self, canonical, template):
        """Inverse of :meth:`state_to_tree`. ``template`` is
        ``eval_shape(tx.init, composite_params)`` — its composite nodes
        mark which positions of the canonical state are param-structured
        moments vs pass-through counters."""
        return jax.tree.map(
            lambda tmpl, node: (self.to_composite(node)
                                if self.is_composite(tmpl) else node),
            template, canonical, is_leaf=self.is_composite)

    # -- identity / accounting -----------------------------------------------
    def signature(self) -> str:
        h = hashlib.sha256(repr(
            (self.axis, self.ride_mask,
             tuple(str(s) for s in self.held_specs))).encode())
        return f"{self.layout.signature()}:{h.hexdigest()[:16]}"

    def gather_shard_bytes_per_sweep(self) -> int:
        """All-gather *operand* bytes one assembly sweep moves per device:
        each bucket's gather operand is its 1/N shard, so one forward's
        gathers read ``padded_total/N`` f32 elements. (XLA may re-gather
        in the backward instead of keeping the full params live — that
        trades one more sweep of wire for HBM high-water; the accounting
        rule therefore checks launches in whole-sweep multiples.)"""
        return self.layout.shard_size * 4

    def summary(self) -> Dict[str, Any]:
        """Declared per-step accounting for the analysis plane (hlo_lint
        cross-checks the compiled program against it) and the sharding
        snapshot/bench surface."""
        lo = self.layout
        return {
            "plane": "sharding",
            "fsdp": {
                "axis": self.axis,
                "axis_size": self.n_dev,
                "axes": {name: int(size)
                         for name, size in self.mesh.shape.items()
                         if size > 1},
                "buckets": len(lo.bucket_sizes),
                "bucket_mb": self.bucket_mb,
                "padded_total": lo.padded_total,
                "shard_size": lo.shard_size,
                "ridden_leaves": int(sum(self.ride_mask)),
                "held_leaves": len(self.held_specs),
                "gather_shard_bytes_per_sweep":
                    self.gather_shard_bytes_per_sweep(),
                "param_bytes_full": lo.total * 4,
                "param_bytes_per_device_ridden": lo.shard_size * 4,
                "layout_sig": lo.signature(),
            },
        }
