"""Tensor parallelism over the ``tp`` mesh axis.

The reference scales a single layer only by data parallelism (its five
backends all replicate the model; SURVEY.md §2.3-2.4). On TPU, tensor
parallelism is a first-class axis: Megatron-style column/row-parallel linear
layers (arXiv:1909.08053) expressed the GSPMD way — parameters carry
``flax.linen.with_partitioning`` metadata naming mesh axes, the engine turns
that metadata into ``NamedSharding``s (TrainEngine._param_sharding), and
XLA's SPMD partitioner inserts the all-gathers/all-reduces over ICI. No
manual collectives, and the same module runs unmodified on a tp=1 mesh.

Layer recipe (the Megatron pairing):

* ``TPDense(mode="column")`` — kernel split on the OUTPUT dim. Each tp shard
  computes a slice of the features; activations come out tp-sharded on the
  feature dim. Bias is sharded the same way.
* ``TPDense(mode="row")`` — kernel split on the INPUT dim. Consumes
  tp-sharded activations; XLA all-reduces the partial products. Bias is
  replicated (added after the reduce).
* ``TPMLP`` — column → gelu → row: one all-reduce per MLP, activations never
  materialize unsharded at the hidden width.
* ``TPSelfAttention`` — fused qkv projection column-split (= heads split
  across tp shards), output projection row-split. Heads must divide tp.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

default_kernel_init = nn.initializers.lecun_normal()


class TPDense(nn.Module):
    """Column- or row-parallel linear layer (see module docstring)."""

    features: int
    mode: str = "column"                # "column" | "row"
    axis: str = "tp"
    use_bias: bool = True
    activation: Optional[Callable] = None
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = default_kernel_init

    @nn.compact
    def __call__(self, x):
        if self.mode not in ("column", "row"):
            raise ValueError(f"mode must be column|row, got {self.mode!r}")
        in_features = x.shape[-1]
        kspec = ((None, self.axis) if self.mode == "column"
                 else (self.axis, None))
        kernel = self.param(
            "kernel", nn.with_partitioning(self.kernel_init, kspec),
            (in_features, self.features))
        x = x.astype(self.dtype) if self.dtype else x
        y = x @ kernel.astype(x.dtype)
        if self.use_bias:
            bspec = (self.axis,) if self.mode == "column" else (None,)
            bias = self.param(
                "bias", nn.with_partitioning(nn.initializers.zeros_init(),
                                             bspec),
                (self.features,))
            y = y + bias.astype(y.dtype)
        if self.activation is not None:
            y = self.activation(y)
        return y


class TPMLP(nn.Module):
    """Transformer MLP block: column-parallel expand, row-parallel project.

    The hidden activation stays tp-sharded; exactly one all-reduce (inserted
    by GSPMD after the row matmul) per call.
    """

    hidden_dim: int
    out_dim: Optional[int] = None
    axis: str = "tp"
    activation: Callable = nn.gelu
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        out_dim = self.out_dim or x.shape[-1]
        h = TPDense(self.hidden_dim, mode="column", axis=self.axis,
                    activation=self.activation, dtype=self.dtype,
                    name="fc_in")(x)
        return TPDense(out_dim, mode="row", axis=self.axis, dtype=self.dtype,
                       name="fc_out")(h)


class TPSelfAttention(nn.Module):
    """Multi-head self-attention with heads split across the tp axis.

    Fused qkv projection is column-parallel (each shard owns
    ``num_heads / tp`` full heads), attention math is embarrassingly parallel
    per head, and the output projection is row-parallel — the canonical
    Megatron attention sharding, expressed purely through param metadata.
    """

    num_heads: int
    head_dim: Optional[int] = None
    axis: str = "tp"
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, mask=None):
        d_model = x.shape[-1]
        head_dim = self.head_dim or d_model // self.num_heads
        inner = self.num_heads * head_dim

        qkv = TPDense(3 * inner, mode="column", axis=self.axis,
                      dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(*t.shape[:-1], self.num_heads, head_dim)

        q, k, v = heads(q), heads(k), heads(v)
        scale = head_dim ** -0.5
        logits = jnp.einsum("...qhd,...khd->...hqk", q * scale, k)
        if mask is not None:
            logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
        probs = nn.softmax(logits)
        ctx = jnp.einsum("...hqk,...khd->...qhd", probs, v)
        ctx = ctx.reshape(*ctx.shape[:-2], inner)
        return TPDense(d_model, mode="row", axis=self.axis, dtype=self.dtype,
                       name="out")(ctx)


class TPTransformerBlock(nn.Module):
    """Pre-LN transformer block wired from the TP pieces: 2 all-reduces per
    layer (one after attention out-proj, one after the MLP row matmul)."""

    num_heads: int
    mlp_ratio: int = 4
    axis: str = "tp"
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, mask=None):
        h = nn.LayerNorm(name="ln1")(x)
        x = x + TPSelfAttention(self.num_heads, axis=self.axis,
                                dtype=self.dtype, name="attn")(h, mask)
        h = nn.LayerNorm(name="ln2")(x)
        return x + TPMLP(self.mlp_ratio * x.shape[-1], out_dim=x.shape[-1],
                         axis=self.axis, dtype=self.dtype, name="mlp")(h)
