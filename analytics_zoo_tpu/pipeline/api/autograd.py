"""Symbolic autograd API (parity: pyzoo/zoo/pipeline/api/autograd.py —
Variable:369, Lambda:393, math ops:32-250; Scala mirror
zoo/.../pipeline/api/autograd/math.scala).

The reference routes every op through py4j to Scala autograd nodes; here an op
is a jnp lambda recorded on the Variable DAG (keras/engine/graph.py), so a
CustomLoss or Lambda layer compiles into the same single XLA program as the
rest of the model."""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from .keras.engine.graph import Variable, has_variable

__all__ = [
    "Variable", "Parameter", "Lambda", "CustomLoss",
    "abs", "sum", "mean", "clip", "square", "sqrt", "exp", "log", "pow",
    "maximum", "minimum", "max", "min", "neg", "softsign", "softplus",
    "mm", "dot", "l2_normalize", "batch_dot", "stack", "expand_dims",
    "contiguous", "mul", "add", "sub", "div", "epsilon", "squeeze",
]

_py_abs, _py_sum, _py_pow, _py_max, _py_min = abs, sum, pow, max, min


def _unary(fn: Callable, name: str):
    def op(x, *args, **kwargs):
        if isinstance(x, Variable):
            return Variable(op=lambda a: fn(a, *args, **kwargs),
                            parents=[x], name=name)
        return fn(x, *args, **kwargs)
    op.__name__ = name
    return op


def _binary(fn: Callable, name: str):
    def op(x, y):
        xv, yv = isinstance(x, Variable), isinstance(y, Variable)
        if xv and yv:
            return Variable(op=fn, parents=[x, y], name=name)
        if xv:
            return Variable(op=lambda a: fn(a, y), parents=[x], name=name)
        if yv:
            return Variable(op=lambda b: fn(x, b), parents=[y], name=name)
        return fn(x, y)
    op.__name__ = name
    return op


def epsilon() -> float:
    return 1e-7


abs = _unary(jnp.abs, "abs")
square = _unary(jnp.square, "square")
sqrt = _unary(jnp.sqrt, "sqrt")
exp = _unary(jnp.exp, "exp")
log = _unary(jnp.log, "log")
neg = _unary(lambda a: -a, "neg")
softsign = _unary(jax.nn.soft_sign, "softsign")
softplus = _unary(jax.nn.softplus, "softplus")
contiguous = _unary(lambda a: a, "contiguous")


def sum(x, axis: int = 0, keepdims: bool = False):
    """reference autograd.sum (axis counts ALL dims incl. batch)."""
    return _unary(lambda a: jnp.sum(a, axis=axis, keepdims=keepdims),
                  "sum")(x)


def mean(x, axis: int = 0, keepdims: bool = False):
    return _unary(lambda a: jnp.mean(a, axis=axis, keepdims=keepdims),
                  "mean")(x)


def max(x, axis: int = 0, keepdims: bool = False):
    return _unary(lambda a: jnp.max(a, axis=axis, keepdims=keepdims),
                  "max")(x)


def min(x, axis: int = 0, keepdims: bool = False):
    return _unary(lambda a: jnp.min(a, axis=axis, keepdims=keepdims),
                  "min")(x)


def clip(x, min_value: float, max_value: float):
    return _unary(lambda a: jnp.clip(a, min_value, max_value), "clip")(x)


def pow(x, a: float):
    return _unary(lambda v: v ** a, "pow")(x)


def expand_dims(x, axis: int):
    return _unary(lambda a: jnp.expand_dims(a, axis), "expand_dims")(x)


def squeeze(x, axis: Optional[int] = None):
    return _unary(lambda a: jnp.squeeze(a, axis=axis), "squeeze")(x)


def l2_normalize(x, axis: int = -1):
    return _unary(
        lambda a: a / jnp.maximum(jnp.linalg.norm(a, axis=axis,
                                                  keepdims=True), 1e-12),
        "l2_normalize")(x)


maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
add = _binary(lambda a, b: a + b, "add")
sub = _binary(lambda a, b: a - b, "sub")
mul = _binary(lambda a, b: a * b, "mul")
div = _binary(lambda a, b: a / b, "div")


def mm(x, y, axes: Optional[Sequence[int]] = None):
    """Batch matrix multiply with optional contraction axes (reference
    autograd.mm)."""
    def fn(a, b):
        if axes is not None:
            return jax.lax.dot_general(
                a, b, (((axes[0],), (axes[1],)), ((0,), (0,))))
        return jnp.matmul(a, b)
    return _binary(fn, "mm")(x, y)


def batch_dot(x, y, axes: Sequence[int] = (2, 2), normalize: bool = False):
    def fn(a, b):
        aa, bb = a, b
        if normalize:
            aa = aa / jnp.maximum(
                jnp.linalg.norm(aa, axis=axes[0], keepdims=True), 1e-12)
            bb = bb / jnp.maximum(
                jnp.linalg.norm(bb, axis=axes[1], keepdims=True), 1e-12)
        return jax.lax.dot_general(
            aa, bb, (((axes[0],), (axes[1],)), ((0,), (0,))))
    return _binary(fn, "batch_dot")(x, y)


def dot(x, y):
    return mm(x, y)


def stack(inputs: Sequence[Any], axis: int = 1):
    if has_variable(inputs):
        return Variable(op=lambda *xs: jnp.stack(xs, axis=axis),
                        parents=list(inputs), name="stack")
    return jnp.stack(inputs, axis=axis)


class Parameter(Variable):
    """A trainable standalone weight usable in autograd expressions
    (reference autograd.py Parameter). Realised as a flax param when the
    graph executes inside a Model."""

    def __init__(self, shape, init_weight=None, trainable: bool = True,
                 name: Optional[str] = None):
        import flax.linen as nn

        pshape = tuple(shape)
        weight = init_weight

        class _ParamLeaf(nn.Module):
            @nn.compact
            def __call__(self):
                if weight is not None:
                    init = lambda rng: jnp.asarray(weight)
                else:
                    init = lambda rng: nn.initializers.lecun_normal()(
                        rng, pshape)
                p = self.param("weight", lambda rng: init(rng))
                return p if trainable else jax.lax.stop_gradient(p)

        super().__init__(shape=pshape, name=name or "parameter",
                         op=_ParamLeaf(), parents=[])


class Lambda:
    """Wrap a jnp function as a layer / graph node (reference autograd.py
    Lambda:393). Call on Variables for graph mode or arrays for eager."""

    def __init__(self, function: Callable, input_shape=None, name=None):
        self.function = function
        self.name = name or "lambda"

    def __call__(self, *xs):
        if has_variable(xs):
            return Variable(op=self.function, parents=list(xs),
                            name=self.name)
        return self.function(*xs)


class CustomLoss:
    """Build a loss from a symbolic expression over (y_true, y_pred) or keep
    a python function (reference autograd.py CustomLoss / topology losses).
    The estimator accepts it anywhere a loss is accepted."""

    def __init__(self, loss_func: Callable = None, y_pred_shape=None,
                 y_true_shape=None):
        self.loss_func = loss_func

    def __call__(self, y_true, y_pred):
        out = self.loss_func(y_true, y_pred)
        if isinstance(out, Variable):
            raise TypeError("CustomLoss function must operate on arrays "
                            "(it is traced under jit); got a Variable graph")
        return out
