from .engine.topology import Input, KerasNet, Model, Sequential
from . import activations
from . import layers
