"""Activation registry for the Keras-style API (reference string set:
pyzoo/zoo/pipeline/api/keras/layers/core.py Activation docstring)."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp


def linear(x):
    return x


def hard_sigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


_ACTIVATIONS = {
    "linear": linear,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": hard_sigmoid,
    "tanh": jnp.tanh,
    "softmax": jax.nn.softmax,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.swish,
    "silu": jax.nn.silu,
    "log_softmax": jax.nn.log_softmax,
    "exp": jnp.exp,
}


def get(activation: Optional[Union[str, Callable]]) -> Callable:
    if activation is None:
        return linear
    if callable(activation):
        return activation
    try:
        return _ACTIVATIONS[activation.lower()]
    except KeyError:
        raise ValueError(f"unknown activation {activation!r}; "
                         f"available: {sorted(_ACTIVATIONS)}")
