from .graph import Variable, keras_call, symbolic_apply
from .topology import Input, KerasNet, Model, Sequential
