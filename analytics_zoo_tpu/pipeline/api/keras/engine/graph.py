"""Symbolic graph machinery behind the functional Keras API and autograd.

The reference builds its functional graphs JVM-side: python Variables proxy
Scala nodes via py4j (reference: pyzoo/zoo/pipeline/api/autograd.py:369
``Variable``, pyzoo/zoo/pipeline/api/keras/engine/topology.py:31). Here a
Variable is a lightweight DAG node evaluated inside ONE flax module — so the
whole functional model jits to a single XLA program; there is no graph
serialization boundary.
"""

from __future__ import annotations

import functools
import itertools
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

_uid_counter = itertools.count()


class Variable:
    """A symbolic tensor: placeholder (op=None) or the output of applying a
    layer / pure function to parent Variables."""

    def __init__(self, shape: Optional[Tuple] = None, name: Optional[str] = None,
                 op: Any = None, parents: Sequence["Variable"] = (),
                 op_kwargs: Optional[dict] = None):
        _install_symbolic_dispatch()  # lazily, on first symbolic tensor
        self._uid = next(_uid_counter)
        self.shape = tuple(shape) if shape is not None else None
        self.name = name or f"var_{self._uid}"
        self.op = op                      # None | nn.Module | callable
        self.parents = list(parents)
        self.op_kwargs = op_kwargs or {}

    # --- autograd operator sugar (reference: autograd.py:32-250) ------------
    def _binop(self, other, fn, name):
        if isinstance(other, Variable):
            return Variable(op=fn, parents=[self, other], name=name)
        return Variable(op=lambda a, _o=other: fn(a, _o), parents=[self],
                        name=name)

    def __add__(self, other):
        return self._binop(other, lambda a, b: a + b, "add")

    def __radd__(self, other):
        return self._binop(other, lambda a, b: b + a, "radd")

    def __sub__(self, other):
        return self._binop(other, lambda a, b: a - b, "sub")

    def __rsub__(self, other):
        return self._binop(other, lambda a, b: b - a, "rsub")

    def __mul__(self, other):
        return self._binop(other, lambda a, b: a * b, "mul")

    def __rmul__(self, other):
        return self._binop(other, lambda a, b: b * a, "rmul")

    def __truediv__(self, other):
        return self._binop(other, lambda a, b: a / b, "div")

    def __rtruediv__(self, other):
        return self._binop(other, lambda a, b: b / a, "rdiv")

    def __neg__(self):
        return Variable(op=lambda a: -a, parents=[self], name="neg")

    def __pow__(self, p):
        return Variable(op=lambda a: a ** p, parents=[self], name="pow")

    def __getitem__(self, idx):
        return Variable(op=lambda a: a[idx], parents=[self], name="slice")

    def index_select(self, dim: int, index: int):
        """reference: autograd.py Variable.index_select"""
        return Variable(op=lambda a: jnp.take(a, index, axis=dim),
                        parents=[self], name="index_select")

    def slice(self, dim: int, start_index: int, length: int):
        return Variable(
            op=lambda a: jnp.take(a, jnp.arange(start_index,
                                                start_index + length),
                                  axis=dim),
            parents=[self], name="slice_range")


def has_variable(args) -> bool:
    return any(isinstance(a, Variable) for a in args)


def symbolic_apply(module, *args, **kwargs) -> Variable:
    """Record `module(*args)` as a graph node (all args must be Variables)."""
    parents = [a for a in args if isinstance(a, Variable)]
    if len(parents) != len(args):
        raise TypeError("mixing Variables and arrays in one call is not "
                        "supported; wrap constants with autograd ops instead")
    return Variable(op=module, parents=parents,
                    name=getattr(module, "name", None) or
                    type(module).__name__.lower(), op_kwargs=kwargs)


def keras_call(fn: Callable) -> Callable:
    """Decorator for layer ``__call__``: route Variable inputs to the symbolic
    graph, arrays to the real computation. Preserves flax's compact marker.

    flax wraps every module method at class-creation time and raises
    CallCompactUnboundModuleError before the wrapped function runs, so the
    real interception happens in ``_install_symbolic_dispatch`` below; this
    decorator stays as a second line of defence for non-flax callables."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        if has_variable(args):
            return symbolic_apply(self, *args, **kwargs)
        return fn(self, *args, **kwargs)

    return wrapper


def _install_symbolic_dispatch():
    """Teach every flax module to record itself as a graph node when called
    on symbolic Variables (unbound call with Variable args). This is what
    makes ``Dense(8)(Input(shape=(4,)))`` build a DAG — for our layers AND
    any stock flax module a user drops into the functional API.

    Installed lazily on first ``Variable`` construction, so importing the
    package never mutates flax for programs that don't use the functional
    graph API. The patch is behavior-preserving for plain flax calls: it only
    diverts when a symbolic Variable appears in the args (which cannot happen
    outside this API). If a flax release renames the internal hook, we warn
    and fall back to the ``keras_call`` decorator (our own layers still build
    graphs; stock flax modules then need an explicit ``keras_call`` wrap)."""
    global _dispatch_installed
    if _dispatch_installed:
        return
    _dispatch_installed = True
    import flax.linen as nn

    orig = getattr(nn.Module, "_call_wrapped_method", None)
    if orig is None:
        logging.getLogger("analytics_zoo_tpu").warning(
            "flax.linen.Module._call_wrapped_method not found (flax version "
            "change?); stock flax modules will not auto-record into the "
            "functional graph — wrap them with keras_call instead")
        return

    def patched(self, fun, args, kwargs):
        if has_variable(args):
            return symbolic_apply(self, *args, **kwargs)
        return orig(self, fun, args, kwargs)

    nn.Module._call_wrapped_method = patched


_dispatch_installed = False


def call_layer(layer, *xs, train: bool = False):
    """Invoke a child layer, forwarding the train flag only if it takes one."""
    import inspect
    try:
        sig = inspect.signature(type(layer).__call__)
        params = sig.parameters
    except (TypeError, ValueError):
        params = {}
    if "train" in params:
        return layer(*xs, train=train)
    if "deterministic" in params:
        return layer(*xs, deterministic=not train)
    if "training" in params:
        return layer(*xs, training=train)
    return layer(*xs)


def topo_order(outputs: Sequence[Variable]) -> List[Variable]:
    order: List[Variable] = []
    seen: Dict[int, bool] = {}

    def visit(v: Variable):
        if v._uid in seen:
            return
        seen[v._uid] = True
        for p in v.parents:
            visit(p)
        order.append(v)

    for o in outputs:
        visit(o)
    return order


def graph_modules(outputs: Sequence[Variable]):
    """Collect the unique layer modules reachable from `outputs` (dedup by
    identity so a shared instance shares weights) plus the uid→slot map.
    The functional Model stores these as flax fields so the layers become
    bound children of the graph module."""
    import flax.linen as nn

    modules: List[Any] = []
    slots: List[Tuple[int, int]] = []
    seen: Dict[int, int] = {}
    for v in topo_order(outputs):
        if isinstance(v.op, nn.Module):
            key = id(v.op)
            if key not in seen:
                seen[key] = len(modules)
                modules.append(v.op)
            slots.append((v._uid, seen[key]))
    return tuple(modules), tuple(slots)


def evaluate_graph(inputs: Sequence[Variable], outputs: Sequence[Variable],
                   xs: Sequence[Any], train: bool = False,
                   bound: Optional[Dict[int, Any]] = None):
    """Evaluate the DAG. `bound` maps node uid -> the parent-bound flax module
    to call for that node (unbound instances can't execute under linen)."""
    import flax.linen as nn

    bound = bound or {}
    cache: Dict[int, Any] = {}
    for var, x in zip(inputs, xs):
        cache[var._uid] = x
    for v in topo_order(outputs):
        if v._uid in cache:
            continue
        if v.op is None:
            raise ValueError(
                f"placeholder {v.name} is not among the model inputs")
        parent_vals = [cache[p._uid] for p in v.parents]
        if isinstance(v.op, nn.Module):
            layer = bound.get(v._uid, v.op)
            cache[v._uid] = call_layer(layer, *parent_vals, train=train,
                                       **v.op_kwargs)
        else:
            cache[v._uid] = v.op(*parent_vals, **v.op_kwargs)
    outs = tuple(cache[o._uid] for o in outputs)
    return outs[0] if len(outs) == 1 else outs
