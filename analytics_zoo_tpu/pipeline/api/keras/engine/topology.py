"""Sequential / functional Model fronts for the Keras-style API.

Parity: pyzoo/zoo/pipeline/api/keras/engine/topology.py:31-342 (KerasNet with
compile/fit/evaluate/predict, Sequential, Model over py4j). Here a model IS a
flax module — Sequential chains layers, Model evaluates the symbolic DAG from
engine/graph.py — and compile/fit route to the single TPU TrainEngine
(orca/learn/engine.py), so `Sequential().add(...).fit(x, y)` runs one jitted
XLA step over the mesh instead of the reference's py4j → DistriOptimizer hop
(SURVEY.md §3.2)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import flax.linen as nn
import numpy as np

from .graph import Variable, call_layer, evaluate_graph, graph_modules, \
    has_variable, symbolic_apply, keras_call


def Input(shape: Tuple[int, ...] = (), name: Optional[str] = None) -> Variable:
    """Symbolic placeholder; `shape` excludes the batch dim (reference
    topology.py Input)."""
    return Variable(shape=(None,) + tuple(shape), name=name or "input")


class _SequentialModule(nn.Module):
    layers: Tuple[nn.Module, ...] = ()

    @nn.compact
    def __call__(self, *xs, train: bool = False):
        if has_variable(xs):
            return symbolic_apply(self, *xs)
        x = xs[0] if len(xs) == 1 else xs
        for lyr in self.layers:
            if isinstance(x, tuple) and not isinstance(lyr, nn.Module):
                x = lyr(*x)
            else:
                x = call_layer(lyr, x, train=train) \
                    if not isinstance(x, tuple) else \
                    call_layer(lyr, *x, train=train)
        return x


class _GraphModule(nn.Module):
    inputs: Tuple[Variable, ...] = ()
    outputs: Tuple[Variable, ...] = ()
    layers: Tuple[nn.Module, ...] = ()       # adopted as children by flax
    layer_slots: Tuple[Tuple[int, int], ...] = ()  # (node uid, layer index)

    @nn.compact
    def __call__(self, *xs, train: bool = False):
        if has_variable(xs):
            return symbolic_apply(self, *xs)
        bound = {uid: self.layers[i] for uid, i in self.layer_slots}
        return evaluate_graph(self.inputs, self.outputs, xs, train=train,
                              bound=bound)


class KerasNet:
    """compile/fit/evaluate/predict surface shared by Sequential and Model.

    Mirrors reference topology.py KerasNet: compile(optimizer, loss, metrics)
    :116, fit(x, y, batch_size, nb_epoch, validation_data) :222,
    evaluate :280, predict :302 — with the estimator underneath."""

    def __init__(self):
        self._estimator = None
        self._compile_args: Dict[str, Any] = {}
        self._tb_dir: Optional[str] = None

    # -- module construction (implemented by subclasses) ---------------------
    def to_module(self) -> nn.Module:
        raise NotImplementedError

    # -- training surface ----------------------------------------------------
    def compile(self, optimizer="adam", loss="mean_squared_error",
                metrics: Optional[List] = None):
        self._compile_args = dict(optimizer=optimizer, loss=loss,
                                  metrics=metrics)
        self._estimator = None  # rebuilt lazily with the module
        return self

    @property
    def estimator(self):
        if self._estimator is None:
            from .....orca.learn.estimator import TPUEstimator
            args = self._compile_args or dict(optimizer="adam",
                                              loss="mean_squared_error",
                                              metrics=None)
            self._estimator = TPUEstimator(
                self.to_module(), loss=args["loss"],
                optimizer=args["optimizer"], metrics=args["metrics"])
            if self._tb_dir is not None:
                self._estimator.set_tensorboard(*self._tb_dir)
        return self._estimator

    def set_tensorboard(self, log_dir: str, app_name: str):
        self._tb_dir = (log_dir, app_name)
        if self._estimator is not None:
            self._estimator.set_tensorboard(log_dir, app_name)

    def get_train_summary(self, tag: str = "Loss"):
        return self.estimator.get_train_summary(tag)

    def get_validation_summary(self, tag: str):
        return self.estimator.get_validation_summary(tag)

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, distributed: bool = True, **kwargs):
        data = {"x": x, "y": y} if y is not None else x
        if validation_data is not None and isinstance(validation_data, tuple):
            validation_data = {"x": validation_data[0],
                               "y": validation_data[1]}
        return self.estimator.fit(data, epochs=nb_epoch,
                                  batch_size=batch_size,
                                  validation_data=validation_data, **kwargs)

    def evaluate(self, x, y=None, batch_size: int = 32, **kwargs):
        data = {"x": x, "y": y} if y is not None else x
        return self.estimator.evaluate(data, batch_size=batch_size, **kwargs)

    def predict(self, x, batch_size: int = 32, distributed: bool = False,
                **kwargs):
        data = {"x": x} if not isinstance(x, dict) else x
        return self.estimator.predict(data, batch_size=batch_size, **kwargs)

    def get_weights(self):
        import jax
        return jax.device_get(self.estimator.engine.params)

    def save_weights(self, path: str):
        self.estimator.save(path)

    def load_weights(self, path: str):
        self.estimator.load(path)

    def summary(self) -> str:
        mod = self.to_module()
        lines = [repr(mod)]
        text = "\n".join(lines)
        print(text)
        return text


class Sequential(KerasNet):
    """reference topology.py Sequential (py4j createZooKerasSequential)."""

    def __init__(self, layers: Optional[Sequence[nn.Module]] = None):
        super().__init__()
        self._layers: List[nn.Module] = list(layers or [])

    def add(self, layer) -> "Sequential":
        if isinstance(layer, KerasNet):
            layer = layer.to_module()
        self._layers.append(layer)
        self._estimator = None
        return self

    def to_module(self) -> nn.Module:
        return _SequentialModule(layers=tuple(self._layers))

    def __call__(self, x):
        """Symbolic or eager application of the whole stack."""
        return self.to_module()(x)


class Model(KerasNet):
    """Functional graph model (reference topology.py Model(input, output))."""

    def __init__(self, input, output):
        super().__init__()
        ins = input if isinstance(input, (list, tuple)) else [input]
        outs = output if isinstance(output, (list, tuple)) else [output]
        if not all(isinstance(v, Variable) for v in ins + outs):
            raise TypeError("Model(input, output) takes symbolic Variables "
                            "from Input(...)")
        self.inputs = tuple(ins)
        self.outputs = tuple(outs)

    def to_module(self) -> nn.Module:
        modules, slots = graph_modules(self.outputs)
        return _GraphModule(inputs=self.inputs, outputs=self.outputs,
                            layers=modules, layer_slots=slots)

    def __call__(self, *xs):
        return self.to_module()(*xs)
