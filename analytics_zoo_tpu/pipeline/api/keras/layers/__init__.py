from .core import (Activation, AddConstant, BinaryThreshold, CAdd, CMul,
                   Dense, Dropout, Exp, ExpandDim, Flatten, GaussianSampler,
                   GetShape, HardShrink, HardTanh, Highway, Identity, Log,
                   Masking, MaxoutDense, Merge, Mul, MulConstant, Narrow,
                   Negative, Permute, Power, RepeatVector, Reshape,
                   ResizeBilinear, Scale, Select, SoftShrink, SparseDense,
                   Sqrt, Square, Squeeze, Threshold, merge)
from .convolutional import (AtrousConvolution1D, AtrousConvolution2D,
                            Convolution1D, Convolution2D, Convolution3D,
                            Cropping1D, Cropping2D, Cropping3D,
                            Deconvolution2D, LocallyConnected1D,
                            LocallyConnected2D, SeparableConvolution2D,
                            ShareConvolution2D, UpSampling1D, UpSampling2D,
                            UpSampling3D, ZeroPadding1D, ZeroPadding2D,
                            ZeroPadding3D)
from .pooling import (AveragePooling1D, AveragePooling2D, AveragePooling3D,
                      GlobalAveragePooling1D, GlobalAveragePooling2D,
                      GlobalAveragePooling3D, GlobalMaxPooling1D,
                      GlobalMaxPooling2D, GlobalMaxPooling3D, MaxPooling1D,
                      MaxPooling2D, MaxPooling3D)
from .normalization import (BatchNormalization, LayerNormalization, LRN2D,
                            WithinChannelLRN2D)
from .recurrent import (Bidirectional, ConvLSTM2D, GRU, LSTM, SimpleRNN,
                        TimeDistributed)
from .embeddings import Embedding, SparseEmbedding, WordEmbedding
from .noise import (GaussianDropout, GaussianNoise, SpatialDropout1D,
                    SpatialDropout2D, SpatialDropout3D)
from .advanced_activations import (ELU, LeakyReLU, PReLU, RReLU, SReLU,
                                   ThresholdedReLU)
from .self_attention import (BERT, MultiHeadAttention, TransformerBlock,
                             TransformerLayer)

# Keras 2-style aliases (reference keras2 package)
Conv1D = Convolution1D
Conv2D = Convolution2D
Conv3D = Convolution3D
Conv2DTranspose = Deconvolution2D
SeparableConv2D = SeparableConvolution2D
