"""Advanced activation layers (parity:
pyzoo/zoo/pipeline/api/keras/layers/advanced_activations.py)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..engine.graph import keras_call


class LeakyReLU(nn.Module):
    alpha: float = 0.3
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jax.nn.leaky_relu(x, negative_slope=self.alpha)


class ELU(nn.Module):
    alpha: float = 1.0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jax.nn.elu(x, alpha=self.alpha)


class PReLU(nn.Module):
    """Learned per-channel slope (reference PReLU; nOutputPlane=0 -> shared)."""
    n_output_plane: int = 0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        shape = (1,) if self.n_output_plane == 0 else (self.n_output_plane,)
        alpha = self.param("alpha",
                           nn.initializers.constant(0.25), shape)
        if self.n_output_plane != 0:
            bshape = [1] * x.ndim
            bshape[1] = self.n_output_plane    # channel axis 1 (th)
            alpha = alpha.reshape(bshape)
        return jnp.where(x >= 0, x, alpha * x)


class ThresholdedReLU(nn.Module):
    theta: float = 1.0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.where(x > self.theta, x, 0.0)


class SReLU(nn.Module):
    """S-shaped ReLU with four learned per-feature params (reference SReLU)."""
    input_shape: Any = None
    shared_axes: Optional[Tuple[int, ...]] = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        feat = x.shape[-1]
        t_r = self.param("t_right", nn.initializers.ones, (feat,))
        a_r = self.param("a_right", nn.initializers.constant(0.2), (feat,))
        t_l = self.param("t_left", nn.initializers.zeros, (feat,))
        a_l = self.param("a_left", nn.initializers.constant(0.2), (feat,))
        above = jnp.where(x >= t_r, t_r + a_r * (x - t_r), x)
        return jnp.where(x <= t_l, t_l + a_l * (x - t_l), above)


class RReLU(nn.Module):
    """Randomized leaky ReLU: random slope in [lower, upper] at train time,
    mean slope at eval (reference advanced_activations.py RReLU)."""
    lower: float = 1.0 / 8
    upper: float = 1.0 / 3
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        if train:
            a = jax.random.uniform(self.make_rng("dropout"), x.shape,
                                   minval=self.lower, maxval=self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, a * x)
