"""Convolution/padding/cropping/upsampling layers.

Parity: pyzoo/zoo/pipeline/api/keras/layers/convolutional.py. TPU-first
deviation: internal layout is channels-last (NHWC) so XLA tiles convs onto the
MXU directly; ``dim_ordering="th"`` inputs are transposed at the layer edge
rather than propagating NCHW through the compute graph.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from .. import activations
from ..engine.graph import keras_call


def _maybe_nchw_in(x, dim_ordering, spatial):
    if dim_ordering == "th":
        return jnp.moveaxis(x, 1, -1)
    return x


def _maybe_nchw_out(x, dim_ordering):
    if dim_ordering == "th":
        return jnp.moveaxis(x, -1, 1)
    return x


def _pad_mode(border_mode: str) -> str:
    return {"same": "SAME", "valid": "VALID"}[border_mode]


class Convolution1D(nn.Module):
    """reference convolutional.py Convolution1D (input (batch, steps, dim))."""
    nb_filter: int = 1
    filter_length: int = 3
    activation: Optional[Union[str, Callable]] = None
    border_mode: str = "valid"
    subsample_length: int = 1
    dilation_rate: int = 1
    use_bias: bool = True
    init_method: str = "glorot_uniform"
    W_regularizer: Any = None
    b_regularizer: Any = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.nb_filter, (self.filter_length,),
                    strides=(self.subsample_length,),
                    kernel_dilation=(self.dilation_rate,),
                    padding=_pad_mode(self.border_mode),
                    use_bias=self.use_bias)(x)
        return activations.get(self.activation)(y)


class AtrousConvolution1D(Convolution1D):
    """reference convolutional.py AtrousConvolution1D (dilated conv)."""
    atrous_rate: int = 1

    @keras_call
    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.nb_filter, (self.filter_length,),
                    strides=(self.subsample_length,),
                    kernel_dilation=(self.atrous_rate,),
                    padding=_pad_mode(self.border_mode),
                    use_bias=self.use_bias)(x)
        return activations.get(self.activation)(y)


class Convolution2D(nn.Module):
    """reference convolutional.py Convolution2D."""
    nb_filter: int = 1
    nb_row: int = 3
    nb_col: int = 3
    activation: Optional[Union[str, Callable]] = None
    border_mode: str = "valid"
    subsample: Tuple[int, int] = (1, 1)
    dim_ordering: str = "th"
    use_bias: bool = True
    init_method: str = "glorot_uniform"
    W_regularizer: Any = None
    b_regularizer: Any = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 2)
        y = nn.Conv(self.nb_filter, (self.nb_row, self.nb_col),
                    strides=tuple(self.subsample),
                    padding=_pad_mode(self.border_mode),
                    use_bias=self.use_bias)(x)
        y = activations.get(self.activation)(y)
        return _maybe_nchw_out(y, self.dim_ordering)


class AtrousConvolution2D(Convolution2D):
    atrous_rate: Tuple[int, int] = (1, 1)

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 2)
        y = nn.Conv(self.nb_filter, (self.nb_row, self.nb_col),
                    strides=tuple(self.subsample),
                    kernel_dilation=tuple(self.atrous_rate),
                    padding=_pad_mode(self.border_mode),
                    use_bias=self.use_bias)(x)
        y = activations.get(self.activation)(y)
        return _maybe_nchw_out(y, self.dim_ordering)


class ShareConvolution2D(Convolution2D):
    """Scala ShareConvolution shares workspace memory between replicas; XLA
    owns buffers, so this is Convolution2D with the same signature."""


class Convolution3D(nn.Module):
    nb_filter: int = 1
    kernel_dim1: int = 3
    kernel_dim2: int = 3
    kernel_dim3: int = 3
    activation: Optional[Union[str, Callable]] = None
    border_mode: str = "valid"
    subsample: Tuple[int, int, int] = (1, 1, 1)
    dim_ordering: str = "th"
    use_bias: bool = True
    init_method: str = "glorot_uniform"
    W_regularizer: Any = None
    b_regularizer: Any = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 3)
        y = nn.Conv(self.nb_filter,
                    (self.kernel_dim1, self.kernel_dim2, self.kernel_dim3),
                    strides=tuple(self.subsample),
                    padding=_pad_mode(self.border_mode),
                    use_bias=self.use_bias)(x)
        y = activations.get(self.activation)(y)
        return _maybe_nchw_out(y, self.dim_ordering)


class Deconvolution2D(nn.Module):
    """Transposed conv (reference convolutional.py Deconvolution2D)."""
    nb_filter: int = 1
    nb_row: int = 3
    nb_col: int = 3
    activation: Optional[Union[str, Callable]] = None
    border_mode: str = "valid"
    subsample: Tuple[int, int] = (1, 1)
    dim_ordering: str = "th"
    use_bias: bool = True
    init_method: str = "glorot_uniform"
    output_shape: Any = None
    W_regularizer: Any = None
    b_regularizer: Any = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 2)
        y = nn.ConvTranspose(self.nb_filter, (self.nb_row, self.nb_col),
                             strides=tuple(self.subsample),
                             padding=_pad_mode(self.border_mode),
                             use_bias=self.use_bias)(x)
        y = activations.get(self.activation)(y)
        return _maybe_nchw_out(y, self.dim_ordering)


class SeparableConvolution2D(nn.Module):
    """Depthwise + pointwise conv (reference SeparableConvolution2D)."""
    nb_filter: int = 1
    nb_row: int = 3
    nb_col: int = 3
    activation: Optional[Union[str, Callable]] = None
    border_mode: str = "valid"
    subsample: Tuple[int, int] = (1, 1)
    depth_multiplier: int = 1
    dim_ordering: str = "th"
    use_bias: bool = True
    init_method: str = "glorot_uniform"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 2)
        in_ch = x.shape[-1]
        depth = nn.Conv(in_ch * self.depth_multiplier,
                        (self.nb_row, self.nb_col),
                        strides=tuple(self.subsample),
                        padding=_pad_mode(self.border_mode),
                        feature_group_count=in_ch,
                        use_bias=False)(x)
        y = nn.Conv(self.nb_filter, (1, 1), use_bias=self.use_bias)(depth)
        y = activations.get(self.activation)(y)
        return _maybe_nchw_out(y, self.dim_ordering)


class LocallyConnected1D(nn.Module):
    """Unshared-weights conv1d (reference local.py LocallyConnected1D)."""
    nb_filter: int = 1
    filter_length: int = 3
    activation: Optional[Union[str, Callable]] = None
    subsample_length: int = 1
    use_bias: bool = True
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        b, steps, dim = x.shape
        out_len = (steps - self.filter_length) // self.subsample_length + 1
        # unfold into per-position patches, per-position weights
        idx = (jnp.arange(out_len)[:, None] * self.subsample_length +
               jnp.arange(self.filter_length)[None, :])
        patches = x[:, idx, :].reshape(b, out_len,
                                       self.filter_length * dim)
        w = self.param("kernel", nn.initializers.glorot_uniform(),
                       (out_len, self.filter_length * dim, self.nb_filter))
        y = jnp.einsum("bli,lio->blo", patches, w)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (out_len, self.nb_filter))
            y = y + bias
        return activations.get(self.activation)(y)


class LocallyConnected2D(nn.Module):
    """reference local.py LocallyConnected2D (channels-last internally)."""
    nb_filter: int = 1
    nb_row: int = 3
    nb_col: int = 3
    activation: Optional[Union[str, Callable]] = None
    subsample: Tuple[int, int] = (1, 1)
    dim_ordering: str = "th"
    use_bias: bool = True
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 2)
        b, h, w, c = x.shape
        sr, sc = self.subsample
        oh = (h - self.nb_row) // sr + 1
        ow = (w - self.nb_col) // sc + 1
        ri = (jnp.arange(oh)[:, None] * sr + jnp.arange(self.nb_row)[None, :])
        ci = (jnp.arange(ow)[:, None] * sc + jnp.arange(self.nb_col)[None, :])
        patches = x[:, ri[:, None, :, None], ci[None, :, None, :], :]
        patches = patches.reshape(b, oh, ow, self.nb_row * self.nb_col * c)
        wgt = self.param("kernel", nn.initializers.glorot_uniform(),
                         (oh, ow, self.nb_row * self.nb_col * c,
                          self.nb_filter))
        y = jnp.einsum("bhwi,hwio->bhwo", patches, wgt)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (oh, ow, self.nb_filter))
            y = y + bias
        y = activations.get(self.activation)(y)
        return _maybe_nchw_out(y, self.dim_ordering)


class Cropping1D(nn.Module):
    cropping: Tuple[int, int] = (1, 1)
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        a, b = self.cropping
        return x[:, a:x.shape[1] - b, :]


class Cropping2D(nn.Module):
    cropping: Tuple[Tuple[int, int], Tuple[int, int]] = ((0, 0), (0, 0))
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        (t, bm), (l, r) = self.cropping
        if self.dim_ordering == "th":
            return x[:, :, t:x.shape[2] - bm, l:x.shape[3] - r]
        return x[:, t:x.shape[1] - bm, l:x.shape[2] - r, :]


class Cropping3D(nn.Module):
    cropping: Tuple = ((1, 1), (1, 1), (1, 1))
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        (a1, b1), (a2, b2), (a3, b3) = self.cropping
        if self.dim_ordering == "th":
            return x[:, :, a1:x.shape[2] - b1, a2:x.shape[3] - b2,
                     a3:x.shape[4] - b3]
        return x[:, a1:x.shape[1] - b1, a2:x.shape[2] - b2,
                 a3:x.shape[3] - b3, :]


def _zero_pad(x, pads, dim_ordering, spatial_ndim):
    cfg = [(0, 0)] * x.ndim
    start = 2 if dim_ordering == "th" else 1
    for i, (a, b) in enumerate(pads):
        cfg[start + i] = (a, b)
    return jnp.pad(x, cfg)


class ZeroPadding1D(nn.Module):
    padding: Union[int, Tuple[int, int]] = 1
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        p = self.padding
        p = (p, p) if isinstance(p, int) else tuple(p)
        return jnp.pad(x, ((0, 0), p, (0, 0)))


class ZeroPadding2D(nn.Module):
    padding: Tuple = (1, 1)
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        p = self.padding
        pads = ((p[0], p[0]), (p[1], p[1])) if len(p) == 2 else \
            ((p[0], p[1]), (p[2], p[3]))
        return _zero_pad(x, pads, self.dim_ordering, 2)


class ZeroPadding3D(nn.Module):
    padding: Tuple[int, int, int] = (1, 1, 1)
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        p = self.padding
        pads = ((p[0], p[0]), (p[1], p[1]), (p[2], p[2]))
        return _zero_pad(x, pads, self.dim_ordering, 3)


def _upsample(x, factors, start_axis):
    for i, f in enumerate(factors):
        x = jnp.repeat(x, f, axis=start_axis + i)
    return x


class UpSampling1D(nn.Module):
    length: int = 2
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return _upsample(x, (self.length,), 1)


class UpSampling2D(nn.Module):
    size: Tuple[int, int] = (2, 2)
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        start = 2 if self.dim_ordering == "th" else 1
        return _upsample(x, tuple(self.size), start)


class UpSampling3D(nn.Module):
    size: Tuple[int, int, int] = (2, 2, 2)
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        start = 2 if self.dim_ordering == "th" else 1
        return _upsample(x, tuple(self.size), start)
