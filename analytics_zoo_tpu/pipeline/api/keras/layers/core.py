"""Core Keras-style layers as flax modules.

Parity targets: pyzoo/zoo/pipeline/api/keras/layers/core.py (Dense, Dropout,
Activation, Flatten, Reshape, Permute, RepeatVector, Masking, Highway,
MaxoutDense, math layers, …). Each layer is an ordinary flax ``nn.Module`` —
the keras_call decorator additionally lets it participate in the symbolic
functional graph (engine/graph.py), so ``layer(Input(...))`` builds a DAG
while ``layer(array)`` computes. Weight layout/initialisers follow flax
conventions, not BigDL's.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from .. import activations
from ..engine.graph import keras_call

Dtype = Any


def _regularizer(_):
    # L1/L2 regularisers are handled by optimizer weight-decay in this stack
    # (optax.add_decayed_weights); layer args are accepted for API parity.
    return None


class Dense(nn.Module):
    """reference: pyzoo/zoo/pipeline/api/keras/layers/core.py Dense"""
    output_dim: int
    activation: Optional[Union[str, Callable]] = None
    use_bias: bool = True
    init_method: str = "glorot_uniform"
    W_regularizer: Any = None
    b_regularizer: Any = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        kernel_init = (nn.initializers.glorot_uniform()
                       if self.init_method == "glorot_uniform"
                       else nn.initializers.lecun_normal())
        y = nn.Dense(self.output_dim, use_bias=self.use_bias,
                     kernel_init=kernel_init)(x)
        return activations.get(self.activation)(y)


class SparseDense(Dense):
    """reference core.py SparseDense — dense math; XLA has no sparse matmul
    on TPU, embeddings cover the sparse-input use case."""


class Activation(nn.Module):
    activation: Union[str, Callable] = "relu"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return activations.get(self.activation)(x)


class Dropout(nn.Module):
    """reference core.py Dropout (p = drop fraction)."""
    p: float = 0.5
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        return nn.Dropout(rate=self.p, deterministic=not train)(x)


class Flatten(nn.Module):
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return x.reshape(x.shape[0], -1)


class Reshape(nn.Module):
    """target_shape may contain one -1 (inferred), like the reference."""
    target_shape: Tuple[int, ...] = ()
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return x.reshape((x.shape[0],) + tuple(self.target_shape))


class Permute(nn.Module):
    """dims are 1-indexed over non-batch axes, matching the reference."""
    dims: Tuple[int, ...] = ()
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.transpose(x, (0,) + tuple(self.dims))


class RepeatVector(nn.Module):
    n: int = 1
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.repeat(x[:, None, :], self.n, axis=1)


class Masking(nn.Module):
    """Zeroes timesteps equal to mask_value (downstream layers see zeros; the
    engine's loss masking covers the metric side)."""
    mask_value: float = 0.0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return x * keep.astype(x.dtype)


class Highway(nn.Module):
    """reference core.py Highway: y = t * h(Wx) + (1-t) * x"""
    activation: Optional[Union[str, Callable]] = None
    use_bias: bool = True
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        dim = x.shape[-1]
        h = activations.get(self.activation)(
            nn.Dense(dim, use_bias=self.use_bias)(x))
        t = jax.nn.sigmoid(nn.Dense(dim, use_bias=self.use_bias)(x))
        return t * h + (1.0 - t) * x


class MaxoutDense(nn.Module):
    """reference core.py MaxoutDense: max over nb_feature linear maps."""
    output_dim: int = 1
    nb_feature: int = 4
    use_bias: bool = True
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        y = nn.Dense(self.output_dim * self.nb_feature,
                     use_bias=self.use_bias)(x)
        y = y.reshape(y.shape[:-1] + (self.nb_feature, self.output_dim))
        return jnp.max(y, axis=-2)


class _Elementwise(nn.Module):
    input_shape: Any = None

    def fn(self, x):
        raise NotImplementedError

    @keras_call
    @nn.compact
    def __call__(self, x):
        return self.fn(x)


class Exp(_Elementwise):
    def fn(self, x):
        return jnp.exp(x)


class Log(_Elementwise):
    def fn(self, x):
        return jnp.log(x)


class Sqrt(_Elementwise):
    def fn(self, x):
        return jnp.sqrt(x)


class Square(_Elementwise):
    def fn(self, x):
        return jnp.square(x)


class Negative(_Elementwise):
    def fn(self, x):
        return -x


class Identity(_Elementwise):
    def fn(self, x):
        return x


class AddConstant(nn.Module):
    constant: float = 0.0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return x + self.constant


class MulConstant(nn.Module):
    constant: float = 1.0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return x * self.constant


class Power(nn.Module):
    """reference core.py Power: (shift + scale * x) ** power"""
    power: float = 1.0
    scale: float = 1.0
    shift: float = 0.0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return (self.shift + self.scale * x) ** self.power


class Scale(nn.Module):
    """Learned per-feature affine: x * w + b (reference core.py Scale)."""
    axis: int = -1
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        dim = x.shape[self.axis]
        shape = [1] * x.ndim
        shape[self.axis] = dim
        w = self.param("weight", nn.initializers.ones, tuple(shape))
        b = self.param("bias", nn.initializers.zeros, tuple(shape))
        return x * w + b


class CAdd(nn.Module):
    """Learned additive bias of arbitrary broadcast shape (reference CAdd)."""
    size: Tuple[int, ...] = ()
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        b = self.param("bias", nn.initializers.zeros, tuple(self.size))
        return x + b


class CMul(nn.Module):
    size: Tuple[int, ...] = ()
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, tuple(self.size))
        return x * w


class Mul(nn.Module):
    """Single learned scalar multiplier (reference core.py Mul)."""
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (1,))
        return x * w


class Select(nn.Module):
    """Select index `index` along dim `dim` (non-batch 1-indexed in the
    reference; here dim counts all axes, negative allowed)."""
    dim: int = 1
    index: int = 0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.take(x, self.index, axis=self.dim)


class Squeeze(nn.Module):
    dim: Optional[Union[int, Tuple[int, ...]]] = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.squeeze(x, axis=self.dim)


class ExpandDim(nn.Module):
    dim: int = 0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.expand_dims(x, axis=self.dim)


class Narrow(nn.Module):
    """Slice `length` elements from `offset` along `dim` (reference Narrow)."""
    dim: int = 1
    offset: int = 0
    length: int = 1
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jax.lax.slice_in_dim(x, self.offset, self.offset + self.length,
                                    axis=self.dim)


class GetShape(nn.Module):
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.asarray(x.shape)


class Threshold(nn.Module):
    """x if x > th else v (reference core.py Threshold)."""
    th: float = 1e-6
    v: float = 0.0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.where(x > self.th, x, self.v)


class BinaryThreshold(nn.Module):
    value: float = 1e-6
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return (x > self.value).astype(jnp.float32)


class HardTanh(nn.Module):
    min_value: float = -1.0
    max_value: float = 1.0
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class HardShrink(nn.Module):
    value: float = 0.5
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.where(jnp.abs(x) > self.value, x, 0.0)


class SoftShrink(nn.Module):
    value: float = 0.5
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.value, 0.0)


class GaussianSampler(nn.Module):
    """VAE reparameterisation: input [mean, log_var] -> sample (reference
    core.py GaussianSampler; takes a table of two tensors)."""
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, mean_logvar, train: bool = False):
        mean, log_var = mean_logvar
        if not train:
            return mean
        eps = jax.random.normal(self.make_rng("dropout"), mean.shape)
        return mean + jnp.exp(0.5 * log_var) * eps


class Merge(nn.Module):
    """Merge a list of inputs: mode in sum/mul/concat/ave/max/min/dot/cos
    (reference engine/topology.py Merge)."""
    mode: str = "sum"
    concat_axis: int = -1
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, *xs):
        if len(xs) == 1 and isinstance(xs[0], (list, tuple)):
            xs = tuple(xs[0])
        m = self.mode
        if m == "concat":
            return jnp.concatenate(xs, axis=self.concat_axis)
        if m == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if m == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if m == "ave":
            return sum(xs) / len(xs)
        if m == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if m == "min":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.minimum(out, x)
            return out
        if m == "dot":
            a, b = xs
            return jnp.sum(a * b, axis=-1, keepdims=True)
        if m == "cos":
            a, b = xs
            num = jnp.sum(a * b, axis=-1, keepdims=True)
            den = (jnp.linalg.norm(a, axis=-1, keepdims=True) *
                   jnp.linalg.norm(b, axis=-1, keepdims=True))
            return num / jnp.maximum(den, 1e-8)
        raise ValueError(f"unknown merge mode {m!r}")


def merge(inputs: Sequence[Any], mode: str = "sum", concat_axis: int = -1,
          name: Optional[str] = None):
    """Functional merge over symbolic Variables or arrays."""
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(*inputs)


class ResizeBilinear(nn.Module):
    output_height: int = 0
    output_width: int = 0
    align_corners: bool = False
    data_format: str = "channels_last"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        if self.data_format == "channels_first":
            x = jnp.moveaxis(x, 1, -1)
        out = jax.image.resize(
            x, (x.shape[0], self.output_height, self.output_width, x.shape[3]),
            method="bilinear")
        if self.data_format == "channels_first":
            out = jnp.moveaxis(out, -1, 1)
        return out
