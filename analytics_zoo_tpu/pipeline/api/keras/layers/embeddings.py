"""Embedding layers (parity: pyzoo/zoo/pipeline/api/keras/layers/embeddings.py
Embedding/SparseEmbedding and WordEmbedding from the Scala layer set).

TPU note: embedding lookup is a gather from an HBM-resident table; keep the
table bfloat16 for bandwidth when large. Pretrained-weight loading takes a
numpy array directly instead of the reference's GloVe-file JVM loader."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn

from .....ops.embedding import embedding_lookup
import jax
import jax.numpy as jnp
import numpy as np

from ..engine.graph import keras_call


class Embedding(nn.Module):
    input_dim: int = 1
    output_dim: int = 1
    init_method: str = "uniform"
    weights: Any = None              # optional pretrained ndarray
    trainable: bool = True
    input_shape: Any = None
    zero_based_id: bool = True
    dtype: Any = jnp.float32

    @keras_call
    @nn.compact
    def __call__(self, x):
        if self.weights is not None:
            init = lambda rng, shape, dtype=self.dtype: jnp.asarray(
                np.asarray(self.weights), dtype)
        elif self.init_method == "uniform":
            init = nn.initializers.uniform(scale=0.05)
        else:
            init = nn.initializers.normal(stddev=0.05)
        table = self.param("embedding", init,
                           (self.input_dim, self.output_dim), self.dtype)
        idx = x.astype(jnp.int32)
        if not self.zero_based_id:
            idx = idx - 1
        out = embedding_lookup(table, jnp.clip(idx, 0, self.input_dim - 1))
        if not self.trainable:
            out = jax.lax.stop_gradient(out)
        return out


class SparseEmbedding(Embedding):
    """reference embeddings.py SparseEmbedding — on TPU the lookup is the same
    gather; sparsity of the input doesn't change the kernel."""


class WordEmbedding(nn.Module):
    """Frozen pretrained word embeddings (Scala keras/layers/WordEmbedding).
    Construct via ``WordEmbedding.from_glove(path, word_index)`` or pass the
    matrix directly."""
    embedding_matrix: Any = None
    trainable: bool = False
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        mat = np.asarray(self.embedding_matrix)
        table = self.param(
            "embedding",
            lambda rng, shape: jnp.asarray(mat, jnp.float32), mat.shape)
        out = jnp.take(table, x.astype(jnp.int32), axis=0)
        return out if self.trainable else jax.lax.stop_gradient(out)

    @staticmethod
    def get_word_index(glove_path: str):
        idx = {}
        with open(glove_path, "r", encoding="utf-8") as f:
            for i, line in enumerate(f):
                idx[line.split(" ", 1)[0]] = i + 1
        return idx

    @classmethod
    def from_glove(cls, glove_path: str, word_index: Optional[dict] = None,
                   trainable: bool = False):
        vecs = {}
        with open(glove_path, "r", encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip().split(" ")
                vecs[parts[0]] = np.asarray(parts[1:], dtype=np.float32)
        dim = len(next(iter(vecs.values())))
        word_index = word_index or {w: i + 1 for i, w in enumerate(vecs)}
        mat = np.zeros((max(word_index.values()) + 1, dim), np.float32)
        for w, i in word_index.items():
            if w in vecs:
                mat[i] = vecs[w]
        return cls(embedding_matrix=mat, trainable=trainable)
