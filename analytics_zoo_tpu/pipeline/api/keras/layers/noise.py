"""Noise / stochastic-regularisation layers (parity:
pyzoo/zoo/pipeline/api/keras/layers/noise.py + SpatialDropout from core)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..engine.graph import keras_call


class GaussianNoise(nn.Module):
    sigma: float = 0.1
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train:
            return x
        noise = jax.random.normal(self.make_rng("dropout"), x.shape, x.dtype)
        return x + self.sigma * noise


class GaussianDropout(nn.Module):
    p: float = 0.5
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train or self.p <= 0:
            return x
        stddev = (self.p / (1.0 - self.p)) ** 0.5
        noise = jax.random.normal(self.make_rng("dropout"), x.shape, x.dtype)
        return x * (1.0 + stddev * noise)


def _spatial_dropout(x, rate, rng, broadcast_axes):
    keep = 1.0 - rate
    shape = [x.shape[i] if i not in broadcast_axes else 1
             for i in range(x.ndim)]
    mask = jax.random.bernoulli(rng, keep, tuple(shape)).astype(x.dtype)
    return x * mask / keep


class SpatialDropout1D(nn.Module):
    """Drops whole feature maps: input (batch, steps, channels)."""
    p: float = 0.5
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train or self.p <= 0:
            return x
        return _spatial_dropout(x, self.p, self.make_rng("dropout"), (1,))


class SpatialDropout2D(nn.Module):
    p: float = 0.5
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train or self.p <= 0:
            return x
        axes = (2, 3) if self.dim_ordering == "th" else (1, 2)
        return _spatial_dropout(x, self.p, self.make_rng("dropout"), axes)


class SpatialDropout3D(nn.Module):
    p: float = 0.5
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        if not train or self.p <= 0:
            return x
        axes = (2, 3, 4) if self.dim_ordering == "th" else (1, 2, 3)
        return _spatial_dropout(x, self.p, self.make_rng("dropout"), axes)
