"""Normalization layers (parity: pyzoo/zoo/pipeline/api/keras/layers/
normalization.py). BatchNormalization keeps running stats in flax's
``batch_stats`` collection, which the TrainEngine threads as mutable extra
vars; on a mesh, flax's use_running_average path plus the engine's psum of
batch stats gives cross-replica behavior."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..engine.graph import keras_call


class BatchNormalization(nn.Module):
    epsilon: float = 1e-3
    momentum: float = 0.99
    beta_init: str = "zero"
    gamma_init: str = "one"
    dim_ordering: str = "th"
    axis: Optional[int] = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        # reference default: channel axis 1 for th ordering on 4D inputs.
        if self.axis is not None:
            axis = self.axis
        elif self.dim_ordering == "th" and x.ndim == 4:
            axis = 1
        else:
            axis = -1
        return nn.BatchNorm(use_running_average=not train,
                            momentum=self.momentum, epsilon=self.epsilon,
                            axis=axis)(x)


class LayerNormalization(nn.Module):
    """Used by Transformer/BERT blocks (Scala: keras/layers/InternalLayerNorm)."""
    epsilon: float = 1e-6
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return nn.LayerNorm(epsilon=self.epsilon)(x)


class LRN2D(nn.Module):
    """Local response normalization across channels (reference LRN2D)."""
    alpha: float = 1e-4
    k: float = 1.0
    beta: float = 0.75
    n: int = 5
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        ch_axis = 1 if self.dim_ordering == "th" else -1
        xc = jnp.moveaxis(x, ch_axis, -1)
        sq = jnp.square(xc)
        half = self.n // 2
        pads = [(0, 0)] * (xc.ndim - 1) + [(half, half)]
        padded = jnp.pad(sq, pads)
        acc = jnp.zeros_like(xc)
        for i in range(self.n):
            acc = acc + lax.slice_in_dim(padded, i, i + xc.shape[-1],
                                         axis=xc.ndim - 1)
        out = xc / jnp.power(self.k + self.alpha * acc, self.beta)
        return jnp.moveaxis(out, -1, ch_axis)


class WithinChannelLRN2D(nn.Module):
    """Spatial (within-channel) LRN (reference WithinChannelLRN2D)."""
    size: int = 5
    alpha: float = 1.0
    beta: float = 0.75
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        # channels-first spatial smoothing over size×size windows
        sq = jnp.square(x)
        win = self.size
        avg = nn.avg_pool(jnp.moveaxis(sq, 1, -1), (win, win),
                          strides=(1, 1), padding="SAME")
        avg = jnp.moveaxis(avg, -1, 1)
        return x / jnp.power(1.0 + (self.alpha / (win * win)) * avg,
                             self.beta)
