"""Pooling layers (parity: pyzoo/zoo/pipeline/api/keras/layers/pooling.py).
Channels-last internally; ``dim_ordering="th"`` transposed at the edges."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from ..engine.graph import keras_call
from .convolutional import _maybe_nchw_in, _maybe_nchw_out, _pad_mode


class _Pool(nn.Module):
    pool_fn: str = "max"          # "max" | "avg"
    window: Tuple[int, ...] = (2,)
    strides: Optional[Tuple[int, ...]] = None
    border_mode: str = "valid"
    dim_ordering: str = "th"
    input_shape: Any = None

    def _run(self, x):
        strides = tuple(self.strides or self.window)
        fn = nn.max_pool if self.pool_fn == "max" else nn.avg_pool
        return fn(x, tuple(self.window), strides=strides,
                  padding=_pad_mode(self.border_mode))


class MaxPooling1D(_Pool):
    pool_length: int = 2
    stride: Optional[int] = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return nn.max_pool(x, (self.pool_length,),
                           strides=(self.stride or self.pool_length,),
                           padding=_pad_mode(self.border_mode))


class AveragePooling1D(_Pool):
    pool_length: int = 2
    stride: Optional[int] = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        return nn.avg_pool(x, (self.pool_length,),
                           strides=(self.stride or self.pool_length,),
                           padding=_pad_mode(self.border_mode))


class MaxPooling2D(nn.Module):
    pool_size: Tuple[int, int] = (2, 2)
    strides: Optional[Tuple[int, int]] = None
    border_mode: str = "valid"
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 2)
        y = nn.max_pool(x, tuple(self.pool_size),
                        strides=tuple(self.strides or self.pool_size),
                        padding=_pad_mode(self.border_mode))
        return _maybe_nchw_out(y, self.dim_ordering)


class AveragePooling2D(nn.Module):
    pool_size: Tuple[int, int] = (2, 2)
    strides: Optional[Tuple[int, int]] = None
    border_mode: str = "valid"
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 2)
        y = nn.avg_pool(x, tuple(self.pool_size),
                        strides=tuple(self.strides or self.pool_size),
                        padding=_pad_mode(self.border_mode))
        return _maybe_nchw_out(y, self.dim_ordering)


class MaxPooling3D(nn.Module):
    pool_size: Tuple[int, int, int] = (2, 2, 2)
    strides: Optional[Tuple[int, int, int]] = None
    border_mode: str = "valid"
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 3)
        y = nn.max_pool(x, tuple(self.pool_size),
                        strides=tuple(self.strides or self.pool_size),
                        padding=_pad_mode(self.border_mode))
        return _maybe_nchw_out(y, self.dim_ordering)


class AveragePooling3D(nn.Module):
    pool_size: Tuple[int, int, int] = (2, 2, 2)
    strides: Optional[Tuple[int, int, int]] = None
    border_mode: str = "valid"
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        x = _maybe_nchw_in(x, self.dim_ordering, 3)
        y = nn.avg_pool(x, tuple(self.pool_size),
                        strides=tuple(self.strides or self.pool_size),
                        padding=_pad_mode(self.border_mode))
        return _maybe_nchw_out(y, self.dim_ordering)


class _GlobalPool(nn.Module):
    dim_ordering: str = "th"
    input_shape: Any = None
    _reduce: str = "max"
    _spatial: int = 2

    @keras_call
    @nn.compact
    def __call__(self, x):
        if self.dim_ordering == "th" and x.ndim > 3:
            axes = tuple(range(2, x.ndim))
        elif x.ndim > 3:
            axes = tuple(range(1, x.ndim - 1))
        else:  # 1D case: (batch, steps, dim)
            axes = (1,)
        fn = jnp.max if self._reduce == "max" else jnp.mean
        return fn(x, axis=axes)


class GlobalMaxPooling1D(_GlobalPool):
    _reduce: str = "max"


class GlobalAveragePooling1D(_GlobalPool):
    _reduce: str = "mean"


class GlobalMaxPooling2D(_GlobalPool):
    _reduce: str = "max"


class GlobalAveragePooling2D(_GlobalPool):
    _reduce: str = "mean"


class GlobalMaxPooling3D(_GlobalPool):
    _reduce: str = "max"


class GlobalAveragePooling3D(_GlobalPool):
    _reduce: str = "mean"
