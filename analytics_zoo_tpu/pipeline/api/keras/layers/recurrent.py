"""Recurrent layers (parity: pyzoo/zoo/pipeline/api/keras/layers/recurrent.py
SimpleRNN/LSTM/GRU, convolutional_recurrent.py ConvLSTM2D, wrappers.py
Bidirectional/TimeDistributed).

TPU-first: the time loop is a ``flax.linen.scan`` — one compiled cell body,
XLA unrolls nothing, activations stream through VMEM. Static sequence length
(XLA requirement); ragged batches are pad-and-masked by the data layer.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from .. import activations
from ..engine.graph import call_layer, keras_call


class SimpleRNN(nn.Module):
    """reference recurrent.py SimpleRNN."""
    output_dim: int = 1
    activation: Union[str, Callable] = "tanh"
    return_sequences: bool = False
    go_backwards: bool = False
    W_regularizer: Any = None
    U_regularizer: Any = None
    b_regularizer: Any = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        act = activations.get(self.activation)
        cell = nn.SimpleCell(features=self.output_dim, activation_fn=act)
        out = nn.RNN(cell, reverse=self.go_backwards, keep_order=True)(x)
        if self.return_sequences:
            return out
        # keep_order=True returns outputs in input order, so the final
        # processed step sits at index 0 when scanning backwards.
        return out[:, 0, :] if self.go_backwards else out[:, -1, :]


class LSTM(nn.Module):
    """reference recurrent.py LSTM."""
    output_dim: int = 1
    activation: Union[str, Callable] = "tanh"
    inner_activation: Union[str, Callable] = "hard_sigmoid"
    return_sequences: bool = False
    go_backwards: bool = False
    W_regularizer: Any = None
    U_regularizer: Any = None
    b_regularizer: Any = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        cell = nn.LSTMCell(
            features=self.output_dim,
            activation_fn=activations.get(self.activation),
            gate_fn=activations.get(self.inner_activation))
        out = nn.RNN(cell, reverse=self.go_backwards, keep_order=True)(x)
        if self.return_sequences:
            return out
        # keep_order=True returns outputs in input order, so the final
        # processed step sits at index 0 when scanning backwards.
        return out[:, 0, :] if self.go_backwards else out[:, -1, :]


class GRU(nn.Module):
    """reference recurrent.py GRU."""
    output_dim: int = 1
    activation: Union[str, Callable] = "tanh"
    inner_activation: Union[str, Callable] = "hard_sigmoid"
    return_sequences: bool = False
    go_backwards: bool = False
    W_regularizer: Any = None
    U_regularizer: Any = None
    b_regularizer: Any = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        cell = nn.GRUCell(
            features=self.output_dim,
            activation_fn=activations.get(self.activation),
            gate_fn=activations.get(self.inner_activation))
        out = nn.RNN(cell, reverse=self.go_backwards, keep_order=True)(x)
        if self.return_sequences:
            return out
        # keep_order=True returns outputs in input order, so the final
        # processed step sits at index 0 when scanning backwards.
        return out[:, 0, :] if self.go_backwards else out[:, -1, :]


class ConvLSTM2D(nn.Module):
    """reference convolutional_recurrent.py ConvLSTM2D. Input
    (batch, time, rows, cols, channels) channels-last (th inputs: transpose
    upstream). Square kernel like the reference (nb_kernel)."""
    nb_filter: int = 1
    nb_kernel: int = 3
    return_sequences: bool = False
    go_backwards: bool = False
    border_mode: str = "same"
    subsample: Tuple[int, int] = (1, 1)
    dim_ordering: str = "th"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        if self.dim_ordering == "th":       # (b, t, c, h, w) -> (b, t, h, w, c)
            x = jnp.moveaxis(x, 2, -1)
        cell = nn.ConvLSTMCell(features=self.nb_filter,
                               kernel_size=(self.nb_kernel, self.nb_kernel))
        out = nn.RNN(cell, reverse=self.go_backwards, keep_order=True)(x)
        if not self.return_sequences:
            out = out[:, 0] if self.go_backwards else out[:, -1]
            if self.dim_ordering == "th":
                out = jnp.moveaxis(out, -1, 1)
            return out
        if self.dim_ordering == "th":
            out = jnp.moveaxis(out, -1, 2)
        return out


class Bidirectional(nn.Module):
    """reference wrappers.py Bidirectional: merge_mode concat/sum/mul/ave."""
    layer: nn.Module = None
    merge_mode: str = "concat"
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x):
        import dataclasses
        fwd = self.layer
        bwd = dataclasses.replace(self.layer, go_backwards=True,
                                  name=(self.layer.name or "rnn") + "_bwd")
        yf = call_layer(fwd, x)
        yb = call_layer(bwd, x)
        if self.merge_mode == "concat":
            return jnp.concatenate([yf, yb], axis=-1)
        if self.merge_mode == "sum":
            return yf + yb
        if self.merge_mode == "mul":
            return yf * yb
        if self.merge_mode == "ave":
            return (yf + yb) / 2.0
        raise ValueError(f"unknown merge_mode {self.merge_mode!r}")


class TimeDistributed(nn.Module):
    """reference wrappers.py TimeDistributed: apply a layer to every timestep.
    Uses one set of params shared over time (folded batch dims), exactly the
    XLA-friendly formulation."""
    layer: nn.Module = None
    input_shape: Any = None

    @keras_call
    @nn.compact
    def __call__(self, x, train: bool = False):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y = call_layer(self.layer, flat, train=train)
        return y.reshape((b, t) + y.shape[1:])
