"""Transformer / BERT layers (parity: pyzoo/zoo/pipeline/api/keras/layers/
self_attention.py TransformerLayer:386 and BERT; Scala
zoo/.../keras/layers/BERT.scala:402).

TPU-first: attention routes through ops/attention.py — the Pallas flash
kernel on-chip — and can shard the sequence over the mesh's ``sp`` axis with
ring or Ulysses attention (parallel/ring_attention.py), which the reference
cannot do at all (SURVEY.md §2.3 "Long-context/SP: ABSENT")."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn

from .....ops.embedding import MXUEmbed
import jax
import jax.numpy as jnp

from analytics_zoo_tpu.ops.attention import flash_attention, mha_reference
from ..engine.graph import keras_call


class MultiHeadAttention(nn.Module):
    """Projections + attention core with a pluggable strategy:
    ``full`` | ``flash`` | ``ring`` | ``ulysses`` (the last two run under an
    ``sp``-mapped shard_map context)."""
    n_head: int = 12
    hidden_size: int = 768
    attn_dropout: float = 0.0
    causal: bool = False
    strategy: str = "flash"
    sp_axis: str = "sp"

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        b, s, _ = x.shape
        h, hs = self.n_head, self.hidden_size
        d = hs // h
        qkv = nn.Dense(3 * hs, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, h, d)
        k = k.reshape(b, s, h, d)
        v = v.reshape(b, s, h, d)
        if self.strategy == "ring":
            from analytics_zoo_tpu.parallel.ring_attention import ring_attention
            out = ring_attention(q, k, v, axis_name=self.sp_axis,
                                 causal=self.causal)
        elif self.strategy == "ulysses":
            from analytics_zoo_tpu.parallel.ring_attention import ulysses_attention
            out = ulysses_attention(q, k, v, axis_name=self.sp_axis,
                                    causal=self.causal)
        elif self.strategy == "flash" and mask is None:
            out = flash_attention(q, k, v, causal=self.causal)
        else:
            bias = None
            if mask is not None:
                # mask: (b, s) 1=keep -> additive bias broadcast over heads
                bias = (1.0 - mask[:, None, None, :]) * -1e9
            out = mha_reference(q, k, v, causal=self.causal, bias=bias)
        out = out.reshape(b, s, hs)
        out = nn.Dense(hs, name="proj")(out)
        if self.attn_dropout:
            out = nn.Dropout(self.attn_dropout, deterministic=not train)(out)
        return out


class TransformerBlock(nn.Module):
    n_head: int = 12
    hidden_size: int = 768
    intermediate_size: int = 3072
    hidden_drop: float = 0.1
    attn_drop: float = 0.1
    causal: bool = False
    after_norm: bool = True          # BERT-style post-norm like the reference
    activation: str = "gelu"
    strategy: str = "flash"

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        attn = MultiHeadAttention(
            n_head=self.n_head, hidden_size=self.hidden_size,
            attn_dropout=self.attn_drop, causal=self.causal,
            strategy=self.strategy, name="attention")(x, mask, train=train)
        if self.hidden_drop:
            attn = nn.Dropout(self.hidden_drop,
                              deterministic=not train)(attn)
        x = nn.LayerNorm(epsilon=1e-5, name="norm1")(x + attn)
        act = (jax.nn.gelu if self.activation == "gelu" else jax.nn.relu)
        ff = nn.Dense(self.intermediate_size, name="ffn_in")(x)
        ff = act(ff)
        ff = nn.Dense(self.hidden_size, name="ffn_out")(ff)
        if self.hidden_drop:
            ff = nn.Dropout(self.hidden_drop, deterministic=not train)(ff)
        return nn.LayerNorm(epsilon=1e-5, name="norm2")(x + ff)


class TransformerLayer(nn.Module):
    """GPT-style decoder stack (reference self_attention.py TransformerLayer:
    init(vocab, seq_len, n_block, ...)). Input: int token ids (b, s) or
    (b, s) + position ids; output: (b, s, hidden)."""
    vocab: int = 40990
    seq_len: int = 77
    n_block: int = 12
    n_head: int = 12
    hidden_size: int = 768
    intermediate_size: Optional[int] = None
    hidden_drop: float = 0.1
    attn_drop: float = 0.1
    embedding_drop: float = 0.1
    mask_attention: bool = True
    strategy: str = "flash"

    @keras_call
    @nn.compact
    def __call__(self, ids, train: bool = False):
        hs = self.hidden_size
        tok = MXUEmbed(self.vocab, hs, name="token_embedding")(
            ids.astype(jnp.int32))
        pos = self.param("position_embedding",
                         nn.initializers.normal(0.02), (self.seq_len, hs))
        x = tok + pos[None, :tok.shape[1]]
        if self.embedding_drop:
            x = nn.Dropout(self.embedding_drop, deterministic=not train)(x)
        inter = self.intermediate_size or 4 * hs
        for i in range(self.n_block):
            x = TransformerBlock(
                n_head=self.n_head, hidden_size=hs, intermediate_size=inter,
                hidden_drop=self.hidden_drop, attn_drop=self.attn_drop,
                causal=self.mask_attention, strategy=self.strategy,
                name=f"block_{i}")(x, train=train)
        return x


class BERT(nn.Module):
    """BERT encoder (reference self_attention.py BERT / BERT.scala:402).
    Inputs: token ids, token type ids, optional attention mask (1=keep).
    Returns (sequence_output, pooled_output)."""
    vocab: int = 40990
    hidden_size: int = 768
    n_block: int = 12
    n_head: int = 12
    seq_len: int = 512
    intermediate_size: int = 3072
    hidden_p_drop: float = 0.1
    attn_p_drop: float = 0.1
    strategy: str = "flash"

    @keras_call
    @nn.compact
    def __call__(self, ids, token_type_ids=None, attention_mask=None,
                 train: bool = False):
        hs = self.hidden_size
        ids = ids.astype(jnp.int32)
        tok = MXUEmbed(self.vocab, hs, name="token_embedding")(ids)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(ids)
        seg = MXUEmbed(2, hs, name="segment_embedding")(
            token_type_ids.astype(jnp.int32))
        pos = self.param("position_embedding",
                         nn.initializers.normal(0.02), (self.seq_len, hs))
        x = tok + seg + pos[None, :ids.shape[1]]
        x = nn.LayerNorm(epsilon=1e-12, name="embedding_norm")(x)
        if self.hidden_p_drop:
            x = nn.Dropout(self.hidden_p_drop, deterministic=not train)(x)
        strategy = self.strategy if attention_mask is None else "full"
        for i in range(self.n_block):
            x = TransformerBlock(
                n_head=self.n_head, hidden_size=hs,
                intermediate_size=self.intermediate_size,
                hidden_drop=self.hidden_p_drop, attn_drop=self.attn_p_drop,
                causal=False, strategy=strategy,
                name=f"block_{i}")(x, attention_mask, train=train)
        pooled = jnp.tanh(nn.Dense(hs, name="pooler")(x[:, 0]))
        return x, pooled
