"""Loss objects for the Keras-style API (parity:
pyzoo/zoo/pipeline/api/keras/objectives.py). Each is a thin callable over the
shared loss registry (orca/learn/losses.py) so compile(loss=...) accepts
strings, these classes, or raw jnp callables interchangeably."""

from __future__ import annotations

from typing import Callable

from analytics_zoo_tpu.orca.learn import losses as L


class _LossObject:
    fn: Callable = None

    def __init__(self, **kwargs):
        self.kwargs = kwargs

    def __call__(self, y_true, y_pred):
        return type(self).fn(y_true, y_pred, **self.kwargs)


class MeanSquaredError(_LossObject):
    fn = staticmethod(L.mean_squared_error)


class MeanAbsoluteError(_LossObject):
    fn = staticmethod(L.mean_absolute_error)


class BinaryCrossEntropy(_LossObject):
    fn = staticmethod(L.binary_crossentropy)


class CategoricalCrossEntropy(_LossObject):
    fn = staticmethod(L.categorical_crossentropy)


class SparseCategoricalCrossEntropy(_LossObject):
    fn = staticmethod(L.sparse_categorical_crossentropy)


class Hinge(_LossObject):
    fn = staticmethod(L.hinge)


class KullbackLeiblerDivergence(_LossObject):
    fn = staticmethod(L.kld)


mse = MSE = MeanSquaredError
mae = MAE = MeanAbsoluteError
