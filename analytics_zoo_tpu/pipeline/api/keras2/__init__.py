"""keras2 API — the reference's tf.keras-style argument-name surface
(reference: pyzoo/zoo/pipeline/api/keras2/, 1,026 LoC of py4j wrappers
whose only delta from keras v1 is naming: units/filters/kernel_size/rate/
padding/data_format instead of output_dim/nb_filter/p/border_mode/
dim_ordering, plus Maximum/Minimum/Average merge classes).

TPU-native collapse: keras2 factories return the SAME flax modules as the
v1 API, so both surfaces share one implementation, one Sequential/Model
engine, and one estimator/compile path. The reference's keras2 engine/
topology.py and engine/training.py are license-only stubs; Sequential,
Model and Input are re-exported from the v1 engine here for symmetry.
"""

from ..keras.engine.topology import Input, Model, Sequential
from . import layers

__all__ = ["Input", "Model", "Sequential", "layers"]
