"""keras2 layer namespace (reference: pyzoo/zoo/pipeline/api/keras2/layers/
__init__.py star-imports merge/core/convolutional/pooling/local/recurrent/
normalization/embeddings/noise/advanced_activations/wrappers/
convolutional_recurrent).

The reference's recurrent/normalization/embeddings/noise/
advanced_activations/wrappers/convolutional_recurrent files are
license-only stubs with no classes; here they carry real tf.keras-style
factories over the shared flax layers — beyond-parity, so tf.keras code
ports without touching the v1 argument names."""

from .advanced_activations import ELU, LeakyReLU, PReLU, ThresholdedReLU
from .convolutional import Conv1D, Conv2D, Cropping1D
from .convolutional_recurrent import ConvLSTM2D
from .core import Activation, Dense, Dropout, Flatten
from .embeddings import Embedding
from .local import LocallyConnected1D
from .merge import (Average, Maximum, Minimum, average, maximum, minimum)
from .noise import GaussianDropout, GaussianNoise
from .normalization import BatchNormalization
from .pooling import (AveragePooling1D, GlobalAveragePooling1D,
                      GlobalAveragePooling2D, GlobalMaxPooling1D,
                      MaxPooling1D)
from .recurrent import GRU, LSTM, SimpleRNN
from .wrappers import Bidirectional, TimeDistributed

__all__ = [
    "Conv1D", "Conv2D", "Cropping1D", "ConvLSTM2D",
    "Activation", "Dense", "Dropout", "Flatten",
    "LocallyConnected1D",
    "Average", "Maximum", "Minimum", "average", "maximum", "minimum",
    "AveragePooling1D", "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalMaxPooling1D", "MaxPooling1D",
    "LSTM", "GRU", "SimpleRNN",
    "Embedding", "BatchNormalization",
    "LeakyReLU", "ELU", "PReLU", "ThresholdedReLU",
    "GaussianNoise", "GaussianDropout",
    "TimeDistributed", "Bidirectional",
]
