"""keras2 layer namespace (reference: pyzoo/zoo/pipeline/api/keras2/layers/
__init__.py star-imports merge/core/convolutional/pooling/local/...; the
reference's recurrent/normalization/embeddings/noise/advanced_activations/
wrappers/convolutional_recurrent files are license-only stubs with no
classes, so there is nothing to mirror for them)."""

from .convolutional import Conv1D, Conv2D, Cropping1D
from .core import Activation, Dense, Dropout, Flatten
from .local import LocallyConnected1D
from .merge import (Average, Maximum, Minimum, average, maximum, minimum)
from .pooling import (AveragePooling1D, GlobalAveragePooling1D,
                      GlobalAveragePooling2D, GlobalMaxPooling1D,
                      MaxPooling1D)

__all__ = [
    "Conv1D", "Conv2D", "Cropping1D",
    "Activation", "Dense", "Dropout", "Flatten",
    "LocallyConnected1D",
    "Average", "Maximum", "Minimum", "average", "maximum", "minimum",
    "AveragePooling1D", "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalMaxPooling1D", "MaxPooling1D",
]
