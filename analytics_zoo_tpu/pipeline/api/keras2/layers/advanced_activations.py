"""keras2 advanced activations — tf.keras argument names over the keras-v1
flax modules (reference: pyzoo/zoo/pipeline/api/keras2/layers/
advanced_activations.py is a license-only stub; these factories expose the
tf.keras surface over the same flax activation modules)."""

from __future__ import annotations

from ...keras import layers as K1
from .core import _shape

__all__ = ["LeakyReLU", "ELU", "PReLU", "ThresholdedReLU"]


def LeakyReLU(alpha=0.3, input_shape=None, **kwargs):
    return K1.LeakyReLU(alpha=float(alpha),
                        input_shape=_shape(None, input_shape), **kwargs)


def ELU(alpha=1.0, input_shape=None, **kwargs):
    return K1.ELU(alpha=float(alpha),
                  input_shape=_shape(None, input_shape), **kwargs)


def PReLU(shared_axes=None, input_shape=None, **kwargs):
    """tf.keras PReLU. The v1 module learns a single shared slope
    (n_output_plane=0); ``shared_axes`` would change the parameter
    structure, so it is rejected rather than silently dropped."""
    if shared_axes is not None:
        raise ValueError(
            "PReLU(shared_axes=...) is not supported: the flax PReLU "
            "learns one shared slope (v1 n_output_plane=0)")
    return K1.PReLU(input_shape=_shape(None, input_shape), **kwargs)


def ThresholdedReLU(theta=1.0, input_shape=None, **kwargs):
    return K1.ThresholdedReLU(theta=float(theta),
                              input_shape=_shape(None, input_shape),
                              **kwargs)
