"""keras2 convolution layers (reference: pyzoo/zoo/pipeline/api/keras2/
layers/convolutional.py — Conv1D/Conv2D/Cropping1D with tf.keras names:
filters/kernel_size/strides/padding/data_format)."""

from __future__ import annotations

from ...keras import layers as K1

__all__ = ["Conv1D", "Conv2D", "Cropping1D"]


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (int(v), int(v))


def _ordering(data_format):
    if data_format in ("channels_first", "th"):
        return "th"
    if data_format in ("channels_last", "tf"):
        return "tf"
    raise ValueError(f"unknown data_format {data_format!r}")


def Conv1D(filters, kernel_size, strides=1, padding="valid",
           activation=None, use_bias=True,
           kernel_initializer="glorot_uniform", bias_initializer="zero",
           kernel_regularizer=None, bias_regularizer=None,
           input_shape=None, **kwargs):
    del bias_initializer
    if isinstance(kernel_size, (tuple, list)):
        kernel_size = kernel_size[0]
    if isinstance(strides, (tuple, list)):
        strides = strides[0]
    return K1.Convolution1D(
        nb_filter=int(filters), filter_length=int(kernel_size),
        activation=activation, border_mode=padding,
        subsample_length=int(strides), use_bias=use_bias,
        init_method=kernel_initializer, W_regularizer=kernel_regularizer,
        b_regularizer=bias_regularizer,
        input_shape=tuple(input_shape) if input_shape else None, **kwargs)


def Conv2D(filters, kernel_size, strides=(1, 1), padding="valid",
           data_format="channels_first", activation=None, use_bias=True,
           kernel_initializer="glorot_uniform", bias_initializer="zero",
           kernel_regularizer=None, bias_regularizer=None,
           input_shape=None, **kwargs):
    """reference keras2 Conv2D defaults to data_format='channels_first',
    matching the v1 dim_ordering='th' default."""
    del bias_initializer
    kh, kw = _pair(kernel_size)
    return K1.Convolution2D(
        nb_filter=int(filters), nb_row=int(kh), nb_col=int(kw),
        activation=activation, border_mode=padding,
        subsample=_pair(strides), dim_ordering=_ordering(data_format),
        use_bias=use_bias, init_method=kernel_initializer,
        W_regularizer=kernel_regularizer, b_regularizer=bias_regularizer,
        input_shape=tuple(input_shape) if input_shape else None, **kwargs)


def Cropping1D(cropping=(1, 1), input_shape=None, **kwargs):
    return K1.Cropping1D(cropping=_pair(cropping),
                         input_shape=tuple(input_shape) if input_shape
                         else None, **kwargs)
