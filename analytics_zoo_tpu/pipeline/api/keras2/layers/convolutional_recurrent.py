"""keras2 convolutional-recurrent — tf.keras argument names over the
keras-v1 flax ConvLSTM2D (reference: pyzoo/zoo/pipeline/api/keras2/layers/
convolutional_recurrent.py is a license-only stub; this factory exposes
the tf.keras surface — ``filters``/``kernel_size``/``strides``/``padding``
— over the same flax scan-based ConvLSTM cell)."""

from __future__ import annotations

from ...keras import layers as K1
from .convolutional import _pair
from .core import _shape

__all__ = ["ConvLSTM2D"]


def ConvLSTM2D(filters, kernel_size, strides=(1, 1), padding="same",
               data_format="channels_last", return_sequences=False,
               go_backwards=False, input_shape=None, **kwargs):
    """tf.keras ConvLSTM2D(filters, kernel_size). The v1 module supports
    square kernels, SAME padding and stride 1 only (matching the
    reference's BigDL ConvLSTM2D cell) — anything else is rejected rather
    than silently computed wrong."""
    kh, kw = _pair(kernel_size)
    if kh != kw:
        raise ValueError(
            f"ConvLSTM2D supports square kernels, got {kernel_size}")
    if padding != "same":
        raise ValueError(
            f"ConvLSTM2D supports padding='same' only, got {padding!r}")
    if _pair(strides) != (1, 1):
        raise ValueError(
            f"ConvLSTM2D supports strides=(1, 1) only, got {strides!r}")
    ordering = "tf" if data_format == "channels_last" else "th"
    return K1.ConvLSTM2D(nb_filter=int(filters), nb_kernel=int(kh),
                         return_sequences=return_sequences,
                         go_backwards=go_backwards,
                         dim_ordering=ordering,
                         input_shape=_shape(None, input_shape), **kwargs)
