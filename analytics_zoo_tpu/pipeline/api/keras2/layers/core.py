"""keras2 core layers — tf.keras argument names over the keras-v1 flax
modules (reference: pyzoo/zoo/pipeline/api/keras2/layers/core.py — Dense,
Activation, Dropout, Flatten with `units`/`rate`/`kernel_initializer`
naming instead of the v1 `output_dim`/`p`/`init`).

Each factory returns the SAME flax module class the keras-v1 API builds,
so keras2 layers compose freely with v1 layers, Sequential/Model, and the
whole estimator stack; only the constructor surface differs.
"""

from __future__ import annotations

from ...keras import layers as K1

__all__ = ["Dense", "Activation", "Dropout", "Flatten"]


def _shape(input_dim, input_shape):
    if input_dim:
        return (input_dim,)
    return tuple(input_shape) if input_shape else None


def Dense(units, kernel_initializer="glorot_uniform",
          bias_initializer="zero", activation=None, kernel_regularizer=None,
          bias_regularizer=None, use_bias=True, input_dim=None,
          input_shape=None, **kwargs):
    """reference keras2/layers/core.py Dense(units, kernel_initializer, ...)"""
    del bias_initializer   # v1 biases are zero-initialized, same default
    return K1.Dense(output_dim=int(units), activation=activation,
                    use_bias=use_bias, init_method=kernel_initializer,
                    W_regularizer=kernel_regularizer,
                    b_regularizer=bias_regularizer,
                    input_shape=_shape(input_dim, input_shape), **kwargs)


def Activation(activation, input_shape=None, **kwargs):
    return K1.Activation(activation=activation,
                         input_shape=_shape(None, input_shape), **kwargs)


def Dropout(rate, input_shape=None, **kwargs):
    """keras2 names the drop fraction ``rate`` (v1: ``p``)."""
    return K1.Dropout(p=float(rate),
                      input_shape=_shape(None, input_shape), **kwargs)


def Flatten(input_shape=None, **kwargs):
    return K1.Flatten(input_shape=_shape(None, input_shape), **kwargs)
