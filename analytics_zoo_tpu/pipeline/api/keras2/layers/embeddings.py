"""keras2 embedding layer — tf.keras argument names over the keras-v1 flax
Embedding (reference: pyzoo/zoo/pipeline/api/keras2/layers/embeddings.py is
a license-only stub; this factory exposes the tf.keras surface —
``embeddings_initializer`` instead of the v1 ``init`` — over the same
MXU-routed embedding module)."""

from __future__ import annotations

from ...keras import layers as K1
from .core import _shape

__all__ = ["Embedding"]


def Embedding(input_dim, output_dim, embeddings_initializer="uniform",
              weights=None, trainable=True, input_length=None,
              input_shape=None, **kwargs):
    """tf.keras Embedding(input_dim, output_dim, embeddings_initializer).

    ``input_length`` maps to the v1 ``input_shape=(length,)`` convention;
    tf.keras ids are zero-based (v1 BigDL's were one-based), which the
    flax module handles via ``zero_based_id``. keras-2 callers pass
    ``weights=[matrix]`` (a list); the v1 module takes the bare matrix."""
    if input_length is not None and input_shape is None:
        input_shape = (int(input_length),)
    if isinstance(weights, (list, tuple)):
        if len(weights) != 1:
            raise ValueError(
                f"weights must be [embedding_matrix], got {len(weights)} "
                "arrays")
        weights = weights[0]
    return K1.Embedding(input_dim=int(input_dim),
                        output_dim=int(output_dim),
                        init_method=embeddings_initializer,
                        weights=weights, trainable=trainable,
                        zero_based_id=True,
                        input_shape=_shape(None, input_shape), **kwargs)
