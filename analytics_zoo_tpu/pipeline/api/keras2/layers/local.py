"""keras2 locally-connected layers (reference: pyzoo/zoo/pipeline/api/
keras2/layers/local.py — LocallyConnected1D with filters/kernel_size
naming; only padding='valid' is supported, as in the reference)."""

from __future__ import annotations

from ...keras import layers as K1

__all__ = ["LocallyConnected1D"]


def LocallyConnected1D(filters, kernel_size, strides=1, padding="valid",
                       activation=None, kernel_regularizer=None,
                       bias_regularizer=None, use_bias=True,
                       input_shape=None, **kwargs):
    if padding != "valid":
        raise ValueError("For LocallyConnected1D, only padding='valid' is "
                         "supported for now")
    del kernel_regularizer, bias_regularizer
    if isinstance(kernel_size, (tuple, list)):
        kernel_size = kernel_size[0]
    if isinstance(strides, (tuple, list)):
        strides = strides[0]
    return K1.LocallyConnected1D(
        nb_filter=int(filters), filter_length=int(kernel_size),
        activation=activation, subsample_length=int(strides),
        use_bias=use_bias,
        input_shape=tuple(input_shape) if input_shape else None, **kwargs)
