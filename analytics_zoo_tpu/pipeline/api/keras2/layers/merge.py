"""keras2 merge layers (reference: pyzoo/zoo/pipeline/api/keras2/layers/
merge.py — Maximum/Minimum/Average classes + lowercase functional forms).
Each wraps the v1 ``Merge`` flax module with the matching mode."""

from __future__ import annotations

from ...keras import layers as K1

__all__ = ["Maximum", "Minimum", "Average",
           "maximum", "minimum", "average"]


def Maximum(input_shape=None, **kwargs):
    return K1.Merge(mode="max", input_shape=input_shape, **kwargs)


def Minimum(input_shape=None, **kwargs):
    return K1.Merge(mode="min", input_shape=input_shape, **kwargs)


def Average(input_shape=None, **kwargs):
    return K1.Merge(mode="ave", input_shape=input_shape, **kwargs)


def maximum(inputs, **kwargs):
    """Functional interface to :func:`Maximum` (reference merge.py maximum)."""
    return Maximum(**kwargs)(*inputs)


def minimum(inputs, **kwargs):
    return Minimum(**kwargs)(*inputs)


def average(inputs, **kwargs):
    return Average(**kwargs)(*inputs)
