"""keras2 noise layers — tf.keras argument names over the keras-v1 flax
modules (reference: pyzoo/zoo/pipeline/api/keras2/layers/noise.py is a
license-only stub; these factories expose the tf.keras surface — ``stddev``
instead of the v1 ``sigma``, ``rate`` instead of ``p``)."""

from __future__ import annotations

from ...keras import layers as K1
from .core import _shape

__all__ = ["GaussianNoise", "GaussianDropout"]


def GaussianNoise(stddev, input_shape=None, **kwargs):
    return K1.GaussianNoise(sigma=float(stddev),
                            input_shape=_shape(None, input_shape), **kwargs)


def GaussianDropout(rate, input_shape=None, **kwargs):
    return K1.GaussianDropout(p=float(rate),
                              input_shape=_shape(None, input_shape),
                              **kwargs)
