"""keras2 normalization — tf.keras argument names over the keras-v1 flax
BatchNormalization (reference: pyzoo/zoo/pipeline/api/keras2/layers/
normalization.py is a license-only stub; this factory exposes the tf.keras
surface — ``axis``, ``momentum``, ``epsilon`` — over the same flax
batch-stats module)."""

from __future__ import annotations

from ...keras import layers as K1
from .core import _shape

__all__ = ["BatchNormalization"]


def BatchNormalization(axis=-1, momentum=0.99, epsilon=1e-3,
                       beta_initializer="zeros", gamma_initializer="ones",
                       input_shape=None, **kwargs):
    """tf.keras BatchNormalization(axis=-1, momentum, epsilon).

    ``axis`` passes straight through to the flax module (it normalizes over
    every other dim). The v1 module initializes beta/gamma to zeros/ones
    only, so any other initializer is rejected rather than silently
    ignored."""
    if beta_initializer not in ("zeros", "zero"):
        raise ValueError(
            f"beta_initializer={beta_initializer!r} unsupported: the flax "
            "BatchNormalization initializes beta to zeros")
    if gamma_initializer not in ("ones", "one"):
        raise ValueError(
            f"gamma_initializer={gamma_initializer!r} unsupported: the "
            "flax BatchNormalization initializes gamma to ones")
    return K1.BatchNormalization(
        epsilon=float(epsilon), momentum=float(momentum),
        axis=int(axis),
        input_shape=_shape(None, input_shape), **kwargs)
