"""keras2 pooling layers (reference: pyzoo/zoo/pipeline/api/keras2/layers/
pooling.py — MaxPooling1D/AveragePooling1D/Global* with tf.keras names)."""

from __future__ import annotations

from ...keras import layers as K1

__all__ = ["MaxPooling1D", "AveragePooling1D", "GlobalMaxPooling1D",
           "GlobalAveragePooling1D", "GlobalAveragePooling2D"]


def MaxPooling1D(pool_size=2, strides=None, padding="valid",
                 input_shape=None, **kwargs):
    return K1.MaxPooling1D(pool_length=int(pool_size),
                           stride=None if not strides else int(strides),
                           border_mode=padding,
                           input_shape=tuple(input_shape) if input_shape
                           else None, **kwargs)


def AveragePooling1D(pool_size=2, strides=None, padding="valid",
                     input_shape=None, **kwargs):
    return K1.AveragePooling1D(pool_length=int(pool_size),
                               stride=None if not strides else int(strides),
                               border_mode=padding,
                               input_shape=tuple(input_shape) if input_shape
                               else None, **kwargs)


def GlobalMaxPooling1D(input_shape=None, **kwargs):
    return K1.GlobalMaxPooling1D(input_shape=tuple(input_shape)
                                 if input_shape else None, **kwargs)


def GlobalAveragePooling1D(input_shape=None, **kwargs):
    return K1.GlobalAveragePooling1D(input_shape=tuple(input_shape)
                                     if input_shape else None, **kwargs)


def GlobalAveragePooling2D(data_format="channels_first", input_shape=None,
                           **kwargs):
    ordering = "th" if data_format in ("channels_first", "th") else "tf"
    return K1.GlobalAveragePooling2D(dim_ordering=ordering,
                                     input_shape=tuple(input_shape)
                                     if input_shape else None, **kwargs)
