"""keras2 recurrent layers — tf.keras argument names over the keras-v1 flax
RNN modules (reference: pyzoo/zoo/pipeline/api/keras2/layers/recurrent.py is
a license-only stub with no classes; these factories go beyond that parity
by exposing the tf.keras constructor surface — ``units`` instead of the v1
``output_dim``, ``recurrent_activation`` instead of ``inner_activation`` —
over the same flax lax.scan RNN cells the v1 API builds)."""

from __future__ import annotations

from ...keras import layers as K1
from .core import _shape

__all__ = ["LSTM", "GRU", "SimpleRNN"]


def LSTM(units, activation="tanh", recurrent_activation="hard_sigmoid",
         return_sequences=False, go_backwards=False,
         kernel_regularizer=None, recurrent_regularizer=None,
         bias_regularizer=None, input_shape=None, **kwargs):
    """tf.keras LSTM surface (units/recurrent_activation) over K1.LSTM."""
    return K1.LSTM(output_dim=int(units), activation=activation,
                   inner_activation=recurrent_activation,
                   return_sequences=return_sequences,
                   go_backwards=go_backwards,
                   W_regularizer=kernel_regularizer,
                   U_regularizer=recurrent_regularizer,
                   b_regularizer=bias_regularizer,
                   input_shape=_shape(None, input_shape), **kwargs)


def GRU(units, activation="tanh", recurrent_activation="hard_sigmoid",
        return_sequences=False, go_backwards=False,
        kernel_regularizer=None, recurrent_regularizer=None,
        bias_regularizer=None, input_shape=None, **kwargs):
    return K1.GRU(output_dim=int(units), activation=activation,
                  inner_activation=recurrent_activation,
                  return_sequences=return_sequences,
                  go_backwards=go_backwards,
                  W_regularizer=kernel_regularizer,
                  U_regularizer=recurrent_regularizer,
                  b_regularizer=bias_regularizer,
                  input_shape=_shape(None, input_shape), **kwargs)


def SimpleRNN(units, activation="tanh", return_sequences=False,
              go_backwards=False, kernel_regularizer=None,
              recurrent_regularizer=None, bias_regularizer=None,
              input_shape=None, **kwargs):
    return K1.SimpleRNN(output_dim=int(units), activation=activation,
                        return_sequences=return_sequences,
                        go_backwards=go_backwards,
                        W_regularizer=kernel_regularizer,
                        U_regularizer=recurrent_regularizer,
                        b_regularizer=bias_regularizer,
                        input_shape=_shape(None, input_shape), **kwargs)
