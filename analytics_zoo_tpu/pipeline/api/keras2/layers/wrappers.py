"""keras2 wrappers — tf.keras surface over the keras-v1 flax wrapper
modules (reference: pyzoo/zoo/pipeline/api/keras2/layers/wrappers.py is a
license-only stub; TimeDistributed and Bidirectional pass through to the
same flax implementations, which already take the wrapped layer as the
first argument like tf.keras)."""

from __future__ import annotations

from ...keras import layers as K1
from .core import _shape

__all__ = ["TimeDistributed", "Bidirectional"]


def TimeDistributed(layer, input_shape=None, **kwargs):
    return K1.TimeDistributed(layer=layer,
                              input_shape=_shape(None, input_shape),
                              **kwargs)


def Bidirectional(layer, merge_mode="concat", input_shape=None, **kwargs):
    return K1.Bidirectional(layer=layer, merge_mode=merge_mode,
                            input_shape=_shape(None, input_shape), **kwargs)
