from .onnx_loader import ONNXModule, load, load_onnx, parse_onnx
