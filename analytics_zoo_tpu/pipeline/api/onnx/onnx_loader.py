"""ONNX model loader (parity: pyzoo/zoo/pipeline/api/onnx/onnx_loader.py +
mapper/ — the reference maps ONNX nodes onto zoo Keras layers JVM-side).

Here the loader parses the .onnx protobuf directly (utils/protostream.py — no
onnx runtime dependency, which this image doesn't ship) and materialises the
graph as a flax module: initializers become flax params (so a loaded model is
fine-tunable), and each node lowers to jnp/lax ops that XLA fuses. Supported
op set mirrors the reference's mapper coverage (Conv/Gemm/BatchNorm/pool/
elementwise/shape ops)."""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.utils.protostream import decode_fields, signed64

# --- proto parsing ----------------------------------------------------------

_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32, 7: np.int64,
           9: np.bool_, 10: np.float16, 11: np.float64}


def _parse_tensor(data: bytes) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    dtype = np.float32
    name = ""
    raw = None
    floats: List[float] = []
    ints: List[int] = []
    for field, wire, val in decode_fields(data):
        if field == 1:
            dims.append(signed64(val) if wire == 0 else
                        struct.unpack("<q", val)[0])
        elif field == 2 and wire == 0:
            dtype = _DTYPES.get(val, np.float32)
        elif field == 4:        # float_data (packed or repeated)
            if wire == 2:
                floats.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                floats.append(struct.unpack("<f", val)[0])
        elif field == 5 and wire == 2:   # int32_data packed varints
            i = 0
            from analytics_zoo_tpu.utils.protostream import read_varint
            while i < len(val):
                v, i = read_varint(val, i)
                ints.append(signed64(v))
        elif field == 7:        # int64_data
            if wire == 2:
                i = 0
                from analytics_zoo_tpu.utils.protostream import read_varint
                while i < len(val):
                    v, i = read_varint(val, i)
                    ints.append(signed64(v))
            else:
                ints.append(signed64(val))
        elif field == 8 and wire == 2:
            name = val.decode("utf-8")
        elif field == 9 and wire == 2:
            raw = val
    if raw is not None:
        arr = np.frombuffer(raw, dtype=dtype)
    elif floats:
        arr = np.asarray(floats, np.float32)
    elif ints:
        arr = np.asarray(ints, np.int64).astype(dtype)
    else:
        arr = np.zeros(0, dtype)
    if dims:
        arr = arr.reshape(dims)
    return name, arr


def _parse_attribute(data: bytes) -> Tuple[str, Any]:
    name = ""
    out: Any = None
    ints: List[int] = []
    floats: List[float] = []
    for field, wire, val in decode_fields(data):
        if field == 1 and wire == 2:
            name = val.decode("utf-8")
        elif field == 2 and wire == 5:
            out = struct.unpack("<f", val)[0]
        elif field == 3 and wire == 0:
            out = signed64(val)
        elif field == 4 and wire == 2:
            out = val.decode("utf-8", errors="replace")
        elif field == 5 and wire == 2:
            out = _parse_tensor(val)[1]
        elif field == 7:
            if wire == 2:
                floats.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                floats.append(struct.unpack("<f", val)[0])
        elif field == 8:
            if wire == 2:
                from analytics_zoo_tpu.utils.protostream import read_varint
                i = 0
                while i < len(val):
                    v, i = read_varint(val, i)
                    ints.append(signed64(v))
            else:
                ints.append(signed64(val))
    if ints:
        out = ints
    elif floats and out is None:
        out = floats
    return name, out


class OnnxNode:
    def __init__(self):
        self.op_type = ""
        self.name = ""
        self.inputs: List[str] = []
        self.outputs: List[str] = []
        self.attrs: Dict[str, Any] = {}


def _parse_node(data: bytes) -> OnnxNode:
    n = OnnxNode()
    for field, wire, val in decode_fields(data):
        if field == 1 and wire == 2:
            n.inputs.append(val.decode("utf-8"))
        elif field == 2 and wire == 2:
            n.outputs.append(val.decode("utf-8"))
        elif field == 3 and wire == 2:
            n.name = val.decode("utf-8")
        elif field == 4 and wire == 2:
            n.op_type = val.decode("utf-8")
        elif field == 5 and wire == 2:
            k, v = _parse_attribute(val)
            n.attrs[k] = v
    return n


def _parse_value_info(data: bytes) -> Tuple[str, List[Optional[int]]]:
    name = ""
    shape: List[Optional[int]] = []
    for field, wire, val in decode_fields(data):
        if field == 1 and wire == 2:
            name = val.decode("utf-8")
        elif field == 2 and wire == 2:   # TypeProto
            for f2, w2, v2 in decode_fields(val):
                if f2 == 1 and w2 == 2:  # tensor_type
                    for f3, w3, v3 in decode_fields(v2):
                        if f3 == 2 and w3 == 2:  # shape
                            for f4, w4, v4 in decode_fields(v3):
                                if f4 == 1 and w4 == 2:  # dim
                                    dim_val = None
                                    for f5, w5, v5 in decode_fields(v4):
                                        if f5 == 1 and w5 == 0:
                                            dim_val = signed64(v5)
                                    shape.append(dim_val)
    return name, shape


class OnnxGraph:
    def __init__(self):
        self.nodes: List[OnnxNode] = []
        self.initializers: Dict[str, np.ndarray] = {}
        self.inputs: List[Tuple[str, List[Optional[int]]]] = []
        self.outputs: List[str] = []
        self.name = ""


def parse_onnx(path_or_bytes) -> OnnxGraph:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    graph_bytes = None
    for field, wire, val in decode_fields(data):
        if field == 7 and wire == 2:
            graph_bytes = val
    if graph_bytes is None:
        raise ValueError("not an ONNX ModelProto: no graph field")
    g = OnnxGraph()
    for field, wire, val in decode_fields(graph_bytes):
        if field == 1 and wire == 2:
            g.nodes.append(_parse_node(val))
        elif field == 2 and wire == 2:
            g.name = val.decode("utf-8")
        elif field == 5 and wire == 2:
            name, arr = _parse_tensor(val)
            g.initializers[name] = arr
        elif field == 11 and wire == 2:
            g.inputs.append(_parse_value_info(val))
        elif field == 12 and wire == 2:
            g.outputs.append(_parse_value_info(val)[0])
    # graph inputs exclude initializers
    g.inputs = [(n, s) for n, s in g.inputs if n not in g.initializers]
    return g


# --- node execution ---------------------------------------------------------

def _auto_pad(attrs, default="VALID"):
    pads = attrs.get("pads")
    if pads:
        half = len(pads) // 2
        return list(zip(pads[:half], pads[half:]))
    ap = attrs.get("auto_pad", "NOTSET")
    if ap in ("SAME_UPPER", "SAME_LOWER"):
        return "SAME"
    return default


def _run_node(node: OnnxNode, env: Dict[str, jnp.ndarray]):
    t = node.op_type
    a = node.attrs
    x = [env[i] if i else None for i in node.inputs]

    if t in ("Relu",):
        return jax.nn.relu(x[0])
    if t == "LeakyRelu":
        return jax.nn.leaky_relu(x[0], a.get("alpha", 0.01))
    if t == "Sigmoid":
        return jax.nn.sigmoid(x[0])
    if t == "Tanh":
        return jnp.tanh(x[0])
    if t == "Softmax":
        return jax.nn.softmax(x[0], axis=a.get("axis", -1))
    if t == "Exp":
        return jnp.exp(x[0])
    if t == "Log":
        return jnp.log(x[0])
    if t == "Sqrt":
        return jnp.sqrt(x[0])
    if t == "Abs":
        return jnp.abs(x[0])
    if t == "Neg":
        return -x[0]
    if t == "Add":
        return x[0] + x[1]
    if t == "Sub":
        return x[0] - x[1]
    if t == "Mul":
        return x[0] * x[1]
    if t == "Div":
        return x[0] / x[1]
    if t == "Pow":
        return x[0] ** x[1]
    if t == "MatMul":
        return jnp.matmul(x[0], x[1])
    if t == "Gemm":
        y = x[0]
        if a.get("transA"):
            y = y.T
        w = x[1].T if a.get("transB") else x[1]
        out = a.get("alpha", 1.0) * jnp.matmul(y, w)
        if len(x) > 2 and x[2] is not None:
            out = out + a.get("beta", 1.0) * x[2]
        return out
    if t == "Conv":
        strides = tuple(a.get("strides", [1, 1]))
        pad = _auto_pad(a)
        dil = tuple(a.get("dilations", [1] * len(strides)))
        groups = a.get("group", 1)
        return jax.lax.conv_general_dilated(
            x[0], x[1], window_strides=strides, padding=pad,
            rhs_dilation=dil, feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW")
        ) + (x[2].reshape(1, -1, *([1] * (x[0].ndim - 2)))
             if len(x) > 2 and x[2] is not None else 0.0)
    if t in ("MaxPool", "AveragePool"):
        k = tuple(a["kernel_shape"])
        strides = tuple(a.get("strides", k))
        pad = _auto_pad(a)
        if pad == "SAME":
            pad_cfg = "SAME"
        elif pad == "VALID":
            pad_cfg = [(0, 0)] * len(k)
        else:
            pad_cfg = pad
        window = (1, 1) + k
        ws = (1, 1) + strides
        pads = ([(0, 0), (0, 0)] + list(pad_cfg)
                if isinstance(pad_cfg, list) else pad_cfg)
        if t == "MaxPool":
            return jax.lax.reduce_window(x[0], -jnp.inf, jax.lax.max,
                                         window, ws, pads)
        summed = jax.lax.reduce_window(x[0], 0.0, jax.lax.add, window, ws,
                                       pads)
        return summed / float(np.prod(k))
    if t == "GlobalAveragePool":
        return jnp.mean(x[0], axis=tuple(range(2, x[0].ndim)), keepdims=True)
    if t == "GlobalMaxPool":
        return jnp.max(x[0], axis=tuple(range(2, x[0].ndim)), keepdims=True)
    if t == "BatchNormalization":
        scale, b, mean, var = x[1], x[2], x[3], x[4]
        eps = a.get("epsilon", 1e-5)
        shape = (1, -1) + (1,) * (x[0].ndim - 2)
        return ((x[0] - mean.reshape(shape)) /
                jnp.sqrt(var.reshape(shape) + eps) * scale.reshape(shape) +
                b.reshape(shape))
    if t == "Flatten":
        ax = a.get("axis", 1)
        lead = int(np.prod(x[0].shape[:ax])) if ax else 1
        return x[0].reshape(lead, -1)
    if t == "Reshape":
        shape = [int(s) for s in np.asarray(x[1])]
        return x[0].reshape([x[0].shape[i] if s == 0 else s
                             for i, s in enumerate(shape)])
    if t == "Transpose":
        perm = a.get("perm")
        return jnp.transpose(x[0], perm)
    if t == "Concat":
        return jnp.concatenate([v for v in x], axis=a.get("axis", 0))
    if t == "Squeeze":
        axes = a.get("axes") or ([int(v) for v in np.asarray(x[1])]
                                 if len(x) > 1 and x[1] is not None else None)
        return jnp.squeeze(x[0], axis=tuple(axes) if axes else None)
    if t == "Unsqueeze":
        axes = a.get("axes") or [int(v) for v in np.asarray(x[1])]
        out = x[0]
        for ax in sorted(axes):
            out = jnp.expand_dims(out, ax)
        return out
    if t == "Clip":
        lo = a.get("min", x[1] if len(x) > 1 else None)
        hi = a.get("max", x[2] if len(x) > 2 else None)
        return jnp.clip(x[0], lo, hi)
    if t in ("Dropout", "Identity"):
        return x[0]
    if t == "Constant":
        return jnp.asarray(a["value"])
    if t == "ReduceMean":
        axes = a.get("axes")
        return jnp.mean(x[0], axis=tuple(axes) if axes else None,
                        keepdims=bool(a.get("keepdims", 1)))
    if t == "ReduceSum":
        axes = a.get("axes")
        return jnp.sum(x[0], axis=tuple(axes) if axes else None,
                       keepdims=bool(a.get("keepdims", 1)))
    if t == "Shape":
        return jnp.asarray(x[0].shape, jnp.int64)
    if t == "Gather":
        return jnp.take(x[0], x[1].astype(jnp.int32),
                        axis=a.get("axis", 0))
    if t == "Slice":
        starts = [int(v) for v in np.asarray(x[1])]
        ends = [int(v) for v in np.asarray(x[2])]
        axes = ([int(v) for v in np.asarray(x[3])]
                if len(x) > 3 and x[3] is not None
                else list(range(len(starts))))
        out = x[0]
        for s, e, ax in zip(starts, ends, axes):
            out = jax.lax.slice_in_dim(out, s, min(e, out.shape[ax]),
                                       axis=ax)
        return out
    raise NotImplementedError(
        f"ONNX op {t!r} is not supported by the loader (node {node.name})")


class ONNXModule(nn.Module):
    """flax module executing a parsed ONNX graph; initializers are params so
    a loaded model can be fine-tuned with the estimator."""
    graph: OnnxGraph = None
    trainable: bool = True

    @nn.compact
    def __call__(self, *xs):
        g = self.graph
        env: Dict[str, jnp.ndarray] = {}
        for (name, _), x in zip(g.inputs, xs):
            env[name] = x
        for name, arr in g.initializers.items():
            if self.trainable and np.issubdtype(arr.dtype, np.floating):
                env[name] = self.param(
                    name.replace("/", "_").replace(".", "_") or "w",
                    lambda rng, a=arr: jnp.asarray(a))
            else:
                env[name] = jnp.asarray(arr)
        for node in g.nodes:
            result = _run_node(node, env)
            if isinstance(result, tuple):
                for out_name, r in zip(node.outputs, result):
                    env[out_name] = r
            else:
                env[node.outputs[0]] = result
        outs = tuple(env[o] for o in g.outputs)
        return outs[0] if len(outs) == 1 else outs


def load(path_or_bytes, trainable: bool = True) -> ONNXModule:
    """reference onnx_loader.py load_onnx → zoo model; here → flax module."""
    return ONNXModule(graph=parse_onnx(path_or_bytes), trainable=trainable)


load_onnx = load
