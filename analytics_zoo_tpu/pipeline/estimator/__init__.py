from .estimator import Estimator
