"""Low-level pipeline Estimator (parity: pyzoo/zoo/pipeline/estimator/
estimator.py:22 — train:127/train_minibatch/evaluate over a model +
OptimMethod; Scala pipeline/estimator/Estimator.scala:68,141).

The TPU engine's minibatch loop is already the whole optimizer, so this class
is the thin imperative surface: construct from a model + optim method, call
train_minibatch on your own loop or train() on a dataset."""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from ...orca.learn.engine import TrainEngine
from ...orca.learn.losses import convert_loss
from ...orca.learn.metrics import convert_metrics_list
from ...orca.learn.optimizers.optimizers_impl import convert_optimizer
from ...orca.learn.utils import Batch
from ...common.context import get_context


class Estimator:
    def __init__(self, model, optim_methods=None, model_dir: Optional[str] = None):
        self.ctx = get_context()
        self.model = model
        self.optim = convert_optimizer(optim_methods or "sgd")
        self.model_dir = model_dir
        self._engine: Optional[TrainEngine] = None
        self._loss = None

    def _engine_for(self, loss, metrics=None) -> TrainEngine:
        loss_fn = convert_loss(loss) if loss is not None else None
        if self._engine is None or self._loss is not loss:
            self._engine = TrainEngine(
                self.model, self.optim, loss_fn,
                convert_metrics_list(metrics), self.ctx.mesh)
            self._loss = loss
        return self._engine

    def train_minibatch(self, x, y, loss="mean_squared_error"):
        """One optimization step on one minibatch (reference
        train_minibatch)."""
        eng = self._engine_for(loss)
        x = (x,) if not isinstance(x, (tuple, list)) else tuple(x)
        y = (y,) if not isinstance(y, (tuple, list)) else tuple(y)
        if eng.params is None:
            eng.build(tuple(np.asarray(a) for a in x))
        import jax.numpy as jnp
        w = jnp.ones(np.asarray(x[0]).shape[0], jnp.float32)
        loss_val = eng.train_batch(Batch(
            x=tuple(jnp.asarray(a) for a in x),
            y=tuple(jnp.asarray(a) for a in y), w=w))
        return float(loss_val)

    def train(self, train_set: Iterable, criterion="mean_squared_error",
              end_trigger=None, checkpoint_trigger=None,
              validation_set=None, validation_method=None,
              batch_size: int = 32, epochs: int = 1) -> List[float]:
        """train_set: iterable of (x, y) minibatches or a {'x','y'} dict."""
        losses = []
        if isinstance(train_set, dict):
            from ...orca.learn.estimator import TPUEstimator
            est = TPUEstimator(self.model, loss=criterion,
                               optimizer=self.optim)
            stats = est.fit(train_set, epochs=epochs, batch_size=batch_size,
                            verbose=False)
            self._engine = est.engine
            return [s["train_loss"] for s in stats]
        for _ in range(epochs):
            for x, y in train_set:
                losses.append(self.train_minibatch(x, y, loss=criterion))
        return losses

    def evaluate(self, validation_set, validation_method=None,
                 batch_size: int = 32) -> Dict[str, float]:
        from ...orca.learn.estimator import TPUEstimator
        est = TPUEstimator(self.model, loss=self._loss or
                           "mean_squared_error",
                           optimizer=self.optim,
                           metrics=validation_method)
        if self._engine is not None:
            est.engine = self._engine
        return est.evaluate(validation_set, batch_size=batch_size,
                            verbose=False)

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float):
        """reference Estimator.setConstantGradientClipping."""
        import optax
        self.optim = optax.chain(
            optax.clip(max(abs(min_value), abs(max_value))), self.optim)
        self._engine = None
        return self

    def set_l2_norm_gradient_clipping(self, clip_norm: float):
        import optax
        self.optim = optax.chain(optax.clip_by_global_norm(clip_norm),
                                 self.optim)
        self._engine = None
        return self

    def clear_gradient_clipping(self):
        # rebuild without the clipping chain on next use
        raise NotImplementedError(
            "construct a fresh Estimator to clear clipping (optax chains "
            "are immutable)")
