from .inference_model import InferenceModel

__all__ = ["InferenceModel"]
