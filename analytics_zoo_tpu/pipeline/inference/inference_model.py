"""InferenceModel — multi-backend, concurrency-safe TPU inference.

The reference's InferenceModel (zoo/.../pipeline/inference/InferenceModel.scala:28)
loads BigDL/Caffe/TF-frozen/TF-SavedModel/OpenVINO models and serves them from
a blocking queue of model copies (:580-626) so concurrent requests don't
contend. On TPU the analogue is: ONE set of weights in HBM (XLA executables
are reentrant; no copies needed) plus a **shape-bucketed executable cache** —
each (batch-bucket, input-signature) pair compiles once and is reused, which
is the serving-latency answer to XLA recompilation (SURVEY.md §7 hard-part #4).

Backends:
* flax module + variables        (native)
* estimator pickle (state.pkl)   (our checkpoint format)
* TF SavedModel / keras model    via keras_bridge conversion when the graph is
  convertible; this covers the reference's TFNet serving configs
  (BASELINE config #5) with the model compiled for TPU rather than run
  through TF-Java JNI (reference TFNet: pipeline/api/net/TFNet.scala:56).
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("analytics_zoo_tpu")


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1] * math.ceil(n / buckets[-1])


class InferenceModel:
    """(reference python wrapper: pyzoo/zoo/pipeline/inference/
    inference_model.py:24 — load/load_tf/load_openvino + predict)

    Multi-chip: the model owns a 1-axis ``dp`` device mesh (default: every
    local device). Params are replicated over it and the batch dim of every
    request is sharded across it, so one predict() uses ALL local chips —
    the TPU-native equivalent of the reference scaling serving with a
    model-replica queue (InferenceModel.scala:580-626) and Flink
    ``setParallelism(modelParallelism)`` (serving/ClusterServing.scala:60),
    per SURVEY §2.3 ("per-core compiled executables; batch dim sharding").
    Shape buckets are rounded up to a multiple of the device count so the
    sharded leading dim always divides evenly.

    Sharding plane (PR 17): pass ``sharding=`` a
    :class:`~analytics_zoo_tpu.parallel.sharding.SpecLayout` (or ``True``
    for the default layout) on an fsdp/tp-factored mesh and the weights are
    *partitioned* across devices instead of replicated —
    ``SpecLayout.param_shardings`` places rule-matched leaves (embedding
    tables over fsdp×tp) on their declared axes and splits every other big
    leaf over the fsdp axis, so a model ~N× one chip's HBM serves on an
    N-way mesh. The batch dim then shards over the (dp, fsdp) axes only —
    tp ranks see the full batch, as the tp layers' row/column matmuls
    require — and buckets round to that divisor rather than the full
    device count.
    """

    DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

    def __init__(self, supported_concurrent_num: int = 1,
                 batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
                 mesh=None, compile_cache=None, sharding=None):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ...compile import resolve_cache
        from ...parallel.sharding import SpecLayout
        # concurrency arg kept for API parity; XLA executables are reentrant
        self.concurrency = supported_concurrent_num
        # serving compiles through the process-wide compile plane: bucket
        # executables are shared with any other model serving the same
        # program, persist to the disk cache when one is configured (warm
        # worker restarts skip bucket compilation), and precompile's
        # compiles-vs-hits show up in compile_stats(). False -> plain jit.
        self._cc = resolve_cache(compile_cache)
        self._jit_apply = None
        if mesh is None:
            mesh = Mesh(np.array(jax.local_devices()), ("dp",))
        self.mesh = mesh
        self._ndev = int(np.prod(list(mesh.shape.values())))
        self._axes = tuple(mesh.axis_names)
        self._repl = NamedSharding(mesh, P())
        self.sharding = SpecLayout.resolve(None, sharding)
        if self.sharding is not None:
            # batch over (dp, fsdp) only; tp ranks consume the full batch
            batch_axes = self.sharding.batch_axes(mesh)
            self._data_spec = P(batch_axes)
            self._batch_div = int(np.prod(
                [mesh.shape.get(a, 1) for a in batch_axes]))
        else:
            self._data_spec = P(self._axes)  # batch dim over every mesh axis
            self._batch_div = self._ndev
        # buckets rounded so the sharded batch dim always divides its axes
        self.buckets = tuple(sorted(
            {math.ceil(b / self._batch_div) * self._batch_div
             for b in batch_buckets}))
        self._apply_fn: Optional[Callable] = None
        self._variables = None
        # on-device input prologue (orca/learn/prologue.BatchPrologue):
        # cast/normalize runs inside the jitted apply so requests ship
        # narrow dtypes (uint8 images) — a 4x ingress byte cut
        self._prologue = None
        # h2d transfer telemetry for the serving path (surfaced by
        # ClusterServing.metrics() and the HTTP /metrics endpoint)
        from ...native.infeed import PipelineStats
        self._tstats = PipelineStats()
        # warmed (bucket, signature) registry; the executables themselves
        # live in the shared ExecutableCache (or the jit wrapper's cache)
        self._cache: Dict[Tuple, Callable] = {}
        self._lock = threading.Lock()
        # checkpoint-plane hot-reload (enable_hot_reload): watcher thread +
        # counters surfaced via ckpt_stats() / serving metrics()["ckpt"]
        self._watcher = None
        self._loaded_step = None     # step load_checkpoint bootstrapped from
        self._ckpt_counters: Dict = {"hot_reloads": 0, "full_reloads": 0,
                                     "reload_skips": 0,
                                     "last_reload_step": None}
        # call_tf-backed loaders set this: jax2tf.call_tf under jit requires
        # the TF function to be XLA-compilable, which frozen graphs with
        # NMS/lookup ops (TFNet's main use case) are not — those apply_fns
        # must run eagerly so TF executes its own kernels host-side.
        self._eager = False

    @property
    def device_count(self) -> int:
        """Chips one predict() actually computes on (1 for eager/call_tf
        models, which run TF kernels host-side)."""
        return 1 if self._eager else self._ndev

    def _reset_executables(self):
        """New apply_fn/variables: drop the warmed-signature registry and
        the cached-function wrapper (the shared cache keeps old entries —
        they are keyed by program, so they can never be served wrongly)."""
        self._cache.clear()
        self._jit_apply = None

    def _place_variables(self, variables):
        """Put a variable tree on the mesh: partitioned per the SpecLayout
        when the sharding plane is on (per-device weight bytes ~1/fsdp of
        the full model), replicated otherwise. Every loader/swap path goes
        through here so hot-reload and quantize keep the layout."""
        import jax
        if self.sharding is not None:
            return jax.device_put(
                variables,
                self.sharding.param_shardings(self.mesh, variables))
        return jax.device_put(variables, self._repl)

    def _shard_batch(self, arr):
        """Place one padded input on the mesh, batch dim sharded: each chip
        receives ONLY its slice (native/transfer.py sharded_put) instead of
        the runtime replicating the full batch to every chip before
        slicing; the transfer is recorded in :meth:`transfer_stats`."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ...native.transfer import narrow_wire, sharded_put
        spec = self._data_spec if arr.ndim else P()
        return sharded_put(narrow_wire(arr), NamedSharding(self.mesh, spec),
                           stats=self._tstats)

    def transfer_stats(self) -> Dict:
        """Serving-ingress transfer counters (h2d seconds/bytes/MB/s) —
        the data-plane twin of :meth:`compile_stats`."""
        return self._tstats.snapshot()

    def set_prologue(self, prologue) -> "InferenceModel":
        """Fuse an on-device input prologue (cast + normalize + ...) into
        the jitted apply, so clients enqueue narrow source dtypes (uint8
        images, int32 ids) and the cast happens after the wire, not before.
        Accepts a :class:`~analytics_zoo_tpu.orca.learn.prologue.
        BatchPrologue` or a LeafOp / tuple of LeafOps for the positional
        inputs. ``None`` clears it."""
        from ...orca.learn.prologue import BatchPrologue
        if prologue is not None and not isinstance(prologue, BatchPrologue):
            self._prologue = BatchPrologue(x=prologue)
        else:
            self._prologue = prologue
        self._reset_executables()
        return self

    # --- loaders ------------------------------------------------------------
    def load_jax(self, module, variables) -> "InferenceModel":
        """Load a flax module + trained variables (native path)."""
        import jax

        def apply_fn(variables, *x):
            kwargs = {}
            out = module.apply(variables, *x, **kwargs)
            return out

        self._apply_fn = apply_fn
        self._variables = self._place_variables(variables)
        self._eager = False
        self._reset_executables()
        return self

    # --- int8 weight quantization -------------------------------------------
    def quantize(self, min_elements: int = 4096) -> "InferenceModel":
        """Weight-only int8 quantization (the reference's local int8
        quantization: ~4x model-size reduction, docs wp-bigdl.md:192; BigDL
        quantizes per-layer with symmetric scales the same way).

        Float leaves with >= ``min_elements`` entries are stored as int8
        with a per-output-channel symmetric scale (last axis); dequant
        happens INSIDE the jitted apply, so weights stream from HBM at 1/4
        the bytes and upcast in registers — on memory-bound serving models
        this is also a throughput win, and XLA folds the dequant into the
        consuming matmul. Accuracy: symmetric per-channel int8 keeps the
        reference's <0.1% top-1 drop envelope for conv/dense nets.
        """
        import jax
        import jax.numpy as jnp

        if self._variables is None:
            raise RuntimeError(
                "no variables to quantize. load_jax/load/load_tf initialize "
                "them eagerly; load_torch defers init until the first "
                "predict() (input shape unknown) — run one predict, then "
                "quantize()")
        variables = jax.device_get(self._variables)

        def quant_leaf(leaf):
            arr = np.asarray(leaf)
            if (arr.dtype.kind != "f" or arr.size < min_elements
                    or arr.ndim < 2):
                return leaf, None
            scale = np.abs(arr).max(axis=tuple(range(arr.ndim - 1)),
                                    keepdims=True) / 127.0
            scale = np.where(scale == 0, 1.0, scale).astype(np.float32)
            q = np.clip(np.round(arr / scale), -127, 127).astype(np.int8)
            return q, scale

        flat, treedef = jax.tree_util.tree_flatten(variables)
        q_leaves, scales = [], []
        n_quantized = 0
        for leaf in flat:
            q, s = quant_leaf(leaf)
            q_leaves.append(q)
            scales.append(s)
            n_quantized += s is not None
        q_vars = jax.tree_util.tree_unflatten(treedef, q_leaves)

        orig_apply = self._apply_fn

        def apply_fn(qvars, *x):
            qflat = jax.tree_util.tree_leaves(qvars)
            deq = [leaf if s is None else
                   leaf.astype(jnp.float32) * s
                   for leaf, s in zip(qflat, scales)]
            return orig_apply(jax.tree_util.tree_unflatten(treedef, deq), *x)

        self._apply_fn = apply_fn
        self._variables = self._place_variables(q_vars)
        self._reset_executables()
        logger.info("quantized %d weight tensors to int8", n_quantized)
        return self

    def _dump_blob(self, module) -> bytes:
        """Serialize the checkpoint dict (one schema for save +
        save_encrypted)."""
        import cloudpickle as pickle
        import jax
        return pickle.dumps(
            {"module": module,
             "state": {"params": jax.device_get(self._variables["params"]),
                       "extra_vars": {
                           k: jax.device_get(v)
                           for k, v in self._variables.items()
                           if k != "params"}}})

    def _load_blob(self, raw: bytes) -> "InferenceModel":
        import io

        import cloudpickle as pickle
        blob = pickle.load(io.BytesIO(raw))
        if "module" not in blob:
            raise ValueError(
                "checkpoint missing module; save with InferenceModel.save "
                "or load_jax(module, variables)")
        return self.load_jax(blob["module"],
                             {"params": blob["state"]["params"],
                              **blob["state"].get("extra_vars", {})})

    def load(self, model_path: str, weight_path: Optional[str] = None
             ) -> "InferenceModel":
        """Load an estimator checkpoint pickle (reference ``load`` loads
        BigDL models, inference_model.py:40) or a checkpoint-plane
        directory/root (``analytics_zoo_tpu.ckpt`` manifest + blobs)."""
        import os
        if os.path.isdir(model_path):
            return self.load_checkpoint(model_path)
        with open(model_path, "rb") as f:
            return self._load_blob(f.read())

    def save(self, module, path: str):
        with open(path, "wb") as f:
            f.write(self._dump_blob(module))

    # --- checkpoint plane (manifest + content-addressed blobs) --------------
    def _state_doc(self, module) -> dict:
        import jax
        return {"module": module,
                "state": {"params": jax.device_get(
                              self._variables["params"]),
                          "extra_vars": {
                              k: jax.device_get(v)
                              for k, v in self._variables.items()
                              if k != "params"}}}

    def save_checkpoint(self, module, root: str, step: int = 0,
                        passphrase: Optional[str] = None) -> str:
        """Write a committed checkpoint-plane artifact (atomic, per-leaf
        content-addressed, optionally encrypted at rest) under ``root`` —
        the serving twin of ``TPUEstimator.save_checkpoint``, and the
        producer side of :meth:`enable_hot_reload`."""
        from ...ckpt import CheckpointPlane
        plane = CheckpointPlane(root, passphrase=passphrase,
                                async_save=False)
        return plane.save(self._state_doc(module), step, blocking=True)

    @staticmethod
    def _state_to_variables(state):
        """Checkpoint state → serving variables. Accepts both schemas:
        serving docs ({"module", "state": {params, extra_vars}}) and raw
        estimator states ({params, extra_vars, opt_state, ...})."""
        inner = state.get("state", state)
        variables = {"params": inner["params"],
                     **(inner.get("extra_vars") or {})}
        return variables, state.get("module")

    def load_checkpoint(self, root: str, step: Optional[int] = None,
                        passphrase: Optional[str] = None
                        ) -> "InferenceModel":
        """Load from a checkpoint-plane root (newest committed checkpoint;
        uncommitted/corrupt dirs are skipped) or a single checkpoint dir.
        Estimator checkpoints work too when a module was loaded before
        (weights-only adoption); serving checkpoints carry their module."""
        import os

        from ...ckpt import CheckpointPlane, is_plane_dir, \
            load_checkpoint_dir
        if is_plane_dir(root) or os.path.exists(
                os.path.join(root, "state.pkl")):
            path = root                                     # one ckpt dir
            state = load_checkpoint_dir(root, passphrase)
        else:
            path, state = CheckpointPlane(
                root, passphrase=passphrase).restore(step=step)
        from ...ckpt import parse_step
        self._loaded_step = parse_step(os.path.basename(path))
        variables, module = self._state_to_variables(state)
        if module is None:
            if self._apply_fn is None:
                raise ValueError(
                    f"{root}: estimator checkpoint has no module; load a "
                    "model first (load_jax) for weights-only adoption")
            self._variables = self._place_variables(variables)
            self._reset_executables()
            return self
        return self.load_jax(module, variables)

    # --- serving hot-reload -------------------------------------------------
    def enable_hot_reload(self, root: str, poll_s: float = 2.0,
                          passphrase: Optional[str] = None,
                          start_at: Optional[int] = None):
        """Watch ``root`` for newly COMMITTED checkpoints and swap the
        weights into the live model. Same-shape states swap without
        touching the compiled executables (the warmed buckets and the
        compile plane's cached executable are reused — zero new compiles;
        in-flight batches finish on the old weights, the next predict uses
        the new ones). A shape/structure mismatch falls back to a full
        reload when the checkpoint carries its module, else it is skipped.
        Returns the :class:`~analytics_zoo_tpu.ckpt.CheckpointWatcher`
        (``poll_now()`` forces a synchronous check). ``start_at`` skips
        steps <= it; the default is the step ``load_checkpoint`` loaded
        this model from, so a server bootstrapped from the watched dir
        does not re-read and re-stage the checkpoint it already serves."""
        from ...ckpt import CheckpointWatcher
        self.disable_hot_reload()
        if start_at is None:
            start_at = getattr(self, "_loaded_step", None)
        self._watcher = CheckpointWatcher(
            root, self._hot_swap, poll_s=poll_s, passphrase=passphrase,
            start_at=start_at)
        self._watcher.start()
        return self._watcher

    def disable_hot_reload(self):
        w = getattr(self, "_watcher", None)
        if w is not None:
            w.stop()
            self._watcher = None

    def apply_checkpoint(self, path: str, state, step: int):
        """Adopt an already-loaded checkpoint state into the live model —
        the public form of the hot-reload callback, for consumers that
        run their own CheckpointWatcher (the streaming plane's
        ``StreamingReloader`` wraps it with a trace span + freshness
        accounting). Same-shape states swap with zero new compiles."""
        return self._hot_swap(path, state, step)

    def _hot_swap(self, path: str, state, step: int):
        import jax
        variables, module = self._state_to_variables(state)

        def sig(tree):
            return jax.tree_util.tree_map(
                lambda l: (getattr(l, "shape", None),
                           str(getattr(l, "dtype", type(l)))), tree)

        # shape/dtype are attributes on the live device arrays — no
        # device_get: a D2H copy of the full weight tree per rollout just
        # to read metadata would be a multi-GB transfer on big models
        same = (self._variables is not None
                and sig(variables) == sig(self._variables))
        if same:
            # weights-only swap: executables are keyed on program + input
            # shapes, both unchanged — no reset, no recompile
            self._variables = self._place_variables(variables)
            self._ckpt_counters["hot_reloads"] += 1
            self._ckpt_counters["last_reload_step"] = int(step)
            self._loaded_step = int(step)
            logger.info("hot-reloaded weights from %s (step %d, "
                        "0 new compiles)", path, step)
        elif module is not None:
            self.load_jax(module, variables)
            self._ckpt_counters["hot_reloads"] += 1
            self._ckpt_counters["full_reloads"] += 1
            self._ckpt_counters["last_reload_step"] = int(step)
            self._loaded_step = int(step)
            logger.warning("hot-reload of %s changed the model structure; "
                           "executables reset (buckets recompile)", path)
        else:
            self._ckpt_counters["reload_skips"] += 1
            logger.warning("hot-reload skipped: %s does not match the "
                           "served model's structure and carries no "
                           "module", path)

    def ckpt_stats(self) -> Dict:
        """Hot-reload counters for the serving metrics surface (empty until
        the first reload attempt, so metrics() can omit the section)."""
        return {k: v for k, v in self._ckpt_counters.items()
                if v is not None} if any(
            v for v in self._ckpt_counters.values()) else {}

    def save_encrypted(self, module, path: str, passphrase: str):
        """Encrypted checkpoint at rest (the TPU-native analogue of the
        reference's encrypted-model serving,
        InferenceModel.scala:315-323 doLoadEncryptedOpenVINO): the
        serialized checkpoint bytes are sealed with authenticated
        encryption (utils/crypto.py — PBKDF2 key derivation, HMAC-CTR
        stream cipher, encrypt-then-MAC)."""
        from ...utils.crypto import encrypt_bytes
        with open(path, "wb") as f:
            f.write(encrypt_bytes(self._dump_blob(module), passphrase))

    def load_encrypted(self, path: str, passphrase: str) -> "InferenceModel":
        """Load a ``save_encrypted`` artifact. The integrity tag is
        verified BEFORE unpickling, so a tampered file or wrong key fails
        loudly without deserializing attacker-controlled bytes."""
        from ...utils.crypto import decrypt_bytes
        with open(path, "rb") as f:
            return self._load_blob(decrypt_bytes(f.read(), passphrase))

    def load_tf(self, model_path: str, backend: str = "convert",
                input_names=None, output_names=None, **_
                ) -> "InferenceModel":
        """Load a TF SavedModel / .h5 keras model, a frozen ``.pb`` graphdef
        (with ``input_names``/``output_names``), or an ``export_tf`` folder
        (reference load_tf variants, inference_model.py:70 +
        TFNet.scala:56). Keras models are converted to flax and compiled for
        TPU when possible; frozen graphs execute via the TFNet path."""
        import os
        import tensorflow as tf
        frozen_in_dir = (os.path.isdir(model_path) and os.path.exists(
            os.path.join(model_path, "frozen_inference_graph.pb")))
        if model_path.endswith(".pb") or frozen_in_dir:
            from ...tfpark import TFNet
            if frozen_in_dir:
                net = TFNet.from_export_folder(model_path)
            else:
                if not (input_names and output_names):
                    raise ValueError(
                        "frozen .pb needs input_names and output_names "
                        "(tensor names like 'input:0')")
                net = TFNet.from_frozen_graph(model_path, input_names,
                                              output_names)
            donor = net.as_inference_model()
            self._apply_fn = donor._apply_fn
            self._variables = donor._variables
            self._eager = donor._eager
            self._reset_executables()
            return self
        model = tf.keras.models.load_model(model_path)
        try:
            from ...orca.learn.tf2.keras_bridge import build_flax_from_keras
            import jax
            module, loader = build_flax_from_keras(model)
            sample_shape = model.inputs[0].shape.as_list()
            sample_shape[0] = 1
            sample = np.zeros([d or 1 for d in sample_shape], np.float32)
            variables = module.init(jax.random.PRNGKey(0), sample)
            variables = loader(variables)
            return self.load_jax(module, variables)
        except Exception as e:
            # non-convertible graph: execute via call_tf. call_tf runs the
            # original TF kernels on the host CPU — it will NOT compile to a
            # TPU executable, so predict() on a TPU-only deployment fails or
            # runs slow. Surface that now, not at predict time.
            logger.warning(
                "keras->flax conversion failed (%s: %s); falling back to "
                "jax2tf.call_tf, which executes TensorFlow kernels on host "
                "CPU and cannot be compiled for TPU. Re-export the model "
                "with supported layers for a native TPU path.",
                type(e).__name__, e)
            from jax.experimental import jax2tf
            cfn = jax2tf.call_tf(model)     # once — apply_fn runs per request

            def apply_fn(variables, *x):
                return cfn(x[0] if len(x) == 1 else list(x))

            self._apply_fn = apply_fn
            self._variables = {}
            self._eager = True
            self._reset_executables()
            return self

    def load_openvino(self, *args, **kwargs):
        raise NotImplementedError(
            "OpenVINO is an Intel-CPU backend (reference: "
            "OpenVinoInferenceSupportive.scala JNI); on TPU use load_tf or "
            "load_jax — models compile to XLA executables instead.")

    def load_torch(self, torch_module) -> "InferenceModel":
        """(reference load_torch executes via JEP; here: convert to flax)"""
        from ...orca.learn.pytorch.torch_bridge import build_flax_from_torch
        import jax
        module, loader = build_flax_from_torch(torch_module)
        # lazily init on first predict (input shape unknown here)
        self._pending_torch = (module, loader)

        def apply_fn(variables, *x):
            return module.apply(variables, *x)

        self._apply_fn = apply_fn
        self._variables = None
        self._eager = False
        self._reset_executables()
        return self

    # --- predict ------------------------------------------------------------
    def precompile(self, example, max_bucket: Optional[int] = None
                   ) -> "InferenceModel":
        """Compile the executable for every shape bucket up front.

        The reference pre-copies model replicas into a blocking queue before
        serving starts so no request pays model-setup cost
        (InferenceModel.scala:580-626); the XLA analogue of that cost is
        per-bucket compilation, which otherwise lands in the latency tail of
        whichever unlucky request first hits each bucket (e.g. timeout-sized
        partial batches).

        ``example`` is a batch (leading dim = batch, any size); every bucket
        <= ``max_bucket`` (default: all buckets) is compiled by running a
        zero-filled batch of exactly the bucket size through ``predict``,
        warming exactly the cache the serving path uses.
        """
        if self._eager:
            # eager (call_tf) models have no jit cache to warm; probing
            # would run the full TF graph once per bucket for zero benefit
            return self
        multi = isinstance(example, (list, tuple))
        xs = [np.asarray(a) for a in (example if multi else [example])]
        if max_bucket is None:
            targets = list(self.buckets)
        else:
            # max_bucket is a batch size: warm every bucket a batch of up
            # to that size can land in, including the rounded-up one
            # (predict pads partial batches UP via _bucket)
            top = _bucket(max_bucket, self.buckets)
            targets = [b for b in self.buckets if b <= top]
            if top not in targets:
                targets.append(top)
        for b in targets:
            probe = [np.zeros((b,) + a.shape[1:], a.dtype) for a in xs]
            self.predict(probe if multi else probe[0])
        return self

    def predict(self, inputs) -> np.ndarray:
        """Batch predict with shape bucketing + executable cache (replaces the
        model-copy queue, InferenceModel.scala:580-626)."""
        import jax

        if self._apply_fn is None:
            raise RuntimeError("no model loaded")
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        xs = [np.asarray(a) for a in xs]
        if self._variables is None and hasattr(self, "_pending_torch"):
            module, loader = self._pending_torch
            variables = module.init(jax.random.PRNGKey(0),
                                    *[a[:1] for a in xs])
            self._variables = self._place_variables(loader(variables))
        n = len(xs[0])
        if self._eager:
            # no compilation to amortize — padding would just run the TF
            # graph on phantom rows
            out = self._apply_fn(self._variables, *xs)
        else:
            out = self._predict_device(xs, n)
        out = jax.device_get(out)
        if isinstance(out, (list, tuple)):
            return type(out)(np.asarray(o)[:n] for o in out)
        return np.asarray(out)[:n]

    def _predict_device(self, xs, n: int):
        """Run the bucketed executable; returns the ON-DEVICE output, batch
        dim sharded over the mesh (all local chips compute). ``predict``
        fetches to host; callers that keep chaining on device can use this
        directly."""
        import jax

        b = _bucket(n, self.buckets)
        padded = [np.concatenate(
            [a, np.zeros((b - n,) + a.shape[1:], a.dtype)]) if b > n
            else np.asarray(a) for a in xs]
        dev = [self._shard_batch(a) for a in padded]
        key = (b,) + tuple((a.shape[1:], str(a.dtype)) for a in padded)
        with self._lock:
            fn = self._cache.get(key)
            if fn is None:
                if self._jit_apply is None:
                    base = self._apply_fn
                    if self._prologue is not None:
                        prol = self._prologue

                        def base(variables, *x, _fn=self._apply_fn,
                                 _p=prol):
                            # prologue traced INSIDE the executable: XLA
                            # fuses the cast/normalize into the first layer
                            return _fn(variables, *_p.apply_x(tuple(x)))
                    self._jit_apply = (
                        self._cc.wrap(base, label="serving")
                        if self._cc is not None
                        else jax.jit(base))
                fn = self._jit_apply
                self._cache[key] = fn
        return fn(self._variables, *dev)

    def compile_stats(self) -> Dict:
        """Compile-plane counters for this model's executable cache
        (empty when the plane is disabled) — lets the serving engine's
        ``precompile`` timer distinguish cache hits from real compiles."""
        return self._cc.stats.snapshot() if self._cc is not None else {}

    def distributed_predict(self, shards, batch_size: int = 64):
        """Predict over XShards (reference: PythonOrca.
        inferenceModelDistriPredict, zoo/.../orca/python/PythonOrca.scala:36)."""
        from ...orca.learn.utils import xshards_from_arrays
        norm = xshards_from_arrays(shards)

        def run(part):
            return {"prediction": self.predict(list(part["x"]))}

        return norm.transform_shard(run)
