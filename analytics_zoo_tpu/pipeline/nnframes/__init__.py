from .nn_classifier import (NNClassifier, NNClassifierModel, NNEstimator,
                            NNModel)
from .nn_image_reader import NNImageReader
