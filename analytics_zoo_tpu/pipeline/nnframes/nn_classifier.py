"""NNFrames — DataFrame ML pipeline API (parity: pyzoo/zoo/pipeline/nnframes/
nn_classifier.py — NNEstimator:139, NNModel:517, NNClassifier:613,
NNClassifierModel:660; Scala nnframes/NNEstimator.scala:202).

The reference wraps Spark ML Estimator/Transformer over Spark DataFrames;
here the same fit(df) -> model, model.transform(df) -> df-with-prediction
contract runs on pandas DataFrames over the one TPU engine. Feature/label
preprocessing mirrors the SeqToTensor/ArrayToTensor converters."""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np
import pandas as pd


def _col_to_array(df: pd.DataFrame, col: str) -> np.ndarray:
    vals = df[col].to_numpy()
    if len(vals) and isinstance(vals[0], (list, tuple, np.ndarray)):
        return np.stack([np.asarray(v, np.float32) for v in vals])
    return vals.astype(np.float32).reshape(-1, 1)


class NNEstimator:
    """fit(df) trains the flax module on featuresCol/labelCol.

    Parameters mirror the reference: model, criterion (loss), plus optional
    feature_preprocessing sizes (accepted for API parity; shapes are derived
    from the data)."""

    def __init__(self, model, criterion="mean_squared_error",
                 feature_preprocessing=None, label_preprocessing=None):
        self.model = model
        self.criterion = criterion
        self._features_col = "features"
        self._label_col = "label"
        self._predictions_col = "prediction"
        self._batch_size = 32
        self._max_epoch = 10
        self._optim_method = "adam"
        self._learning_rate = None      # None = optimizer's own default
        self._caching_sample = True

    # --- Spark-ML style setters (reference NNEstimator setters) -------------
    def setFeaturesCol(self, name: str) -> "NNEstimator":
        self._features_col = name
        return self

    def setLabelCol(self, name: str) -> "NNEstimator":
        self._label_col = name
        return self

    def setPredictionCol(self, name: str) -> "NNEstimator":
        self._predictions_col = name
        return self

    def setBatchSize(self, bs: int) -> "NNEstimator":
        self._batch_size = int(bs)
        return self

    def setMaxEpoch(self, n: int) -> "NNEstimator":
        self._max_epoch = int(n)
        return self

    def setOptimMethod(self, opt) -> "NNEstimator":
        self._optim_method = opt
        return self

    def setLearningRate(self, lr: float) -> "NNEstimator":
        self._learning_rate = float(lr)
        return self

    def setCachingSample(self, b: bool) -> "NNEstimator":
        self._caching_sample = bool(b)
        return self

    # snake_case aliases
    set_features_col = setFeaturesCol
    set_label_col = setLabelCol
    set_batch_size = setBatchSize
    set_max_epoch = setMaxEpoch

    def _make_estimator(self):
        from analytics_zoo_tpu.orca.learn.estimator import TPUEstimator
        opt = self._optim_method
        if isinstance(opt, str) and self._learning_rate is not None:
            # only an explicit setLearningRate overrides; lr-less optimizers
            # (e.g. adadelta) keep working with their own defaults
            from analytics_zoo_tpu.orca.learn.optimizers.optimizers_impl \
                import convert_optimizer
            opt = convert_optimizer(opt, learning_rate=self._learning_rate)
        return TPUEstimator(self.model, loss=self.criterion, optimizer=opt)

    def _label_array(self, df: pd.DataFrame) -> np.ndarray:
        y = _col_to_array(df, self._label_col)
        return y

    def fit(self, df: pd.DataFrame) -> "NNModel":
        x = _col_to_array(df, self._features_col)
        y = self._label_array(df)
        est = self._make_estimator()
        est.fit({"x": x, "y": y}, epochs=self._max_epoch,
                batch_size=self._batch_size, verbose=False)
        return self._make_model(est)

    def _make_model(self, est) -> "NNModel":
        m = NNModel(self.model, estimator=est)
        m._features_col = self._features_col
        m._predictions_col = self._predictions_col
        m._batch_size = self._batch_size
        return m


class NNModel:
    """transform(df) appends the prediction column (reference NNModel:517)."""

    def __init__(self, model, estimator=None):
        self.model = model
        if estimator is None:
            from analytics_zoo_tpu.orca.learn.estimator import TPUEstimator
            estimator = TPUEstimator(model, loss="mean_squared_error",
                                     optimizer="adam")
        self.estimator = estimator
        self._features_col = "features"
        self._predictions_col = "prediction"
        self._batch_size = 32

    def setFeaturesCol(self, name: str) -> "NNModel":
        self._features_col = name
        return self

    def setPredictionCol(self, name: str) -> "NNModel":
        self._predictions_col = name
        return self

    def setBatchSize(self, bs: int) -> "NNModel":
        self._batch_size = int(bs)
        return self

    def _predict_array(self, df: pd.DataFrame) -> np.ndarray:
        x = _col_to_array(df, self._features_col)
        return np.asarray(self.estimator.predict(
            {"x": x}, batch_size=self._batch_size))

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        preds = self._predict_array(df)
        out = df.copy()
        out[self._predictions_col] = list(preds)
        return out

    def save(self, path: str):
        self.estimator.save(path)

    @classmethod
    def load(cls, model, path: str) -> "NNModel":
        m = cls(model)
        m.estimator.load(path)
        return m


class NNClassifier(NNEstimator):
    """Classification specialisation (reference NNClassifier:613): labels are
    class ids; prediction is argmax."""

    def __init__(self, model, criterion="sparse_categorical_crossentropy",
                 feature_preprocessing=None):
        super().__init__(model, criterion, feature_preprocessing)

    def _label_array(self, df: pd.DataFrame) -> np.ndarray:
        return df[self._label_col].to_numpy().astype(np.int32)

    def _make_model(self, est) -> "NNClassifierModel":
        m = NNClassifierModel(self.model, estimator=est)
        m._features_col = self._features_col
        m._predictions_col = self._predictions_col
        m._batch_size = self._batch_size
        return m


class NNClassifierModel(NNModel):
    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        probs = self._predict_array(df)
        out = df.copy()
        out[self._predictions_col] = np.argmax(probs, -1).astype(np.int64)
        return out
