"""NNImageReader (parity: pyzoo/zoo/pipeline/nnframes/nn_image_reader.py:25 —
read image files into a DataFrame with an image-struct column)."""

from __future__ import annotations

import glob
import os
from typing import Optional

import numpy as np
import pandas as pd


def _decode(path: str) -> Optional[dict]:
    try:
        from PIL import Image
        with Image.open(path) as im:
            im = im.convert("RGB")
            arr = np.asarray(im, np.uint8)
        return {"origin": path, "height": arr.shape[0],
                "width": arr.shape[1], "nChannels": arr.shape[2],
                "mode": 16, "data": arr}
    except ImportError:
        # PIL not in the image: fall back to raw bytes record
        with open(path, "rb") as f:
            data = f.read()
        return {"origin": path, "height": -1, "width": -1, "nChannels": -1,
                "mode": -1, "data": np.frombuffer(data, np.uint8)}
    except Exception:
        return None


class NNImageReader:
    @staticmethod
    def readImages(path: str, min_partitions: int = 1,
                   resize_height: int = -1, resize_width: int = -1,
                   image_codec: int = -1) -> pd.DataFrame:
        if os.path.isdir(path):
            files = sorted(
                p for p in glob.glob(os.path.join(path, "**", "*"),
                                     recursive=True) if os.path.isfile(p))
        else:
            files = sorted(glob.glob(path))
        rows = []
        for p in files:
            rec = _decode(p)
            if rec is None:
                continue
            if resize_height > 0 and resize_width > 0 and rec["height"] > 0:
                try:
                    from PIL import Image
                    im = Image.fromarray(rec["data"]).resize(
                        (resize_width, resize_height))
                    rec["data"] = np.asarray(im, np.uint8)
                    rec["height"], rec["width"] = resize_height, resize_width
                except ImportError:
                    pass
            rows.append({"image": rec})
        return pd.DataFrame(rows)

    read_images = readImages
