"""Resilience plane — fault injection, retry/backoff, dispatch watchdog,
and checkpoint-backed training supervision.

Lightweight by construction: this package imports only stdlib at module
load (the hot paths import :mod:`.faults` and :mod:`.watchdog` — their
disabled cost is one global read), and the supervisor pulls the estimator
stack in lazily.
"""

from . import faults  # noqa: F401  (re-exported module: faults.fire etc.)
from .retry import CircuitBreaker, RetryBudgetExceeded, RetryPolicy
from .stats import STATS, ResilienceStats, resilience_snapshot
from .watchdog import (DispatchTimeout, DispatchWatchdog, classify,
                       default_timeout_s)

__all__ = ["faults", "RetryPolicy", "RetryBudgetExceeded", "CircuitBreaker",
           "DispatchTimeout", "DispatchWatchdog", "classify",
           "default_timeout_s", "STATS", "ResilienceStats",
           "resilience_snapshot", "TrainingSupervisor", "SupervisorGiveUp"]


def __getattr__(name):
    # TrainingSupervisor imports the estimator stack — resolve lazily so
    # native/transfer.py can import this package without a cycle
    if name in ("TrainingSupervisor", "SupervisorGiveUp"):
        from . import supervisor as _sup
        return getattr(_sup, name)
    raise AttributeError(name)
