"""Deterministic, seedable fault injection for the whole stack.

At pod scale device loss, wedged collectives and broker drops are the
steady state — recovery code that only runs in production incidents is
untested code. This registry lets tests, the chaos CI gate and
``bench.py --only resilience`` arm *named fault sites* that the hot paths
already carry as zero-cost-when-disabled hooks:

==================  ========================================================
site                where it fires
==================  ========================================================
``h2d.put``         ``native/transfer.py`` — every host→device placement
``engine.dispatch`` ``orca/learn/engine.py`` — every train-step dispatch
``ckpt.blob_io``    ``ckpt/store.py`` — every checkpoint blob write
``serving.decode``  ``serving/engine.py`` — every serving batch decode
``broker.connect``  ``serving/redis_protocol.py`` — every broker (re)connect
==================  ========================================================

Arming is either programmatic (the :func:`inject` context manager, used by
the chaos tests) or via ``ZOO_FAULTS`` for whole-process runs::

    ZOO_FAULTS="engine.dispatch:p=1.0,count=1,skip=3"          # one-shot
    ZOO_FAULTS="h2d.put:p=0.05;broker.connect:count=2,kind=connection"

Per-site spec keys: ``p`` (fire probability, default 1.0), ``count`` (max
fires, default unlimited), ``skip`` (eligible calls to let pass first —
"fault at step k"), ``mode`` (``raise`` | ``delay``: a delay models a hang
for the watchdog instead of a crash), ``delay`` (seconds, delay mode),
``kind`` (``runtime`` | ``connection``: which exception class fires).
Draws come from one ``random.Random`` per site seeded with
``(ZOO_FAULT_SEED, site)``, so a fixed seed replays the exact fire pattern
regardless of which other sites run in the process.

The hook the production code calls is :func:`fire` — a module-global
``None`` check when nothing is armed, so the disabled cost is one load +
compare (gated unmeasurable in ``bench_infeed``, ±2%).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from .stats import STATS

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["InjectedFault", "InjectedConnectionFault", "FaultRegistry",
           "fire", "enabled", "activate", "deactivate", "inject",
           "registry_from_env", "KNOWN_SITES"]

#: the sites threaded into the stack (arming others is allowed — custom
#: code can add its own fire() calls — but gets a log warning)
KNOWN_SITES = ("h2d.put", "engine.dispatch", "ckpt.blob_io",
               "serving.decode", "broker.connect")


class InjectedFault(RuntimeError):
    """Raised by an armed fault site (``kind=runtime``, the default)."""


class InjectedConnectionFault(InjectedFault, ConnectionError):
    """``kind=connection`` — lands in the brokers' reconnect/retry
    classification like a real dropped socket."""


class _FaultSpec:
    __slots__ = ("site", "prob", "count", "skip", "mode", "delay_s", "kind",
                 "rng", "fired", "calls")

    def __init__(self, site: str, prob: float, count: Optional[int],
                 skip: int, mode: str, delay_s: float, kind: str, seed: int):
        if mode not in ("raise", "delay"):
            raise ValueError(f"fault mode must be raise|delay, got {mode!r}")
        if kind not in ("runtime", "connection"):
            raise ValueError(f"fault kind must be runtime|connection, "
                             f"got {kind!r}")
        self.site = site
        self.prob = float(prob)
        self.count = count
        self.skip = int(skip)
        self.mode = mode
        self.delay_s = float(delay_s)
        self.kind = kind
        # per-site stream: the fire pattern under a fixed seed depends only
        # on this site's own call sequence, never on interleaving with
        # other sites
        self.rng = random.Random(f"{seed}:{site}")
        self.fired = 0
        self.calls = 0


class FaultRegistry:
    """Armed fault specs + deterministic draw state. One registry is
    *active* process-wide at a time (:func:`activate`); the production
    hooks consult it through :func:`fire`."""

    def __init__(self, seed: Optional[int] = None):
        self.seed = (int(os.environ.get("ZOO_FAULT_SEED", "0"))
                     if seed is None else int(seed))
        self._lock = threading.Lock()
        self._specs: Dict[str, _FaultSpec] = {}

    def arm(self, site: str, prob: float = 1.0,
            count: Optional[int] = None, skip: int = 0,
            mode: str = "raise", delay_s: float = 0.5,
            kind: str = "runtime") -> "FaultRegistry":
        if site not in KNOWN_SITES:
            logger.warning("arming fault site %r not threaded into the "
                           "stack (known: %s)", site, ", ".join(KNOWN_SITES))
        with self._lock:
            self._specs[site] = _FaultSpec(site, prob, count, skip, mode,
                                           delay_s, kind, self.seed)
        return self

    def disarm(self, site: Optional[str] = None):
        with self._lock:
            if site is None:
                self._specs.clear()
            else:
                self._specs.pop(site, None)

    def fire(self, site: str):
        with self._lock:
            spec = self._specs.get(site)
            if spec is None:
                return
            spec.calls += 1
            if spec.count is not None and spec.fired >= spec.count:
                return
            if spec.calls <= spec.skip:
                return
            if spec.prob < 1.0 and spec.rng.random() >= spec.prob:
                return
            spec.fired += 1
            mode, delay_s, kind = spec.mode, spec.delay_s, spec.kind
            n = spec.fired
        STATS.add(f"fault.{site}")
        if mode == "delay":
            logger.warning("fault injection: site %s stalling %.2fs "
                           "(fire %d)", site, delay_s, n)
            time.sleep(delay_s)
            return
        exc = (InjectedConnectionFault if kind == "connection"
               else InjectedFault)
        logger.warning("fault injection: site %s raising %s (fire %d)",
                       site, exc.__name__, n)
        raise exc(f"injected fault at {site} (fire {n})")

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            return {s.site: {"fired": s.fired, "calls": s.calls,
                             "prob": s.prob, "mode": s.mode}
                    for s in self._specs.values()}


# --- the hook the production code calls -------------------------------------

_active: Optional[FaultRegistry] = None


def fire(site: str) -> None:
    """Zero-cost-when-disabled fault hook: one global load + compare."""
    reg = _active
    if reg is not None:
        reg.fire(site)


def enabled() -> bool:
    return _active is not None


def activate(registry: FaultRegistry) -> FaultRegistry:
    global _active
    _active = registry
    return registry


def deactivate():
    global _active
    _active = None


@contextmanager
def inject(site: Optional[str] = None, *, seed: Optional[int] = None,
           registry: Optional[FaultRegistry] = None, **spec):
    """Arm faults for a scope::

        with faults.inject("engine.dispatch", count=1, skip=3):
            supervisor.fit(...)

    With ``site=None`` an empty (or caller-built) registry activates —
    arm sites on the yielded registry. The previously active registry is
    restored on exit, so scopes nest."""
    global _active
    reg = registry if registry is not None else FaultRegistry(seed=seed)
    if site is not None:
        reg.arm(site, **spec)
    prev, _active = _active, reg
    try:
        yield reg
    finally:
        _active = prev


# --- env arming -------------------------------------------------------------

def registry_from_env(spec: Optional[str] = None,
                      seed: Optional[int] = None
                      ) -> Optional[FaultRegistry]:
    """Parse a ``ZOO_FAULTS`` spec string into a registry (None when
    empty). Format: ``site:k=v,k=v;site2:...``; bare ``site`` arms an
    always-fire raise."""
    spec = os.environ.get("ZOO_FAULTS", "") if spec is None else spec
    spec = spec.strip()
    if not spec:
        return None
    reg = FaultRegistry(seed=seed)
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, kvs = part.partition(":")
        kw: Dict = {}
        for kv in filter(None, (s.strip() for s in kvs.split(","))):
            k, _, v = kv.partition("=")
            if k == "p":
                kw["prob"] = float(v)
            elif k == "count":
                kw["count"] = int(v)
            elif k == "skip":
                kw["skip"] = int(v)
            elif k == "mode":
                kw["mode"] = v
            elif k == "delay":
                kw["delay_s"] = float(v)
            elif k == "kind":
                kw["kind"] = v
            else:
                raise ValueError(f"unknown ZOO_FAULTS key {k!r} in {part!r}")
        reg.arm(site.strip(), **kw)
    return reg


# whole-process chaos runs (the CI gate, operator drills) arm at import:
# the hooks are live from the first device_put on
_env_registry = registry_from_env()
if _env_registry is not None:
    activate(_env_registry)
