"""Shared retry/backoff policy + circuit breaker.

Every layer of the stack used to hand-roll its own retry loop — bench.py's
``BENCH_INIT_RETRIES`` driver-init probe, TrialRuntime's
``retry_backoff_s * 2**n`` trial backoff, the estimator's
one-blocking-retry checkpoint path. :class:`RetryPolicy` is the one
implementation: bounded exponential backoff with optional deterministic
jitter, and a transient/fatal classification so a genuinely fatal error
(bad config, corrupt input) never burns the budget that a flaky driver or
dropped socket deserves.

:class:`CircuitBreaker` is the serving-side complement: after
``threshold`` consecutive failures it *opens* (requests are shed without
touching the wedged model/device), after ``cooldown_s`` it *half-opens*
and admits exactly one probe; the probe's outcome closes or re-opens it.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, Tuple, Type, Union

from .faults import InjectedFault
from .stats import STATS

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["RetryPolicy", "RetryBudgetExceeded", "CircuitBreaker",
           "DEFAULT_TRANSIENT"]

#: error classes retried by default: dropped connections, timeouts, IO
#: errors, and injected chaos faults (which model exactly those)
DEFAULT_TRANSIENT: Tuple[Type[BaseException], ...] = (
    ConnectionError, TimeoutError, OSError, InjectedFault)

#: substrings marking a transient accelerator-runtime error (the JAX/PJRT
#: driver surfaces chip contention and resets as RuntimeError text)
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED",
                      "RESOURCE_EXHAUSTED", "ABORTED", "device lost")


class RetryBudgetExceeded(RuntimeError):
    """All attempts failed; ``__cause__`` carries the last error."""


class RetryPolicy:
    """Bounded exponential backoff with classification.

    Parameters
    ----------
    max_attempts : total tries, including the first (1 = no retry).
    base_delay_s / multiplier / max_delay_s : attempt ``n`` (1-based)
        waits ``min(base * multiplier**(n-1), max)`` before retrying.
    jitter_frac : ± fraction of the delay drawn from ``rng`` (seedable,
        so tests and the AutoML scheduler stay deterministic at 0).
    transient : exception classes (or a predicate) worth retrying;
        defaults to :data:`DEFAULT_TRANSIENT` plus anything whose message
        carries a transient accelerator-runtime marker (UNAVAILABLE, ...).
    fatal : classes never retried even when ``transient`` matches.
    """

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.5,
                 max_delay_s: float = 30.0, multiplier: float = 2.0,
                 jitter_frac: float = 0.1,
                 transient: Union[None, Callable, Tuple, Type] = None,
                 fatal: Tuple[Type[BaseException], ...] = (),
                 name: Optional[str] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 seed: int = 0):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter_frac = float(jitter_frac)
        self._transient = transient
        self._fatal = tuple(fatal)
        self.name = name or "retry"
        self._sleep = sleep
        self._rng = random.Random(seed)

    # --- classification -----------------------------------------------------
    def is_transient(self, exc: BaseException) -> bool:
        if isinstance(exc, self._fatal) or \
                isinstance(exc, (KeyboardInterrupt, SystemExit)):
            return False
        t = self._transient
        if t is None:
            if isinstance(exc, DEFAULT_TRANSIENT):
                return True
            msg = str(exc)
            return any(m in msg for m in _TRANSIENT_MARKERS)
        if callable(t) and not isinstance(t, (tuple, type)):
            return bool(t(exc))
        return isinstance(exc, t)

    # --- backoff ------------------------------------------------------------
    def delay_for(self, attempt: int) -> float:
        """Seconds to wait after failed attempt ``attempt`` (1-based)."""
        d = min(self.base_delay_s * self.multiplier ** (max(attempt, 1) - 1),
                self.max_delay_s)
        if self.jitter_frac:
            d *= 1.0 + self.jitter_frac * (2.0 * self._rng.random() - 1.0)
        return max(d, 0.0)

    # --- driver -------------------------------------------------------------
    def call(self, fn: Callable, *args,
             on_retry: Optional[Callable] = None, **kwargs):
        """Run ``fn`` under the policy. ``on_retry(attempt, exc, delay_s)``
        fires before each backoff sleep. A fatal (non-transient) error or
        an exhausted budget raises the last error unchanged — callers keep
        their exception contract."""
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:      # noqa: BLE001 — classified below
                last = e
                if not self.is_transient(e) or attempt >= self.max_attempts:
                    raise
                delay = self.delay_for(attempt)
                STATS.add(f"retry.{self.name}")
                logger.warning(
                    "%s: attempt %d/%d failed (%s: %s); retrying in %.2fs",
                    self.name, attempt, self.max_attempts,
                    type(e).__name__, e, delay)
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                self._sleep(delay)
        raise RetryBudgetExceeded(self.name) from last   # pragma: no cover


class CircuitBreaker:
    """closed → (``threshold`` consecutive failures) → open →
    (``cooldown_s``) → half-open → one probe → closed / open.

    Thread-safe; ``allow()`` is the admission check callers run before
    dispatching work to the protected resource, paired with exactly one
    ``record_success()`` / ``record_failure()`` per allowed dispatch."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 30.0,
                 name: str = "breaker",
                 clock: Callable[[], float] = time.monotonic):
        import threading
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "closed"
        self.consecutive_failures = 0
        self.trips = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    def allow(self) -> bool:
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self.state = "half_open"
                    self._probe_inflight = True
                    logger.warning("%s: half-open, admitting one probe",
                                   self.name)
                    return True
                return False
            # half_open: exactly one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self):
        with self._lock:
            if self.state != "closed":
                logger.warning("%s: probe succeeded, closing", self.name)
            self.state = "closed"
            self.consecutive_failures = 0
            self._probe_inflight = False

    def record_failure(self):
        with self._lock:
            self.consecutive_failures += 1
            reopen = self.state == "half_open"
            trip = (self.state == "closed"
                    and self.consecutive_failures >= self.threshold)
            if reopen or trip:
                self.state = "open"
                self._opened_at = self._clock()
                self._probe_inflight = False
                self.trips += 1
        if reopen or trip:
            STATS.add(f"breaker.{self.name}.trips")
            logger.warning(
                "%s: OPEN after %d consecutive failures (cooldown %.1fs)",
                self.name, self.consecutive_failures, self.cooldown_s)

    def snapshot(self) -> dict:
        """Read-only view. The reported ``state`` is *effective*: an open
        circuit whose cooldown has elapsed reads as ``half_open`` even
        though the transition itself happens lazily in :meth:`allow` —
        otherwise a readiness probe on an idle (traffic-removed) server
        would see ``open`` forever and never let traffic back to run the
        probe that closes it."""
        with self._lock:
            state = self.state
            remaining = 0.0
            if state == "open":
                remaining = self.cooldown_s - (self._clock()
                                               - self._opened_at)
                if remaining <= 0:
                    state = "half_open"
                    remaining = 0.0
            return {"state": state, "trips": self.trips,
                    "consecutive_failures": self.consecutive_failures,
                    "threshold": self.threshold,
                    "cooldown_s": self.cooldown_s,
                    "cooldown_remaining_s": round(max(remaining, 0.0), 3)}
