"""Process-wide resilience counters.

One flat, thread-safe counter table shared by every resilience component:
the fault registry reports fires per site, the watchdog reports trips, the
supervisor reports restarts / replayed steps, RetryPolicy reports retries,
and the serving engine reports shed requests and breaker transitions. The
existing observability surfaces pick the snapshot up —
``estimator.data_pipeline_stats()["resilience"]``, serving
``metrics()["resilience"]`` / HTTP ``/metrics``, and
``TrialRuntime.summary()["resilience"]`` — so a pod operator reads fault
history in the same place as throughput.

Since the observability plane (PR 10) the backing store is the unified
metrics registry: every ``add(key)`` increments the
``zoo_resilience_events_total{event=key}`` counter family in
``analytics_zoo_tpu.obs.registry.REGISTRY``, and :meth:`ResilienceStats.
snapshot` is a *view over the registry* — the dict API is unchanged
(empty until something fires), and the same counters now also serve on
the Prometheus exposition (``/metrics.prom``, ``zoo-metrics dump``).
"""

from __future__ import annotations

from typing import Dict

from ..obs.registry import REGISTRY

__all__ = ["ResilienceStats", "STATS", "resilience_snapshot"]

_FAMILY_NAME = "zoo_resilience_events_total"
_FAMILY_DOC = ("resilience-plane events by kind: fault fires, watchdog "
               "trips, supervisor restarts, retries, serving sheds/drains")


class ResilienceStats:
    """Monotonic named counters; empty snapshot until something happens, so
    surfaces can omit the section on healthy runs. Backed by one registry
    counter family — instances share it (the process-wide :data:`STATS` is
    the only instance the stack creates)."""

    def __init__(self):
        self._family = REGISTRY.counter(_FAMILY_NAME, _FAMILY_DOC,
                                        labelnames=("event",))

    def add(self, key: str, n: float = 1):
        # labels() is itself a get-or-create cache (one dict get when the
        # child exists) — no second cache layer needed
        self._family.labels(event=key).inc(n)

    def snapshot(self) -> Dict[str, float]:
        out = {}
        for labels, child in self._family.samples():
            v = child.value
            if v:
                v = int(v) if float(v).is_integer() else round(v, 6)
                out[labels["event"]] = v
        return dict(sorted(out.items()))

    def reset(self):
        self._family.clear()


#: the process-wide table every resilience component reports into
STATS = ResilienceStats()


def resilience_snapshot() -> Dict[str, float]:
    """Global resilience counters (empty dict when nothing has fired)."""
    return STATS.snapshot()
