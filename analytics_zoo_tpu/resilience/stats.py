"""Process-wide resilience counters.

One flat, thread-safe counter table shared by every resilience component:
the fault registry reports fires per site, the watchdog reports trips, the
supervisor reports restarts / replayed steps, RetryPolicy reports retries,
and the serving engine reports shed requests and breaker transitions. The
existing observability surfaces pick the snapshot up —
``estimator.data_pipeline_stats()["resilience"]``, serving
``metrics()["resilience"]`` / HTTP ``/metrics``, and
``TrialRuntime.summary()["resilience"]`` — so a pod operator reads fault
history in the same place as throughput.
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = ["ResilienceStats", "STATS", "resilience_snapshot"]


class ResilienceStats:
    """Monotonic named counters; empty snapshot until something happens, so
    surfaces can omit the section on healthy runs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, float] = {}

    def add(self, key: str, n: float = 1):
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in sorted(self._counts.items())}

    def reset(self):
        with self._lock:
            self._counts.clear()


#: the process-wide table every resilience component reports into
STATS = ResilienceStats()


def resilience_snapshot() -> Dict[str, float]:
    """Global resilience counters (empty dict when nothing has fired)."""
    return STATS.snapshot()
