"""Training supervisor — checkpoint-backed automatic recovery for fit().

PR 6 made checkpoints async, atomic and nearly free; this is the layer
that *uses* them. The supervisor drives a training run as per-epoch
segments of ``TPUEstimator.fit`` (the segmented-fit contract PR 2 proved
bit-exact: ``fit(epochs=1, initial_epoch=i)`` re-aligns the shuffle seed,
the step counter rides the checkpoint, so N segments == one
uninterrupted N-epoch fit, bit for bit). Around each segment it arms:

* a :class:`~analytics_zoo_tpu.resilience.watchdog.DispatchWatchdog`
  bounding every device dispatch (``ZOO_DISPATCH_TIMEOUT_S``) — a wedged
  chip becomes a classified *hang* instead of an eternal wait;
* a :class:`~analytics_zoo_tpu.orca.learn.preemption.PreemptionWatcher`
  with the shared ``on_signal`` entry point, so SIGTERM checkpoints and
  returns a clean report.

On a hang, injected device loss, or unhandled step exception the
supervisor: flushes the checkpoint plane (queued ≠ durable is not
acceptable when the backend is about to be torn down), shuts the
estimator down, optionally drops the cached JAX backend (classified
device loss + ``ZOO_SUPERVISOR_REINIT_BACKEND=1`` — safe only when no
other component holds live device arrays), rebuilds the estimator from
its factory, restores the newest *committed* supervisor checkpoint
(``ckpt.format.loadable_step_dirs`` candidacy — torn writes can never be
the resume point), and resumes at the recorded epoch boundary. The
restart budget is bounded; exhausting it raises
:class:`SupervisorGiveUp` carrying a structured failure report instead
of a bare traceback soup.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..obs import trace as _trace
from . import watchdog as wd_mod
from .retry import RetryPolicy
from .stats import STATS
from .watchdog import DispatchTimeout, DispatchWatchdog, classify

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["TrainingSupervisor", "SupervisorGiveUp"]


class SupervisorGiveUp(RuntimeError):
    """Restart budget exhausted; ``.report`` is the structured failure
    report (attempt history, classifications, last checkpoint)."""

    def __init__(self, report: Dict[str, Any]):
        super().__init__(
            f"training supervisor gave up after "
            f"{report['restarts']} restart(s); last failure: "
            f"{report['failures'][-1]['error'] if report['failures'] else '?'}")
        self.report = report


class TrainingSupervisor:
    """Wraps ``TPUEstimator.fit`` with watchdog + auto-recovery.

    Parameters
    ----------
    estimator_factory : zero-arg callable returning a *fresh*
        ``TPUEstimator`` (same module/optimizer/seed each time — recovery
        rebuilds the engine through it). A bare estimator instance is
        accepted for convenience; recovery then reuses it (fine for step
        failures, insufficient for a genuinely lost backend).
    model_dir : checkpoint root (defaults to the estimator's own).
    max_restarts : recovery budget across the whole fit.
    dispatch_timeout_s : per-dispatch hang bound (default
        ``ZOO_DISPATCH_TIMEOUT_S``; None = no hang detection).
    retry_policy : backoff between restarts (default: 1s base, x2,
        capped 30s, deterministic).
    """

    def __init__(self, estimator_factory, *, model_dir: Optional[str] = None,
                 max_restarts: int = 3,
                 dispatch_timeout_s: Optional[float] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 poll_s: float = 0.05):
        if callable(estimator_factory):
            self._factory = estimator_factory
        else:
            est = estimator_factory
            self._factory = lambda: est
        self.model_dir = model_dir
        self.max_restarts = int(max_restarts)
        self.dispatch_timeout_s = dispatch_timeout_s
        self.poll_s = float(poll_s)
        self.retry_policy = retry_policy if retry_policy is not None else \
            RetryPolicy(max_attempts=self.max_restarts + 1, base_delay_s=1.0,
                        max_delay_s=30.0, jitter_frac=0.0,
                        name="supervisor.restart")
        self.report: Optional[Dict[str, Any]] = None

    # --- resume bookkeeping -------------------------------------------------
    @staticmethod
    def _latest_supervised(model_dir: str):
        """Newest committed checkpoint carrying the supervisor's epoch
        meta, as (step, epoch) — fit-internal trigger checkpoints (no
        meta) coexist but never drive epoch accounting."""
        import os

        from ..ckpt import format as fmt
        if not model_dir or not os.path.isdir(model_dir):
            return None, 0
        for step, path in reversed(fmt.loadable_step_dirs(model_dir)):
            if not fmt.is_plane_dir(path):
                continue
            try:
                meta = fmt.read_manifest(path).get("meta") or {}
            except Exception:       # noqa: BLE001 — torn/foreign manifest
                continue
            if "supervisor_epoch" in meta:
                return step, int(meta["supervisor_epoch"])
        return None, 0

    def _resume(self, est) -> int:
        step, epoch = self._latest_supervised(self.model_dir)
        if step is None:
            return 0
        path = est.load_checkpoint(self.model_dir, step=step)
        logger.info("supervisor: resuming from %s (epoch %d, step %d)",
                    path, epoch, step)
        return epoch

    # --- one epoch segment --------------------------------------------------
    def _run_segment(self, est, data, epoch: int, batch_size: int,
                     fit_kwargs: Dict, wd: DispatchWatchdog) -> Dict:
        """Run fit(epochs=1, initial_epoch=epoch) on a worker thread while
        the main thread watches for a watchdog trip. Returns
        {"stats": [...]} on success or {"error": exc, "kind": hang|crash};
        on a hang the worker thread is abandoned (the stuck dispatch holds
        it — recovery rebuilds the estimator, so its late writes land on a
        discarded engine)."""
        box: Dict[str, Any] = {}
        # trace handoff: the segment runs on a worker thread; adopting the
        # supervisor's token keeps fit's spans on the supervised trace
        tok = _trace.token()

        def target():
            try:
                with _trace.adopt(tok):
                    box["stats"] = est.fit(
                        data, epochs=1, batch_size=batch_size,
                        initial_epoch=epoch, max_failure_retries=0,
                        verbose=False, **fit_kwargs)
            except BaseException as e:      # noqa: BLE001 — classified
                box["error"] = e

        t = threading.Thread(target=target, daemon=True,
                             name=f"zoo-supervised-fit-ep{epoch}")
        t.start()
        while t.is_alive():
            t.join(self.poll_s)
            if wd.tripped.is_set() and t.is_alive():
                label, elapsed = wd.trips[-1] if wd.trips else ("?", 0.0)
                return {"error": DispatchTimeout(
                    label, elapsed, wd.timeout_s or 0.0), "kind": "hang"}
        if "error" in box:
            return {"error": box["error"], "kind": classify(box["error"])}
        return {"stats": box.get("stats") or []}

    # --- recovery -----------------------------------------------------------
    @staticmethod
    def _is_device_loss(exc: BaseException) -> bool:
        if isinstance(exc, DispatchTimeout):
            return True
        msg = str(exc)
        return any(m in msg for m in ("UNAVAILABLE", "device lost",
                                      "DATA_LOSS", "INTERNAL"))

    def _teardown(self, est, err: BaseException):
        """Flush + shut down the failed estimator; optionally drop the
        cached JAX backend so re-init re-probes the driver."""
        import os
        try:
            est.flush_checkpoints(timeout=30)
        except Exception:           # noqa: BLE001 — flush is best-effort here
            logger.exception("supervisor: checkpoint flush failed during "
                             "teardown")
        try:
            est.shutdown()
        except Exception:           # noqa: BLE001
            logger.exception("supervisor: estimator shutdown failed")
        if self._is_device_loss(err) and \
                os.environ.get("ZOO_SUPERVISOR_REINIT_BACKEND") == "1":
            # full backend re-init: only under classified device loss and
            # explicit opt-in — clear_backends invalidates every live
            # device array in the process, which is exactly right for a
            # lost chip and exactly wrong for a shared test mesh
            try:
                import jax
                jax.clear_backends()
                logger.warning("supervisor: cleared cached JAX backends "
                               "for re-init")
            except Exception:       # noqa: BLE001 — best-effort
                logger.exception("supervisor: backend re-init failed")

    # --- public -------------------------------------------------------------
    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            **fit_kwargs) -> Dict[str, Any]:
        """Supervised training run. Returns a report::

            {"epoch_stats": [...], "completed": bool, "preempted": bool,
             "restarts": n, "hangs": n, "crashes": n,
             "downtime_s": s, "steps_replayed": n, "failures": [...]}

        Raises :class:`SupervisorGiveUp` (report attached) when the
        restart budget is exhausted."""
        from ..orca.learn.preemption import PreemptionWatcher

        est = self._factory()
        model_dir = self.model_dir or est.model_dir
        if model_dir is None:
            raise ValueError("TrainingSupervisor needs a model_dir "
                             "(supervisor arg or estimator model_dir)")
        self.model_dir = model_dir
        wd = DispatchWatchdog(timeout_s=self.dispatch_timeout_s)
        prev_wd = wd_mod.active()
        wd_mod.set_active(wd)
        report: Dict[str, Any] = {
            "epoch_stats": [], "completed": False, "preempted": False,
            "restarts": 0, "hangs": 0, "crashes": 0, "downtime_s": 0.0,
            "steps_replayed": 0, "failures": []}
        self.report = report
        preempted = threading.Event()
        watcher = PreemptionWatcher(
            on_signal=lambda signum: preempted.set())
        self.estimator = est
        try:
            with watcher, _trace.span("supervisor.fit", epochs=epochs):
                epoch = self._resume(est)
                while epoch < epochs:
                    wd.reset()
                    outcome = self._run_segment(est, data, epoch, batch_size,
                                                fit_kwargs, wd)
                    if "error" not in outcome:
                        report["epoch_stats"].extend(outcome["stats"])
                        est.save_checkpoint(
                            model_dir,
                            meta={"supervisor_epoch": epoch + 1})
                        epoch += 1
                        if (preempted.is_set() or watcher.triggered) and \
                                epoch < epochs:
                            # SIGTERM grace window: make the boundary
                            # checkpoint durable and return cleanly — the
                            # next supervised run resumes at this epoch
                            est.flush_checkpoints()
                            report["preempted"] = True
                            logger.warning(
                                "supervisor: preemption notice — stopping "
                                "after epoch %d (checkpoint committed)",
                                epoch)
                            break
                        continue
                    err, kind = outcome["error"], outcome["kind"]
                    failed_step = getattr(
                        getattr(est, "engine", None), "step", 0)
                    # restart span annotated with the classified fault
                    # kind (hang|crash) + cause: teardown → rebuild →
                    # backoff → restore, all one segment on the timeline
                    with _trace.span("supervisor.restart", kind=kind,
                                     step=int(failed_step),
                                     cause=type(err).__name__):
                        self._teardown(est, err)
                        est = self._factory()
                        epoch = self._recover(est, err, kind, failed_step,
                                              report)
                self.estimator = est
                report["completed"] = not report["preempted"] and \
                    epoch >= epochs
                if report["completed"] or report["preempted"]:
                    est.flush_checkpoints()
                return report
        finally:
            if prev_wd is not None:
                wd_mod.set_active(prev_wd)
            else:
                wd_mod.clear_active()
            wd.close()

    def _recover(self, est, err: BaseException, kind: str,
                 failed_step: int, report: Dict[str, Any]) -> int:
        """Bookkeep one failure, enforce the restart budget, back off, and
        restore the fresh estimator to the last supervised epoch boundary.
        Returns the epoch to resume at."""
        t0 = time.perf_counter()
        report["restarts"] += 1
        plural = "hangs" if kind == "hang" else "crashes"
        report[plural] = report.get(plural, 0) + 1
        STATS.add("supervisor.restarts")
        STATS.add(f"supervisor.{plural}")
        report["failures"].append(
            {"kind": kind, "error": f"{type(err).__name__}: {err}",
             "step": int(failed_step), "time": time.time()})
        if report["restarts"] > self.max_restarts:
            report["downtime_s"] += time.perf_counter() - t0
            step, ep = self._latest_supervised(self.model_dir)
            report["last_checkpoint"] = {"step": step, "epoch": ep}
            logger.error(
                "supervisor: restart budget (%d) exhausted; escalating. "
                "failures: %s", self.max_restarts,
                [f["error"] for f in report["failures"]])
            raise SupervisorGiveUp(report) from err
        delay = self.retry_policy.delay_for(report["restarts"])
        logger.warning(
            "supervisor: %s at step %s (%s: %s); restart %d/%d in %.1fs",
            kind, failed_step, type(err).__name__, err,
            report["restarts"], self.max_restarts, delay)
        time.sleep(delay)
        epoch = self._resume(est)
        restored_step = getattr(est.engine, "step", 0)
        report["steps_replayed"] += max(
            0, int(failed_step) - int(restored_step))
        report["downtime_s"] += time.perf_counter() - t0
        return epoch
