"""Dispatch watchdog — bound the waits a wedged device turns infinite.

A hung collective or lost chip does not raise: ``block_until_ready`` /
the H2D ``device_put`` simply never return, and at pod scale one wedged
worker stalls the whole job silently (the failure mode Horovod's timeline
and MLPerf pod runs both call out). The watchdog turns "never returns"
into a *classified, bounded* event:

* hot paths wrap their device waits in :meth:`DispatchWatchdog.enter` /
  :meth:`~DispatchWatchdog.exit` sections (the engine's train dispatch,
  the transfer plane's placement — armed only when a watchdog is active,
  one global read otherwise);
* a monitor thread trips any section older than ``timeout_s``
  (``ZOO_DISPATCH_TIMEOUT_S``), records it, and notifies ``on_trip`` —
  the supervisor's signal to abandon the stuck thread and recover from
  the last committed checkpoint;
* :meth:`DispatchWatchdog.run` runs a callable on a worker thread with a
  deadline, raising :class:`DispatchTimeout` on expiry — hang vs crash is
  the exception class (``DispatchTimeout`` = hang, anything else = crash,
  see :func:`classify`).

The monitor cannot interrupt a thread stuck inside a C extension — no
Python mechanism can. What it *can* do is make the hang observable and
bounded so the layer above replaces the whole backend instead of waiting
forever; that is exactly how the training supervisor uses it.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .stats import STATS

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["DispatchTimeout", "DispatchWatchdog", "classify",
           "default_timeout_s", "set_active", "active", "clear_active",
           "watched"]

TIMEOUT_ENV = "ZOO_DISPATCH_TIMEOUT_S"


def default_timeout_s() -> Optional[float]:
    """``ZOO_DISPATCH_TIMEOUT_S`` (seconds), or None = unbounded."""
    env = os.environ.get(TIMEOUT_ENV, "").strip()
    return float(env) if env else None


class DispatchTimeout(RuntimeError):
    """A watched dispatch exceeded its bound — the *hang* classification
    (a crash keeps its original exception class)."""

    def __init__(self, label: str, elapsed_s: float, timeout_s: float):
        super().__init__(
            f"dispatch {label!r} exceeded {timeout_s:.1f}s "
            f"(waited {elapsed_s:.1f}s) — device hang suspected")
        self.label = label
        self.elapsed_s = elapsed_s
        self.timeout_s = timeout_s


def classify(exc: BaseException) -> str:
    """``hang`` (watchdog bound exceeded) vs ``crash`` (the step raised)."""
    return "hang" if isinstance(exc, DispatchTimeout) else "crash"


class DispatchWatchdog:
    """Monitor thread bounding named wait sections.

    ``timeout_s=None`` (and no ``ZOO_DISPATCH_TIMEOUT_S``) disables the
    monitor entirely — sections become free bookkeeping no-ops."""

    def __init__(self, timeout_s: Optional[float] = None,
                 poll_s: float = 0.05,
                 on_trip: Optional[Callable[[str, float], None]] = None):
        self.timeout_s = (default_timeout_s() if timeout_s is None
                          else float(timeout_s))
        self.poll_s = float(poll_s)
        self.on_trip = on_trip
        self.tripped = threading.Event()
        self.trips: List[Tuple[str, float]] = []
        self._lock = threading.Lock()
        self._sections: Dict[int, Tuple[str, float, bool]] = {}
        self._ids = itertools.count()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # --- sections (hot-path API: two dict ops, no context manager) ----------
    def enter(self, label: str) -> Optional[int]:
        if self.timeout_s is None:
            return None
        token = next(self._ids)
        with self._lock:
            self._sections[token] = (label, time.monotonic(), False)
        self._ensure_monitor()
        return token

    def exit(self, token: Optional[int]):
        if token is None:
            return
        with self._lock:
            self._sections.pop(token, None)

    def _ensure_monitor(self):
        if self._monitor is None or not self._monitor.is_alive():
            with self._lock:
                if self._monitor is None or not self._monitor.is_alive():
                    self._monitor = threading.Thread(
                        target=self._watch, name="zoo-dispatch-watchdog",
                        daemon=True)
                    self._monitor.start()

    def _watch(self):
        while not self._stop.wait(self.poll_s):
            now = time.monotonic()
            fired: List[Tuple[str, float]] = []
            with self._lock:
                for token, (label, t0, tripped) in self._sections.items():
                    if tripped or now - t0 <= self.timeout_s:
                        continue
                    self._sections[token] = (label, t0, True)
                    fired.append((label, now - t0))
            for label, elapsed in fired:
                self._record_trip(label, elapsed)

    def _record_trip(self, label: str, elapsed: float):
        with self._lock:
            self.trips.append((label, elapsed))
        self.tripped.set()
        STATS.add("watchdog.trips")
        STATS.add(f"watchdog.trip.{label}")
        logger.error("watchdog: dispatch %r has been blocked %.1fs "
                     "(timeout %.1fs) — hang suspected", label, elapsed,
                     self.timeout_s)
        if self.on_trip is not None:
            try:
                self.on_trip(label, elapsed)
            except Exception:           # noqa: BLE001 — observer bug must
                logger.exception("watchdog on_trip callback failed")

    # --- bounded call (waits the caller owns end-to-end) --------------------
    def run(self, fn: Callable, *args, label: str = "call",
            timeout_s: Optional[float] = None, **kwargs):
        """Run ``fn`` on a worker thread, bounded by ``timeout_s`` (default
        the watchdog's own). On expiry the worker is abandoned (daemon) and
        :class:`DispatchTimeout` raises — classification *hang*; an
        exception from ``fn`` re-raises unchanged — classification
        *crash*."""
        bound = self.timeout_s if timeout_s is None else float(timeout_s)
        if bound is None:
            return fn(*args, **kwargs)
        result: list = []
        error: list = []

        def target():
            try:
                result.append(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                error.append(e)

        t0 = time.monotonic()
        t = threading.Thread(target=target, daemon=True,
                             name=f"zoo-watchdog-{label}")
        t.start()
        t.join(bound)
        if t.is_alive():
            elapsed = time.monotonic() - t0
            self._record_trip(label, elapsed)
            raise DispatchTimeout(label, elapsed, bound)
        if error:
            raise error[0]
        return result[0]

    def reset(self):
        """Clear the trip latch between recovery attempts."""
        self.tripped.clear()

    def close(self):
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2)

    def snapshot(self) -> dict:
        with self._lock:
            return {"timeout_s": self.timeout_s, "trips": len(self.trips),
                    "by_label": {lbl: sum(1 for l, _ in self.trips
                                          if l == lbl)
                                 for lbl, _ in self.trips},
                    "open_sections": len(self._sections)}


def watched(label: str, fn: Callable, *args, **kwargs):
    """Run ``fn`` inside a section of the active watchdog (plain call when
    none is armed). For the host-side waits where a wedged device actually
    blocks — ``device_get`` / ``block_until_ready`` — since on real TPUs
    the *dispatch* returns asynchronously and the hang surfaces at the
    sync point."""
    wd = _active
    if wd is None:
        return fn(*args, **kwargs)
    token = wd.enter(label)
    try:
        return fn(*args, **kwargs)
    finally:
        wd.exit(token)


# --- process-wide active watchdog (the hot paths' one global read) ----------

_active: Optional[DispatchWatchdog] = None


def set_active(wd: DispatchWatchdog) -> DispatchWatchdog:
    global _active
    _active = wd
    return wd


def active() -> Optional[DispatchWatchdog]:
    return _active


def clear_active():
    global _active
    _active = None
