from .client import InputQueue, OutputQueue
from .codecs import SparseTensor
from .engine import ClusterServing, Timer
from .fleet import Autoscaler, ServingFleet, SleepModel, sleep_model_factory
from .queue_api import (FileBroker, InMemoryBroker, PartitionedBroker,
                        RedisBroker, make_broker, partitioned_spec)
from .redis_protocol import MiniRedisServer, RedisClient
from .scheduler import ContinuousScheduler, ModelMultiplexer

__all__ = ["InputQueue", "OutputQueue", "ClusterServing", "Timer",
           "InMemoryBroker", "FileBroker", "RedisBroker", "MiniRedisServer",
           "RedisClient", "make_broker", "partitioned_spec",
           "PartitionedBroker", "SparseTensor",
           "ContinuousScheduler", "ModelMultiplexer",
           "ServingFleet", "Autoscaler", "SleepModel",
           "sleep_model_factory"]
