from .client import InputQueue, OutputQueue
from .engine import ClusterServing, Timer
from .queue_api import FileBroker, InMemoryBroker, make_broker

__all__ = ["InputQueue", "OutputQueue", "ClusterServing", "Timer",
           "InMemoryBroker", "FileBroker", "make_broker"]
