"""Serving client — InputQueue / OutputQueue, same surface as the reference
(pyzoo/zoo/serving/client.py:82 InputQueue.enqueue/predict, :234
OutputQueue.dequeue/query). Passing ``host``/``port`` selects the Redis
transport exactly like the reference client's ``InputQueue(host, port)``;
otherwise ``queue`` picks a broker (memory:// file:// redis://)."""

from __future__ import annotations

import uuid
from typing import Any, Dict, Optional

import numpy as np

from .codecs import decode_payload, encode_payload
from .queue_api import Broker, make_broker


class API:
    def __init__(self, queue: str = "memory://serving_stream",
                 host: Optional[str] = None, port=None,
                 name: str = "serving_stream"):
        self.name = name
        if host is not None:
            # reference signature: API(host, port) → Redis transport
            queue = f"redis://{host}:{int(port or 6379)}/{name}"
        self.broker: Broker = make_broker(queue) if isinstance(queue, str) \
            else queue


class InputQueue(API):
    def __init__(self, queue: str = "memory://serving_stream",
                 host: Optional[str] = None, port=None,
                 name: str = "serving_stream",
                 max_pending: Optional[int] = None,
                 backpressure_poll_s: float = 0.002):
        """``max_pending`` caps the broker backlog: enqueue blocks while
        ``pending() >= max_pending``, so a burst of producers cannot grow the
        queue (and the tail latency of everything behind it) without bound.
        The reference relies on Flink backpressure for the same effect."""
        super().__init__(queue, host, port, name)
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.max_pending = max_pending
        self._poll_s = backpressure_poll_s
        # pending() costs a round trip on the Redis transport; only re-query
        # once the locally-sent count could plausibly have reached the cap
        self._last_pending = 0
        self._sent_since = 0

    def enqueue(self, uri: str, model_name: Optional[str] = None,
                deadline: Optional[float] = None, **data) -> str:
        """enqueue(uri, t=ndarray) or multiple named tensors
        (reference: client.py:144-233). ``model_name`` routes to one of a
        multiplexed engine's co-served models (default: the engine's
        default model); ``deadline`` is an absolute epoch-seconds stamp the
        engine sheds against."""
        if not data:
            raise ValueError("provide at least one named tensor, e.g. "
                             "input_api.enqueue('my-id', t=arr)")
        if self.max_pending is not None:
            import time as _time
            while self._last_pending + self._sent_since >= self.max_pending:
                self._last_pending = self.broker.pending()
                self._sent_since = 0
                if self._last_pending >= self.max_pending:
                    _time.sleep(self._poll_s)
            self._sent_since += 1
        from .codecs import SparseTensor

        def norm(v):
            return v if isinstance(v, SparseTensor) else np.asarray(v)

        meta: Dict[str, Any] = {"uri": uri}
        if model_name is not None:
            meta["model"] = model_name
        if deadline is not None:
            meta["deadline"] = float(deadline)
        if len(data) == 1:
            payload = encode_payload(norm(next(iter(data.values()))),
                                     meta=meta)
        else:
            payload = encode_payload({k: norm(v) for k, v in data.items()},
                                     meta=meta)
        self.broker.enqueue(uri, payload)
        return uri

    def predict(self, request_data, timeout_s: float = 30.0,
                model_name: Optional[str] = None):
        """Synchronous single prediction (reference: client.py:105-143)."""
        uri = uuid.uuid4().hex
        meta: Dict[str, Any] = {"uri": uri}
        if model_name is not None:
            meta["model"] = model_name
        self.broker.enqueue(uri, encode_payload(np.asarray(request_data),
                                                meta=meta))
        raw = self.broker.get_result(uri, timeout_s)
        if raw is None:
            raise TimeoutError(f"no result for {uri} within {timeout_s}s")
        data, meta = decode_payload(raw)
        if meta.get("error"):
            raise RuntimeError(f"serving error: {meta['error']}")
        return data


class OutputQueue(API):
    def query(self, uri: str, timeout_s: float = 10.0):
        """(reference: client.py:238-252)"""
        raw = self.broker.get_result(uri, timeout_s)
        if raw is None:
            return "{}"
        data, _ = decode_payload(raw)
        return data

    def dequeue(self, uris, timeout_s: float = 10.0) -> Dict[str, Any]:
        """Fetch many results (reference: client.py:253-265)."""
        out = {}
        for uri in uris:
            out[uri] = self.query(uri, timeout_s)
        return out
