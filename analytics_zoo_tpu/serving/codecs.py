"""Wire codecs for serving payloads — ndarray <-> base64(arrow), matching the
reference client's encoding (pyzoo/zoo/serving/client.py:267-282 b64 + arrow
streaming format; JVM twin serving/arrow/ArrowSerializer.scala:170). Sparse
tensors ride the same wire as {shape, data, indices} triples, the reference
ingress schema (serving/http/domains.scala:100 ``SparseTensor[T](shape,
data, indices)``) — recommendation traffic routinely sends sparse features.
"""

from __future__ import annotations

import base64
import io
import json
from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import numpy as np


@dataclass
class SparseTensor:
    """COO sparse tensor (reference: http/domains.scala:100).

    ``indices`` is (nnz, ndim) int; ``data`` is (nnz,) values. The TPU
    compute path is dense (XLA static shapes), so serving densifies at
    batch-assembly time via ``to_dense`` — for the reference's
    recommendation models these are small per-record feature vectors, and
    the dense batch then rides the normal bucketed executable."""
    shape: Tuple[int, ...]
    data: np.ndarray
    indices: np.ndarray

    def __post_init__(self):
        self.shape = tuple(int(s) for s in self.shape)
        self.data = np.asarray(self.data)
        self.indices = np.asarray(self.indices, dtype=np.int64)
        if self.indices.size == 0:     # all-zero tensor: [] at any rank
            self.indices = self.indices.reshape(0, len(self.shape))
        if self.indices.ndim == 1:     # 1-D tensor: allow flat index lists
            self.indices = self.indices[:, None]
        if self.indices.shape != (len(self.data), len(self.shape)):
            raise ValueError(
                f"indices shape {self.indices.shape} does not match "
                f"{len(self.data)} values over a rank-{len(self.shape)} "
                "tensor")
        # reject out-of-range at ingress: negative indices would silently
        # wrap in to_dense, and overflow would explode at batch time —
        # inside a co-batched group, failing OTHER clients' requests
        if len(self.data):
            upper = np.asarray(self.shape, dtype=np.int64)
            if (self.indices < 0).any() or (self.indices >= upper).any():
                raise ValueError(
                    f"indices out of range for shape {self.shape}")

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.shape, dtype=self.data.dtype)
        if len(self.data):
            # np.add.at: duplicate coordinates SUM (un-coalesced COO
            # convention) instead of silently keeping the last value
            np.add.at(out, tuple(self.indices.T), self.data)
        return out


def densify(data):
    """Replace any SparseTensor in a decoded payload with its dense form."""
    if isinstance(data, SparseTensor):
        return data.to_dense()
    if isinstance(data, list):
        return [densify(d) for d in data]
    if isinstance(data, dict):
        return {k: densify(v) for k, v in data.items()}
    return data


def encode_ndarray(arr: np.ndarray) -> str:
    import pyarrow as pa
    arr = np.ascontiguousarray(arr)
    tensor = pa.Tensor.from_numpy(arr)
    sink = pa.BufferOutputStream()
    pa.ipc.write_tensor(tensor, sink)
    return base64.b64encode(sink.getvalue().to_pybytes()).decode("ascii")


def decode_ndarray(s: str) -> np.ndarray:
    import pyarrow as pa
    buf = base64.b64decode(s)
    tensor = pa.ipc.read_tensor(pa.BufferReader(buf))
    return tensor.to_numpy()


def _encode_one(data) -> Dict:
    if isinstance(data, SparseTensor):
        return {"kind": "sparse", "shape": list(data.shape),
                "data": encode_ndarray(data.data),
                "indices": encode_ndarray(data.indices)}
    return {"kind": "tensor", "data": encode_ndarray(np.asarray(data))}


def _decode_one(body):
    if isinstance(body, str):              # bare tensor (legacy form)
        return decode_ndarray(body)
    if body["kind"] == "sparse":
        return SparseTensor(shape=tuple(body["shape"]),
                            data=decode_ndarray(body["data"]),
                            indices=decode_ndarray(body["indices"]))
    return decode_ndarray(body["data"])


def encode_payload(data: Any, meta: Dict | None = None) -> bytes:
    """data: ndarray | SparseTensor | list/tuple | dict[str, ...] of them."""
    if isinstance(data, np.ndarray):
        body = {"kind": "tensor", "data": encode_ndarray(data)}
    elif isinstance(data, SparseTensor):
        body = _encode_one(data)
    elif isinstance(data, (list, tuple)):
        body = {"kind": "tensors", "data": [_encode_one(a) for a in data]}
    elif isinstance(data, dict):
        body = {"kind": "named",
                "data": {k: _encode_one(v) for k, v in data.items()}}
    else:
        raise ValueError(f"cannot encode {type(data)}")
    if meta:
        body["meta"] = meta
    return json.dumps(body).encode("utf-8")


def decode_payload(raw: bytes) -> Tuple[Any, Dict]:
    body = json.loads(raw.decode("utf-8") if isinstance(
        raw, (bytes, bytearray)) else bytes(raw).decode("utf-8"))
    kind = body["kind"]
    if kind in ("tensor", "sparse"):
        data = _decode_one(body)
    elif kind == "tensors":
        data = [_decode_one(s) for s in body["data"]]
    else:
        data = {k: _decode_one(v) for k, v in body["data"].items()}
    return data, body.get("meta", {})


# --- shm descriptor wire ----------------------------------------------------
# The JSON + base64(arrow) wire above costs ~2.7 copies of every tensor on
# each side (contiguous copy, arrow buffer, b64 text). On a shm-enabled
# stream the producer instead writes RAW tensor bytes into arena slabs once
# and ships descriptors (dtype/shape ride the ObjectRef); the consumer maps
# them read-only — zero payload copies on decode. Sparse tensors and any
# arena failure fall back to an inline frame wrapping the exact legacy
# encoding, so mixed traffic drains through one decode entry point.

def encode_payload_ref(data: Any, meta: Dict | None = None, *,
                       arena) -> Tuple[bytes, List]:
    """Encode for a shm-enabled stream: ``(wire_bytes, refs)``.

    Dense payloads (ndarray | list/tuple | dict[str, ndarray]) go to
    slabs — one descriptor per tensor, layout + user meta in the envelope
    header. The producer pin is released before returning (the frame is
    self-contained); consumers owe ``arena.done(ref)`` per ref after the
    result is published. Sparse payloads and arena overflow return an
    inline frame of :func:`encode_payload` with ``refs == []``; with no
    arena at all this IS :func:`encode_payload`."""
    from ..shm import ArenaFull, min_shm_bytes, wrap_inline, wrap_ref
    if arena is None:
        return encode_payload(data, meta), []
    names: List[str] | None = None
    if isinstance(data, np.ndarray):
        kind, arrays = "tensor", [data]
    elif isinstance(data, (list, tuple)) and data and all(
            not isinstance(a, SparseTensor) for a in data):
        kind, arrays = "tensors", [np.asarray(a) for a in data]
    elif isinstance(data, dict) and data and all(
            not isinstance(v, SparseTensor) for v in data.values()):
        kind = "named"
        names = [str(k) for k in data.keys()]
        arrays = [np.asarray(data[k]) for k in data.keys()]
    else:
        return wrap_inline(encode_payload(data, meta)), []
    if sum(int(np.asarray(a).nbytes) for a in arrays) < min_shm_bytes():
        # under the size floor the descriptor overhead (slab burn, index
        # lock, lease writes) costs more than the copy it saves — stay on
        # the legacy wire, byte for byte
        return encode_payload(data, meta), []
    refs = []
    try:
        for a in arrays:
            a = np.ascontiguousarray(a)
            refs.append(arena.put(a, dtype=a.dtype.str, shape=a.shape))
    except (ArenaFull, OSError, ValueError):
        for r in refs:          # free the partial put — inline carries all
            arena.done(r)
        return wrap_inline(encode_payload(data, meta)), []
    env_meta: Dict = {}
    if names is not None:
        env_meta["names"] = names
    if meta:
        env_meta["meta"] = meta
    frame = wrap_ref(refs, meta=env_meta or None, kind=kind)
    for r in refs:              # handoff complete: drop the producer pins
        arena.release(r)
    return frame, refs


def decode_ref(raw, *, arena=None) -> Tuple[Any, Dict, List]:
    """Decode a serving payload that may be a shm envelope: returns
    ``(data, meta, refs)``. Descriptor frames map each tensor's slab
    read-only (zero copy, C-contiguous, pinned in this process's lease)
    and the caller owes ``arena.done(ref)`` per ref strictly AFTER the
    answer for the item is published — a PEL reclaim must be able to
    re-resolve the same generation. Inline frames and legacy payloads
    decode exactly as :func:`decode_payload` with ``refs == []``."""
    from ..shm import ObjectRef, is_envelope, unwrap
    if not is_envelope(raw):
        return (*decode_payload(raw), [])
    flag, header, payload = unwrap(raw)
    if flag == "I":
        return (*decode_payload(payload), [])
    if arena is None:
        raise ValueError("descriptor frame on a stream with no shm arena "
                         "(consumer has ZOO_SHM off or shm unavailable)")
    refs = [ObjectRef.from_dict(d) for d in header.get("refs", [])]
    arrays = []
    try:
        for r in refs:
            arrays.append(arena.checkout(r))
    except Exception:
        for r, _ in zip(refs, arrays):   # unwind partial pins
            arena.release(r)
        raise
    env_meta = header.get("meta") or {}
    kind = header.get("kind", "tensors")
    if kind == "tensor":
        data: Any = arrays[0]
    elif kind == "named":
        data = dict(zip(env_meta.get("names", []), arrays))
    else:
        data = list(arrays)
    return data, env_meta.get("meta", {}), refs
