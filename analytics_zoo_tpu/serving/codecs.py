"""Wire codecs for serving payloads — ndarray <-> base64(arrow), matching the
reference client's encoding (pyzoo/zoo/serving/client.py:267-282 b64 + arrow
streaming format; JVM twin serving/arrow/ArrowSerializer.scala:170)."""

from __future__ import annotations

import base64
import io
import json
from typing import Any, Dict, List, Tuple

import numpy as np


def encode_ndarray(arr: np.ndarray) -> str:
    import pyarrow as pa
    arr = np.ascontiguousarray(arr)
    tensor = pa.Tensor.from_numpy(arr)
    sink = pa.BufferOutputStream()
    pa.ipc.write_tensor(tensor, sink)
    return base64.b64encode(sink.getvalue().to_pybytes()).decode("ascii")


def decode_ndarray(s: str) -> np.ndarray:
    import pyarrow as pa
    buf = base64.b64decode(s)
    tensor = pa.ipc.read_tensor(pa.BufferReader(buf))
    return tensor.to_numpy()


def encode_payload(data: Any, meta: Dict | None = None) -> bytes:
    """data: ndarray | list/tuple of ndarray | dict[str, ndarray]."""
    if isinstance(data, np.ndarray):
        body = {"kind": "tensor", "data": encode_ndarray(data)}
    elif isinstance(data, (list, tuple)):
        body = {"kind": "tensors",
                "data": [encode_ndarray(np.asarray(a)) for a in data]}
    elif isinstance(data, dict):
        body = {"kind": "named",
                "data": {k: encode_ndarray(np.asarray(v))
                         for k, v in data.items()}}
    else:
        raise ValueError(f"cannot encode {type(data)}")
    if meta:
        body["meta"] = meta
    return json.dumps(body).encode("utf-8")


def decode_payload(raw: bytes) -> Tuple[Any, Dict]:
    body = json.loads(raw.decode("utf-8"))
    kind = body["kind"]
    if kind == "tensor":
        data = decode_ndarray(body["data"])
    elif kind == "tensors":
        data = [decode_ndarray(s) for s in body["data"]]
    else:
        data = {k: decode_ndarray(v) for k, v in body["data"].items()}
    return data, body.get("meta", {})
