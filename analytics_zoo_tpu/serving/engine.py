"""Cluster Serving engine — queue -> dynamic batcher -> TPU inference -> results.

The reference pipeline (SURVEY.md §3.5) is Redis stream -> Flink
FlinkRedisSource (xreadGroup, engine/FlinkRedisSource.scala:78-104) ->
FlinkInference -> ClusterServingInference batching
(engine/ClusterServingInference.scala:36-152) -> InferenceModel.doPredict ->
FlinkRedisSink. The TPU-native pipeline drops Flink entirely: a worker thread
claims up to ``batch_size`` requests (waiting at most ``batch_timeout_ms`` —
dynamic batching), stacks them, runs the shape-bucketed compiled executable,
and writes per-request results back. Per-stage latency is tracked like the
reference's Timer (serving/engine/Timer.scala:102).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from ..pipeline.inference.inference_model import InferenceModel
from .codecs import decode_payload, densify, encode_payload
from .queue_api import Broker, make_broker

logger = logging.getLogger("analytics_zoo_tpu")


class Timer:
    """(reference: serving/engine/Timer.scala) — n-record latency stats."""

    def __init__(self):
        self.stats: Dict[str, List[float]] = defaultdict(list)

    def time(self, name: str):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()

            def __exit__(self, *a):
                timer.stats[name].append(time.perf_counter() - self.t0)

        return _Ctx()

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for name, vals in self.stats.items():
            arr = np.asarray(vals)
            out[name] = {"count": len(arr), "mean_ms": float(arr.mean() * 1e3),
                         "p50_ms": float(np.percentile(arr, 50) * 1e3),
                         "p95_ms": float(np.percentile(arr, 95) * 1e3),
                         "p99_ms": float(np.percentile(arr, 99) * 1e3)}
        return out

    def reset(self):
        """Drop accumulated samples (e.g. after warmup, so reported
        percentiles are steady-state rather than compile-tainted)."""
        self.stats = defaultdict(list)


class ClusterServing:
    """(reference entry: serving/ClusterServing.scala:69; config via
    utils/ClusterServingHelper.scala)"""

    def __init__(self, model: InferenceModel,
                 queue: str = "memory://serving_stream",
                 batch_size: int = 32, batch_timeout_ms: float = 5.0,
                 model_parallelism: int = 1):
        self.model = model
        self.broker: Broker = make_broker(queue) if isinstance(queue, str) \
            else queue
        self.batch_size = batch_size
        self.batch_timeout = batch_timeout_ms / 1e3
        # modelParallelism in the reference = number of model copies
        # (ClusterServing.scala:60); XLA executables are reentrant so this is
        # the number of batcher threads.
        self.num_workers = model_parallelism
        self.timer = Timer()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.records_out = 0

    # --- worker loop --------------------------------------------------------
    def _worker(self):
        while not self._stop.is_set():
            with self.timer.time("claim"):
                batch = self.broker.claim_batch(self.batch_size,
                                                self.batch_timeout)
            if not batch:
                continue
            try:
                self._process(batch)
            except Exception as e:  # noqa: BLE001 — serving must not die
                logger.exception("serving batch failed: %s", e)
                for item_id, _ in batch:
                    self.broker.put_result(item_id, encode_payload(
                        np.zeros(0), meta={"error": str(e)}))

    def _process(self, batch):
        with self.timer.time("decode"):
            decoded = [decode_payload(p) for _, p in batch]
            # sparse ingress (reference: http/domains.scala:100) densifies
            # at batch assembly — the TPU executable wants static dense
            arrays = [densify(d) for d, _ in decoded]
        with self.timer.time("batch"):
            first = arrays[0]
            if isinstance(first, list):
                stacked = [np.stack([a[i] for a in arrays])
                           for i in range(len(first))]
            elif isinstance(first, dict):
                # named multi-tensor records: stack per key (values
                # fetched BY NAME per record) and feed the model
                # positionally in the record's own key order — the
                # reference's LinkedHashMap insertion-order semantics
                # (http/domains.scala:102), i.e. clients declare tensors
                # in the model's input order. Records that disagree on
                # that order cannot be bound unambiguously: fail the
                # batch with a clear message instead of silently feeding
                # someone's tensors into the wrong inputs.
                order = tuple(first.keys())
                for a in arrays:
                    if tuple(a.keys()) != order:
                        raise ValueError(
                            f"named-tensor records disagree on key order "
                            f"({order} vs {tuple(a.keys())}); all clients "
                            "of one stream must enqueue tensors in the "
                            "model's input order")
                stacked = [np.stack([a[k] for a in arrays]) for k in order]
            else:
                stacked = np.stack(arrays)
        with self.timer.time("inference"):
            preds = self.model.predict(stacked)
        with self.timer.time("encode"):
            multi = isinstance(preds, (list, tuple))
            for i, (item_id, _) in enumerate(batch):
                if multi:
                    out = [np.asarray(p[i]) for p in preds]
                else:
                    out = np.asarray(preds[i])
                self.broker.put_result(item_id, encode_payload(out))
        self.records_out += len(batch)

    # --- lifecycle ----------------------------------------------------------
    def start(self, example=None):
        """Start worker threads. With ``example`` (a batch-shaped array, or
        list of arrays, matching real traffic's record shape/dtype), every
        shape bucket up to ``batch_size`` is compiled BEFORE serving begins —
        the XLA analogue of the reference pre-filling its model-copy queue
        (InferenceModel.scala:580-626). Without it, timeout-sized partial
        batches hit cold buckets and compiles land in the latency tail."""
        if example is not None:
            with self.timer.time("precompile"):
                # precompile rounds batch_size up to the bucket steady-state
                # full batches actually land in
                self.model.precompile(example, max_bucket=self.batch_size)
        for i in range(self.num_workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"serving-worker-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)

    def metrics(self) -> Dict:
        """(reference observability: Flink numRecordsOutPerSecond +
        Timer stats)"""
        out = {"records_out": self.records_out,
               # batch-dim sharding spreads every batch over these chips
               # (reference scales with model replicas / Flink parallelism);
               # 1 for eager/call_tf models, which compute host-side
               "devices": getattr(self.model, "device_count", 1),
               "stages": self.timer.summary()}
        if hasattr(self.model, "transfer_stats"):
            # transfer-plane counters: serving-ingress h2d seconds/bytes/
            # MB/s from the sharded device_put path (native/transfer.py)
            snap = self.model.transfer_stats()
            if snap and snap.get("h2d_n"):
                out["transfer"] = snap
        if hasattr(self.model, "compile_stats"):
            # compiles vs cache/disk hits — read next to the "precompile"
            # stage timer to see whether warmup paid real compilation or
            # reused executables (in-process or from the disk cache). Empty
            # when this model's plane is off: omit rather than clobber the
            # process-wide counters the HTTP /metrics handler surfaces.
            snap = self.model.compile_stats()
            if snap:
                out["compile"] = snap
        if hasattr(self.model, "ckpt_stats"):
            # checkpoint-plane hot-reload counters (weights swapped into
            # the live model; full_reloads > 0 means a structure change
            # forced bucket recompiles). Empty until the first reload.
            snap = self.model.ckpt_stats()
            if snap:
                out["ckpt"] = snap
        return out

    def reset_metrics(self):
        """Zero the stage timers and record counter — call after warmup so
        ``metrics()`` reports steady-state percentiles."""
        self.timer.reset()
        self.records_out = 0

    def update_model(self, model: InferenceModel):
        """Hot-swap the served model (the reference rolls a new model by
        restarting the Flink job, ClusterServingGuide 'model update'; here
        the swap is a reference assignment — in-flight batches finish on
        the old executables, the next claim uses the new ones)."""
        self.model = model
        return self
