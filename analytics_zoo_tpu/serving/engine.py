"""Cluster Serving engine — queue -> continuous batch former -> TPU -> results.

The reference pipeline (SURVEY.md §3.5) is Redis stream -> Flink
FlinkRedisSource (xreadGroup, engine/FlinkRedisSource.scala:78-104) ->
FlinkInference -> ClusterServingInference batching
(engine/ClusterServingInference.scala:36-152) -> InferenceModel.doPredict ->
FlinkRedisSink. The TPU-native pipeline drops Flink entirely, and since the
serving-scale arc also drops the reference's fixed claim loop: a **claim
pump** streams records off the broker, decodes and sheds them, and routes
them into per-(model, signature) admission queues; dispatch workers pull
EDF-formed batches from the :class:`~.scheduler.ContinuousScheduler` (bucket
full, or head slack at the dispatch-now threshold — no fixed
``batch_timeout_ms`` stall) and run whichever model the batch belongs to on
the shared chip set via the :class:`~.scheduler.ModelMultiplexer`. Per-stage
latency is tracked like the reference's Timer (serving/engine/Timer.scala:102).

``policy="fixed"`` keeps the original claim-up-to-batch_size discipline as a
baseline (bench_serving_scale A/Bs the two on the same model).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common import knobs
from ..obs import trace as _trace
from ..obs.registry import REGISTRY, InstancedEvents
from ..resilience import faults as _faults
from ..resilience.stats import STATS
from ..shm import arena_for_spec as _shm_arena_for_spec
from ..shm import peek_refs as _shm_peek_refs
from .codecs import decode_payload, decode_ref, densify, encode_payload
from .queue_api import Broker, make_broker
from .scheduler import ContinuousScheduler, ModelMultiplexer, ServingRequest

logger = logging.getLogger("analytics_zoo_tpu")


class Timer:
    """(reference: serving/engine/Timer.scala) — n-record latency stats."""

    def __init__(self):
        self._lock = threading.Lock()
        self.stats: Dict[str, List[float]] = defaultdict(list)

    def time(self, name: str):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()

            def __exit__(self, *a):
                dt = time.perf_counter() - self.t0
                with timer._lock:
                    timer.stats[name].append(dt)

        return _Ctx()

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        with self._lock:
            items = [(k, list(v)) for k, v in self.stats.items()]
        for name, vals in items:
            arr = np.asarray(vals)
            out[name] = {"count": len(arr), "mean_ms": float(arr.mean() * 1e3),
                         "p50_ms": float(np.percentile(arr, 50) * 1e3),
                         "p95_ms": float(np.percentile(arr, 95) * 1e3),
                         "p99_ms": float(np.percentile(arr, 99) * 1e3)}
        return out

    def reset(self):
        """Drop accumulated samples (e.g. after warmup, so reported
        percentiles are steady-state rather than compile-tainted)."""
        with self._lock:
            self.stats = defaultdict(list)


class ClusterServing:
    """(reference entry: serving/ClusterServing.scala:69; config via
    utils/ClusterServingHelper.scala)

    ``model`` may be a single model object (wrapped as the multiplexer's
    ``default``) or a :class:`~.scheduler.ModelMultiplexer` co-serving
    several models on one chip set. Scheduler knobs come from
    ``common/knobs.py`` (``ZOO_SERVING_BATCH_SIZE`` /
    ``ZOO_SERVING_BATCH_TIMEOUT_MS`` / ``ZOO_SERVING_MAX_INFLIGHT`` /
    ``ZOO_SERVING_SLACK_MS``) when the constructor arguments are left None.
    """

    def __init__(self, model,
                 queue: str = "memory://serving_stream",
                 batch_size: Optional[int] = None,
                 batch_timeout_ms: Optional[float] = None,
                 model_parallelism: int = 1,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 30.0,
                 policy: str = "continuous",
                 max_inflight: Optional[int] = None,
                 slack_ms: Optional[float] = None,
                 form_ms: float = 2.0,
                 worker_id: Optional[str] = None,
                 heartbeat_s: Optional[float] = None):
        if isinstance(model, ModelMultiplexer):
            self.mux = model
        else:
            self.mux = ModelMultiplexer(
                breaker_threshold=breaker_threshold,
                breaker_cooldown_s=breaker_cooldown_s).add_model(
                "default", model)
        if len(self.mux) == 0:
            raise ValueError("ModelMultiplexer has no models; add_model "
                             "before constructing ClusterServing")
        self.broker: Broker = make_broker(queue) if isinstance(queue, str) \
            else queue
        # shm object plane: on a local, ZOO_SHM-enabled stream request
        # payloads may arrive as descriptor frames — map them from the
        # spec-derived arena every sibling process agrees on (None keeps
        # today's inline wire, byte for byte)
        self._arena = _shm_arena_for_spec(
            queue if isinstance(queue, str)
            else getattr(self.broker, "spec", None))
        self.batch_size = int(knobs.get("ZOO_SERVING_BATCH_SIZE")
                              if batch_size is None else batch_size)
        self.batch_timeout = float(
            knobs.get("ZOO_SERVING_BATCH_TIMEOUT_MS")
            if batch_timeout_ms is None else batch_timeout_ms) / 1e3
        if policy not in ("continuous", "fixed"):
            raise ValueError(f"policy must be 'continuous' or 'fixed', "
                             f"got {policy!r}")
        self.policy = policy
        self.max_inflight = int(knobs.get("ZOO_SERVING_MAX_INFLIGHT")
                                if max_inflight is None else max_inflight)
        self.slack_s = float(knobs.get("ZOO_SERVING_SLACK_MS")
                             if slack_ms is None else slack_ms) / 1e3
        self.form_s = form_ms / 1e3
        # modelParallelism in the reference = number of model copies
        # (ClusterServing.scala:60); XLA executables are reentrant so this is
        # the number of dispatch threads sharing the chip set.
        self.num_workers = model_parallelism
        # fleet membership: with a worker_id, a heartbeat thread publishes
        # liveness + occupancy stats through the broker every heartbeat_s
        # (the autoscaler's signal, and /readyz's live-worker count)
        self.worker_id = worker_id
        self.heartbeat_s = float(knobs.get("ZOO_FLEET_HEARTBEAT_S")
                                 if heartbeat_s is None else heartbeat_s)
        self._hb_thread: Optional[threading.Thread] = None
        self.timer = Timer()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        self._threads: List[threading.Thread] = []
        self.records_out = 0
        # overload counters live in the unified metrics registry (obs
        # plane): one family labeled per engine instance, so metrics()'s
        # dict stays a per-engine view (starting at 0) while /metrics.prom
        # exposes the same series process-wide
        self._res_events = InstancedEvents(
            REGISTRY.counter(
                "zoo_serving_engine_events_total",
                "serving-engine overload events: expired/open-circuit "
                "sheds, batch failures, decode errors, unknown-model "
                "rejects",
                labelnames=("inst", "event")),
            ("shed_expired", "shed_open", "batch_failures",
             "decode_errors", "unknown_model"))
        self._res_children = self._res_events.children
        inst = self._res_events.inst
        # scheduler observability: admitted-inflight / per-model queue-depth
        # gauges pushed from the scheduler hooks, per-model batch/record
        # counters and busy-seconds bumped at dispatch — the serving face
        # of chip occupancy, scraped next to the span timeline
        self._g_inflight = REGISTRY.gauge(
            "zoo_serving_sched_inflight",
            "requests admitted into the continuous former (queued + "
            "mid-dispatch), bounded by ZOO_SERVING_MAX_INFLIGHT",
            labelnames=("inst",)).labels(inst=inst)
        self._depth_family = REGISTRY.gauge(
            "zoo_serving_sched_queue_depth",
            "admission-queue depth per co-served model",
            labelnames=("inst", "model"))
        self._batches_family = REGISTRY.counter(
            "zoo_serving_sched_batches_total",
            "batches dispatched per co-served model",
            labelnames=("inst", "model"))
        self._records_family = REGISTRY.counter(
            "zoo_serving_sched_records_total",
            "records served per co-served model",
            labelnames=("inst", "model"))
        self._c_busy = REGISTRY.counter(
            "zoo_serving_sched_busy_seconds_total",
            "wall seconds the dispatch workers spent in model execution "
            "(chip occupancy numerator)",
            labelnames=("inst",)).labels(inst=inst)
        self._inst = inst
        self._depth_children: Dict[str, object] = {}
        self._batch_children: Dict[str, object] = {}
        self._record_children: Dict[str, object] = {}
        self.sched = ContinuousScheduler(
            max_inflight=self.max_inflight, slack_s=self.slack_s,
            form_s=self.form_s,
            on_inflight=self._g_inflight.set,
            on_depth=self._set_depth)

    # --- per-model obs children --------------------------------------------
    def _model_child(self, family, cache: Dict, model: str):
        child = cache.get(model)
        if child is None:
            child = family.labels(inst=self._inst, model=model)
            cache[model] = child
        return child

    def _set_depth(self, model: str, depth: int):
        self._model_child(self._depth_family, self._depth_children,
                          model).set(depth)

    def _count_batch(self, model: str, n_records: int):
        self._model_child(self._batches_family, self._batch_children,
                          model).inc()
        self._model_child(self._records_family, self._record_children,
                          model).inc(n_records)

    def _count(self, key: str, n: int = 1):
        self._res_children[key].inc(n)

    def _close_series(self):
        """Drop this instance's registry series from the exposition —
        rebuilt engines must not leak dead-uuid series into every scrape.
        Cached children keep serving metrics()'s view."""
        self._res_events.close()
        for fam, children in (
                (self._depth_family, self._depth_children),
                (self._batches_family, self._batch_children),
                (self._records_family, self._record_children)):
            for model in children:
                fam.remove(inst=self._inst, model=model)
        REGISTRY.gauge("zoo_serving_sched_inflight",
                       labelnames=("inst",)).remove(inst=self._inst)
        REGISTRY.counter("zoo_serving_sched_busy_seconds_total",
                         labelnames=("inst",)).remove(inst=self._inst)

    # --- single-model compatibility surface --------------------------------
    @property
    def model(self):
        """The default model (single-model constructor compatibility)."""
        return self.mux.default.model

    @property
    def breaker(self):
        """The default model's circuit breaker (readiness probes and the
        legacy metrics key read this one; per-model breakers are in
        ``metrics()["scheduler"]["per_model"]``)."""
        return self.mux.default.breaker

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    # --- claim pump (continuous policy) -------------------------------------
    def _pump(self):
        """Stream records off the broker into the admission queues. The
        claim timeout is only an idle poll — batch formation happens in the
        scheduler, so the chip never waits on this thread's timeout."""
        try:
            while not self._stop.is_set():
                with self.timer.time("claim"):
                    batch = self.broker.claim_batch(
                        max(1, self.max_inflight),
                        max(self.batch_timeout, 0.001))
                if batch:
                    self._route_claim(batch)
                elif self._draining.is_set():
                    if self._safe_pending() in (0, None):
                        return      # drained: broker empty, stop claiming
        finally:
            self.sched.finish_input()

    def _refs_done(self, refs):
        """Mark slab descriptors consumed — called strictly AFTER the
        item's answer was published (put_result is serving's ack): a PEL
        reclaim of an unanswered item must re-resolve the same
        generation."""
        if not refs or self._arena is None:
            return
        for r in refs:
            try:
                self._arena.done(r)
            except Exception as e:  # noqa: BLE001 — freeing must not
                # fail serving; a sweep/gc reclaims whatever this missed
                logger.warning("shm done failed for %s: %s", r, e)

    def _route_claim(self, batch):
        """Decode + shed + route one claimed batch. Every claimed item gets
        a result — error payloads for shed/failed ones — so frontend fetches
        never wait out their full timeout on a request the engine already
        gave up on."""
        prologue = self._decode_prologue(batch)
        if prologue is None:
            return
        reqs, _batch_tok = prologue
        admitted = self.sched.offer_many(reqs)
        for req in reqs[admitted:]:
            # closed mid-offer (stop during shutdown): answer rather
            # than orphan — at-least-once brokers would redeliver, the
            # in-memory one would hang the client to its timeout
            self.broker.put_result(req.item_id, encode_payload(
                np.zeros(0), meta={"error": "serving stopped"}))
            self._refs_done(req.shm_refs)

    def _decode_and_shed(self, batch):
        """Per-item decode (one malformed record fails itself, not its
        batchmates) + deadline shedding: a request whose ``meta.deadline``
        (absolute epoch seconds, stamped at admission) has passed is
        answered with an error payload and NEVER reaches the device. Routes
        the rest by ``meta.model`` (default: the multiplexer's first model).
        Returns ``(requests, shed_replies, trace_token)`` — shed replies
        are (item_id, payload) pairs the CALLER publishes after recording
        the decode/batch spans (publishing here would let a fast client
        observe every result before the shed-all batch span exists — the
        span-vs-result race the streaming-cadence tests caught); the token
        is the first decoded item's (shed included)."""
        reqs: List[ServingRequest] = []
        shed: List[Tuple[str, bytes, tuple]] = []
        batch_tok = None
        default_model = self.mux.default_name
        with self.timer.time("decode"):
            _faults.fire("serving.decode")  # chaos hook (whole batch)
            now = time.time()
            for item_id, payload in batch:
                refs: tuple = ()
                try:
                    data, meta, item_refs = decode_ref(
                        payload, arena=self._arena)
                    refs = tuple(item_refs)
                    if batch_tok is None:
                        batch_tok = meta.get("trace")
                    # deadline parse is per-item too: a client that sends
                    # meta={"deadline": "soon"} must fail itself, not
                    # feed the breaker and fail its batchmates
                    deadline = meta.get("deadline")
                    expired = (deadline is not None
                               and now > float(deadline))
                except Exception as e:      # noqa: BLE001 — bad record
                    self._count("decode_errors")
                    self.broker.put_result(item_id, encode_payload(
                        np.zeros(0), meta={"error": f"bad payload: {e}"}))
                    self._refs_done(refs)
                    continue
                if expired:
                    self._count("shed_expired")
                    STATS.add("serving.shed_expired")
                    shed.append((item_id, encode_payload(
                        np.zeros(0),
                        meta={"error": "deadline exceeded",
                              "shed": "expired"}), refs))
                    continue
                model = meta.get("model") or default_model
                if model not in self.mux:
                    self._count("unknown_model")
                    self.broker.put_result(item_id, encode_payload(
                        np.zeros(0), meta={
                            "error": f"unknown model {model!r} (serving: "
                                     f"{sorted(self.mux.names())})"}))
                    self._refs_done(refs)
                    continue
                # sparse ingress (reference: http/domains.scala:100)
                # densifies at admission — the TPU executable wants static
                # dense. Per-item like the decode: a record that decodes
                # but won't densify (out-of-range sparse indices) fails
                # itself, not its batchmates
                try:
                    reqs.append(ServingRequest(item_id, densify(data),
                                               meta, model,
                                               shm_refs=refs))
                except Exception as e:      # noqa: BLE001 — bad record
                    self._count("decode_errors")
                    self.broker.put_result(item_id, encode_payload(
                        np.zeros(0), meta={"error": f"bad payload: {e}"}))
                    self._refs_done(refs)
        return reqs, shed, batch_tok

    def _publish_shed(self, shed):
        for item_id, payload, refs in shed:
            self.broker.put_result(item_id, payload)
            self._refs_done(refs)

    def _decode_prologue(self, batch):
        """The shared claim prologue for BOTH claim paths (continuous
        ``_route_claim`` and legacy ``_handle_fixed``): decode + shed with
        whole-stage fault answering, the ``serving.decode`` span, and —
        for a fully-expired claim — a shed-all ``serving.batch`` span
        recorded BEFORE the shed answers publish (a fast client that saw
        every result can rely on the span existing — exactly the overload
        case the Perfetto timeline should explain). Returns
        ``(requests, batch_token)``, or None when the claim was fully
        answered here."""
        t_dec = time.perf_counter()
        try:
            reqs, shed, batch_tok = self._decode_and_shed(batch)
        except Exception as e:  # noqa: BLE001 — injected/decode-stage fault
            self.mux.default.breaker.record_failure()
            self._count("batch_failures")
            logger.exception("serving decode stage failed: %s", e)
            for item_id, payload in batch:
                self.broker.put_result(item_id, encode_payload(
                    np.zeros(0), meta={"error": str(e)}))
                # the per-item refs were lost with the stage: peek the
                # descriptors off the raw payload (no checkout) so the
                # answered items' slabs still free
                try:
                    self._refs_done(_shm_peek_refs(payload))
                except Exception as pe:  # noqa: BLE001 — malformed frame
                    logger.warning("shm peek failed: %s", pe)
            return None
        _trace.record_span("serving.decode", t_dec, time.perf_counter(),
                           parent=batch_tok, n=len(batch))
        if not reqs:
            if shed:
                t1 = time.perf_counter()
                _trace.record_span("serving.batch", t1, t1,
                                   parent=batch_tok, n=0, shed=len(shed))
            self._publish_shed(shed)
            return None
        self._publish_shed(shed)
        return reqs, batch_tok

    # --- dispatch workers ----------------------------------------------------
    def _cap_for(self, model: str) -> int:
        return self.mux.bucket_cap(model, self.batch_size)

    def _dispatch_loop(self):
        while not self._stop.is_set():
            nb = self.sched.next_batch(self._cap_for)
            if nb is None:
                return      # stopped, or drained dry
            model_name, reqs = nb
            self._dispatch_batch(model_name, reqs)

    def _dispatch_batch(self, model_name: str, reqs):
        """Shed-recheck + breaker-gate + run one formed batch. EVERY
        request in ``reqs`` is released from the inflight ledger in the
        one outer ``finally`` — a broker that throws mid-answer (even on
        the shed or open-circuit paths) or a BaseException worker death
        must not leak ``max_inflight`` slots and wedge the claim pump;
        results never published stay claimed for XAUTOCLAIM."""
        try:
            self._dispatch_batch_inner(model_name, reqs)
        finally:
            self.sched.done(len(reqs))

    def _dispatch_batch_inner(self, model_name: str, reqs):
        entry = self.mux.get(model_name)
        batch_tok = next((r.trace for r in reqs if r.trace), None)
        # requests can expire while queued: shed them at the moment of
        # dispatch too, so the device never computes an answer nobody is
        # waiting for
        now = time.time()
        live = []
        n_shed = 0
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                n_shed += 1
                self._count("shed_expired")
                STATS.add("serving.shed_expired")
                self.broker.put_result(r.item_id, encode_payload(
                    np.zeros(0), meta={"error": "deadline exceeded",
                                       "shed": "expired"}))
                self._refs_done(r.shm_refs)
            else:
                live.append(r)
        if not live:
            # shed-all batch: still a batch span, so the timeline shows
            # the overload instead of a silent gap
            t1 = time.perf_counter()
            _trace.record_span("serving.batch", t1, t1, parent=batch_tok,
                               n=0, shed=n_shed, model=model_name)
            return
        if not entry.breaker.allow():
            # open circuit: fail fast, the device never sees the batch —
            # per-model, so a wedged neighbour cannot shed this one's
            # traffic
            self._count("shed_open", len(live))
            STATS.add("serving.shed_open", len(live))
            for r in live:
                self.broker.put_result(r.item_id, encode_payload(
                    np.zeros(0), meta={"error": "circuit open",
                                       "shed": "circuit_open"}))
                self._refs_done(r.shm_refs)
            return
        try:
            self._process(entry, live, batch_tok)
            entry.breaker.record_success()
        except Exception as e:  # noqa: BLE001 — serving must not die
            entry.breaker.record_failure()
            self._count("batch_failures")
            logger.exception("serving batch failed (model=%s): %s",
                             model_name, e)
            for r in live:
                self.broker.put_result(r.item_id, encode_payload(
                    np.zeros(0), meta={"error": str(e)}))
                self._refs_done(r.shm_refs)

    def _process(self, entry, live, batch_tok=None):
        arrays = [r.data for r in live]
        tok = batch_tok
        with _trace.span_under(tok, "serving.batch", n=len(live),
                               model=entry.name), \
                self.timer.time("batch"):
            first = arrays[0]
            if isinstance(first, list):
                stacked = [np.stack([a[i] for a in arrays])
                           for i in range(len(first))]
            elif isinstance(first, dict):
                # named multi-tensor records: stack per key (values
                # fetched BY NAME per record) and feed the model
                # positionally in the record's own key order — the
                # reference's LinkedHashMap insertion-order semantics
                # (http/domains.scala:102), i.e. clients declare tensors
                # in the model's input order. The signature routing already
                # groups by key order, so a mismatch here is a bug guard.
                order = tuple(first.keys())
                for a in arrays:
                    if tuple(a.keys()) != order:
                        raise ValueError(
                            f"named-tensor records disagree on key order "
                            f"({order} vs {tuple(a.keys())}); all clients "
                            "of one stream must enqueue tensors in the "
                            "model's input order")
                stacked = [np.stack([a[k] for a in arrays]) for k in order]
            else:
                stacked = np.stack(arrays)
        t_busy = time.perf_counter()
        with _trace.span_under(tok, "serving.dispatch", n=len(live),
                               model=entry.name), \
                self.timer.time("inference"):
            preds = entry.model.predict(stacked)
        self._c_busy.inc(time.perf_counter() - t_busy)
        with _trace.span_under(tok, "serving.respond"), \
                self.timer.time("encode"):
            done_t = time.time()
            multi = isinstance(preds, (list, tuple))
            for i, r in enumerate(live):
                if multi:
                    out = [np.asarray(p[i]) for p in preds]
                else:
                    out = np.asarray(preds[i])
                # t_done lets open-loop load generators account latency at
                # completion time, independent of their fetch scheduling
                self.broker.put_result(r.item_id, encode_payload(
                    out, meta={"t_done": done_t}))
                self._refs_done(r.shm_refs)
        self.records_out += len(live)
        entry.records_out += len(live)
        entry.batches += 1
        self._count_batch(entry.name, len(live))

    # --- legacy fixed policy -------------------------------------------------
    def _worker_fixed(self):
        """The original discipline: claim up to ``batch_size`` (waiting at
        most ``batch_timeout``), then decode/shed/group/dispatch in this
        thread. Kept as the A/B baseline for bench_serving_scale."""
        while not self._stop.is_set():
            with self.timer.time("claim"):
                batch = self.broker.claim_batch(self.batch_size,
                                                self.batch_timeout)
            if not batch:
                if self._draining.is_set():
                    return      # drained: queue empty, stop claiming
                continue
            self._handle_fixed(batch)

    def _handle_fixed(self, batch):
        prologue = self._decode_prologue(batch)
        if prologue is None:
            return
        reqs, _batch_tok = prologue
        # group by (model, signature) — a mixed claim dispatches per group
        groups: Dict = {}
        for r in reqs:
            groups.setdefault((r.model, r.sig), []).append(r)
        for (model_name, _sig), grp in groups.items():
            # the fixed path bypasses the admission queues but keeps the
            # inflight ledger balanced against _dispatch_batch's done()
            self.sched.admit(len(grp))
            self._dispatch_batch(model_name, grp)

    # --- lifecycle ----------------------------------------------------------
    def start(self, example=None):
        """Start the claim pump + dispatch workers. With ``example`` (a
        batch-shaped array, or list of arrays, matching real traffic's
        record shape/dtype), every shape bucket up to ``batch_size`` is
        compiled for the DEFAULT model before serving begins; multiplexed
        models precompile from the ``example`` passed to
        ``ModelMultiplexer.add_model`` — the XLA analogue of the reference
        pre-filling its model-copy queue (InferenceModel.scala:580-626).
        Without warm buckets, partial batches hit cold buckets and compiles
        land in the latency tail."""
        if example is not None:
            self.mux.default.example = example
        with self.timer.time("precompile"):
            for entry in self.mux.entries():
                if entry.example is not None and \
                        hasattr(entry.model, "precompile"):
                    # precompile rounds batch_size up to the bucket
                    # steady-state full batches actually land in
                    entry.model.precompile(entry.example,
                                           max_bucket=self.batch_size)
        if self.policy == "continuous":
            self._pump_thread = threading.Thread(
                target=self._pump, daemon=True, name="serving-pump")
            self._pump_thread.start()
            target = self._dispatch_loop
        else:
            target = self._worker_fixed
        for i in range(self.num_workers):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"serving-worker-{i}")
            t.start()
            self._threads.append(t)
        if self.worker_id:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name="serving-heartbeat")
            self._hb_thread.start()
        return self

    # --- fleet heartbeat -----------------------------------------------------
    def _hb_stats(self) -> Dict:
        return {
            "busy_s": round(float(self._c_busy.value), 6),
            "records_out": self.records_out,
            "inflight": self.sched.inflight,
            "queue_depth": sum(self.sched.depths().values()),
            "oldest_wait_s": round(self.sched.oldest_wait_s(), 4),
            "reclaimed": int(getattr(self.broker, "reclaimed", 0)),
            "draining": self.draining,
        }

    def _heartbeat_loop(self):
        # first beat immediately: the fleet's wait_live() sees a spawned
        # worker as soon as its engine starts, not one period later
        while True:
            try:
                self.broker.heartbeat(self.worker_id, self._hb_stats())
            except Exception as e:  # noqa: BLE001 — liveness is best-effort
                logger.debug("heartbeat publish failed: %s", e)
            if self._stop.wait(self.heartbeat_s):
                return

    def _clear_heartbeat(self):
        if not self.worker_id:
            return
        try:
            self.broker.clear_heartbeat(self.worker_id)
        except Exception as e:  # noqa: BLE001 — broker may already be down
            logger.debug("heartbeat clear failed: %s", e)

    def stop(self):
        self._stop.set()
        self.sched.close()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5)
        for t in self._threads:
            t.join(timeout=5)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        self._clear_heartbeat()
        self._close_series()

    def drain(self, timeout_s: float = 30.0) -> Dict:
        """Graceful shutdown (the SIGTERM path, shared with the training
        supervisor via ``PreemptionWatcher(on_signal=...)``): stop
        *accepting* (the frontend 503s while ``draining``), let the pump
        finish claiming the broker backlog and the workers finish every
        admitted request — in-flight batches AND the queued backlog — then
        stop and return the final metrics snapshot (flushed to the log,
        the Flink analogue of a savepoint-stop)."""
        self._draining.set()
        STATS.add("serving.drains")
        deadline = time.monotonic() + timeout_s
        if self._pump_thread is not None:
            self._pump_thread.join(
                timeout=max(0.0, deadline - time.monotonic()))
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        # short final joins — a wedged worker must not stretch the
        # caller's SIGTERM grace budget by stop()'s 5s-per-thread joins
        self._stop.set()
        self.sched.close()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=1)
        for t in self._threads:
            t.join(timeout=1)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        self._clear_heartbeat()
        # drop this instance's registry series like stop() does — a
        # supervisor that drain()s and rebuilds must not accumulate
        # dead-uuid series scrape after scrape; metrics() keeps working
        # off the cached children for the returned snapshot
        self._close_series()
        snap = self.metrics()
        logger.info("serving drained (records_out=%d, pending=%s): %s",
                    self.records_out,
                    self._safe_pending(), snap.get("resilience"))
        return snap

    def _safe_pending(self):
        try:
            return self.broker.pending()
        except Exception:       # noqa: BLE001 — broker may already be down
            return None

    def metrics(self) -> Dict:
        """(reference observability: Flink numRecordsOutPerSecond +
        Timer stats)"""
        # the dict is a view over the registry children (obs plane): same
        # keys and int values as the pre-registry per-engine dict
        res = {k: int(c.value) for k, c in self._res_children.items()}
        res["breaker"] = self.breaker.snapshot()
        res["draining"] = self.draining
        model = self.model
        out = {"records_out": self.records_out,
               # batch-dim sharding spreads every batch over these chips
               # (reference scales with model replicas / Flink parallelism);
               # 1 for eager/call_tf models, which compute host-side
               "devices": getattr(model, "device_count", 1),
               "stages": self.timer.summary(),
               # overload/fault counters: expired requests shed before
               # dispatch, open-circuit sheds, breaker state — the serving
               # face of the resilience plane
               "resilience": res,
               # the continuous former + multiplexer: admitted inflight,
               # per-model queue depth / served counts / breaker state
               "scheduler": {
                   "policy": self.policy,
                   "models": self.mux.names(),
                   "inflight": self.sched.inflight,
                   "max_inflight": self.max_inflight,
                   "slack_ms": round(self.slack_s * 1e3, 3),
                   "queue_depth": self.sched.depths(),
                   "busy_s": round(float(self._c_busy.value), 6),
                   "per_model": self.mux.snapshot()}}
        if hasattr(model, "transfer_stats"):
            # transfer-plane counters: serving-ingress h2d seconds/bytes/
            # MB/s from the sharded device_put path (native/transfer.py)
            snap = model.transfer_stats()
            if snap and snap.get("h2d_n"):
                out["transfer"] = snap
        if hasattr(model, "compile_stats"):
            # compiles vs cache/disk hits — read next to the "precompile"
            # stage timer to see whether warmup paid real compilation or
            # reused executables (in-process or from the disk cache). Empty
            # when this model's plane is off: omit rather than clobber the
            # process-wide counters the HTTP /metrics handler surfaces.
            snap = model.compile_stats()
            if snap:
                out["compile"] = snap
        if hasattr(model, "ckpt_stats"):
            # checkpoint-plane hot-reload counters (weights swapped into
            # the live model; full_reloads > 0 means a structure change
            # forced bucket recompiles). Empty until the first reload.
            snap = model.ckpt_stats()
            if snap:
                out["ckpt"] = snap
        if len(self.mux) > 1:
            # multiplexed: per-model compile counters prove (or disprove)
            # the zero-cross-model-churn contract from the same surface
            snap = self.mux.compile_stats()
            if snap:
                out["compile_per_model"] = snap
        return out

    def reset_metrics(self):
        """Zero the stage timers and record counter — call after warmup so
        ``metrics()`` reports steady-state percentiles."""
        self.timer.reset()
        self.records_out = 0

    def update_model(self, model: InferenceModel, name: Optional[str] = None):
        """Hot-swap a served model (the reference rolls a new model by
        restarting the Flink job, ClusterServingGuide 'model update'; here
        the swap is a reference assignment — in-flight batches finish on
        the old executables, the next dispatch uses the new ones). With
        ``name``, swaps (or adds) that multiplexer entry; default: the
        default model."""
        self.mux.add_model(name or self.mux.default_name, model)
        return self
