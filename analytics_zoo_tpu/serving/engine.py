"""Cluster Serving engine — queue -> dynamic batcher -> TPU inference -> results.

The reference pipeline (SURVEY.md §3.5) is Redis stream -> Flink
FlinkRedisSource (xreadGroup, engine/FlinkRedisSource.scala:78-104) ->
FlinkInference -> ClusterServingInference batching
(engine/ClusterServingInference.scala:36-152) -> InferenceModel.doPredict ->
FlinkRedisSink. The TPU-native pipeline drops Flink entirely: a worker thread
claims up to ``batch_size`` requests (waiting at most ``batch_timeout_ms`` —
dynamic batching), stacks them, runs the shape-bucketed compiled executable,
and writes per-request results back. Per-stage latency is tracked like the
reference's Timer (serving/engine/Timer.scala:102).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np

from ..obs import trace as _trace
from ..obs.registry import REGISTRY, InstancedEvents
from ..pipeline.inference.inference_model import InferenceModel
from ..resilience import faults as _faults
from ..resilience.retry import CircuitBreaker
from ..resilience.stats import STATS
from .codecs import decode_payload, densify, encode_payload
from .queue_api import Broker, make_broker

logger = logging.getLogger("analytics_zoo_tpu")


class Timer:
    """(reference: serving/engine/Timer.scala) — n-record latency stats."""

    def __init__(self):
        self.stats: Dict[str, List[float]] = defaultdict(list)

    def time(self, name: str):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()

            def __exit__(self, *a):
                timer.stats[name].append(time.perf_counter() - self.t0)

        return _Ctx()

    def summary(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for name, vals in self.stats.items():
            arr = np.asarray(vals)
            out[name] = {"count": len(arr), "mean_ms": float(arr.mean() * 1e3),
                         "p50_ms": float(np.percentile(arr, 50) * 1e3),
                         "p95_ms": float(np.percentile(arr, 95) * 1e3),
                         "p99_ms": float(np.percentile(arr, 99) * 1e3)}
        return out

    def reset(self):
        """Drop accumulated samples (e.g. after warmup, so reported
        percentiles are steady-state rather than compile-tainted)."""
        self.stats = defaultdict(list)


class ClusterServing:
    """(reference entry: serving/ClusterServing.scala:69; config via
    utils/ClusterServingHelper.scala)"""

    def __init__(self, model: InferenceModel,
                 queue: str = "memory://serving_stream",
                 batch_size: int = 32, batch_timeout_ms: float = 5.0,
                 model_parallelism: int = 1,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 30.0):
        self.model = model
        self.broker: Broker = make_broker(queue) if isinstance(queue, str) \
            else queue
        self.batch_size = batch_size
        self.batch_timeout = batch_timeout_ms / 1e3
        # modelParallelism in the reference = number of model copies
        # (ClusterServing.scala:60); XLA executables are reentrant so this is
        # the number of batcher threads.
        self.num_workers = model_parallelism
        self.timer = Timer()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._threads: List[threading.Thread] = []
        self.records_out = 0
        # overload safety: expired requests are shed BEFORE device
        # dispatch; the breaker opens after `breaker_threshold` consecutive
        # batch failures so a wedged model/device sheds fast instead of
        # burning every request's deadline against it, half-opening on one
        # probe after the cooldown
        self.breaker = CircuitBreaker(threshold=breaker_threshold,
                                      cooldown_s=breaker_cooldown_s,
                                      name="serving")
        # overload counters live in the unified metrics registry (obs
        # plane): one family labeled per engine instance, so metrics()'s
        # dict stays a per-engine view (starting at 0) while /metrics.prom
        # exposes the same series process-wide
        self._res_events = InstancedEvents(
            REGISTRY.counter(
                "zoo_serving_engine_events_total",
                "serving-engine overload events: expired/open-circuit "
                "sheds, batch failures, decode errors",
                labelnames=("inst", "event")),
            ("shed_expired", "shed_open", "batch_failures",
             "decode_errors"))
        self._res_children = self._res_events.children

    def _count(self, key: str, n: int = 1):
        self._res_children[key].inc(n)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    # --- worker loop --------------------------------------------------------
    def _worker(self):
        while not self._stop.is_set():
            with self.timer.time("claim"):
                batch = self.broker.claim_batch(self.batch_size,
                                                self.batch_timeout)
            if not batch:
                if self._draining.is_set():
                    return      # drained: queue empty, stop claiming
                continue
            self._handle(batch)

    def _handle(self, batch):
        """Decode + shed + breaker-gate + process one claimed batch. Every
        claimed item gets a result — error payloads for shed/failed ones —
        so frontend fetches never wait out their full timeout on a request
        the engine already gave up on."""
        t_dec = time.perf_counter()     # span timebase (see record_span)
        try:
            live, batch_tok = self._decode_and_shed(batch)
            # the request's trace token rides the payload meta (stamped by
            # the HTTP frontend inside its serving.request span), so the
            # decode/batch/dispatch spans recorded on THIS worker thread
            # chain to the request that enqueued the batch's head — the
            # Dapper-style cross-process handoff. Retroactive: the token
            # is only known after decoding. The token comes from the first
            # decoded item carrying one, shed or live, so a fully-shed batch
            # (exactly the overload case tracing should explain) still
            # chains to the shedding request instead of minting an orphan
            # trace per drain.
            _trace.record_span("serving.decode", t_dec,
                               time.perf_counter(),
                               parent=batch_tok, n=len(batch))
        except Exception as e:  # noqa: BLE001 — injected/decode-stage fault
            self.breaker.record_failure()
            self._count("batch_failures")
            logger.exception("serving decode stage failed: %s", e)
            for item_id, _ in batch:
                self.broker.put_result(item_id, encode_payload(
                    np.zeros(0), meta={"error": str(e)}))
            return
        if not live:
            return
        if not self.breaker.allow():
            # open circuit: fail fast, the device never sees the batch
            self._count("shed_open", len(live))
            STATS.add("serving.shed_open", len(live))
            for item_id, _arr, _meta in live:
                self.broker.put_result(item_id, encode_payload(
                    np.zeros(0), meta={"error": "circuit open",
                                       "shed": "circuit_open"}))
            return
        try:
            self._process(live, batch_tok)
            self.breaker.record_success()
        except Exception as e:  # noqa: BLE001 — serving must not die
            self.breaker.record_failure()
            self._count("batch_failures")
            logger.exception("serving batch failed: %s", e)
            for item_id, _arr, _meta in live:
                self.broker.put_result(item_id, encode_payload(
                    np.zeros(0), meta={"error": str(e)}))

    def _decode_and_shed(self, batch):
        """Per-item decode (one malformed record fails itself, not its
        batchmates) + deadline shedding: a request whose ``meta.deadline``
        (absolute epoch seconds, stamped at admission) has passed is
        answered with an error payload and NEVER reaches the device.
        Returns ``(live, trace_token)`` — the token of the first decoded
        item CARRYING one (shed included), for the batch's spans."""
        live = []
        batch_tok = None
        with self.timer.time("decode"):
            _faults.fire("serving.decode")  # chaos hook (whole batch)
            now = time.time()
            for item_id, payload in batch:
                try:
                    data, meta = decode_payload(payload)
                    if batch_tok is None:
                        batch_tok = meta.get("trace")
                    # deadline parse is per-item too: a client that sends
                    # meta={"deadline": "soon"} must fail itself, not
                    # feed the breaker and fail its batchmates
                    deadline = meta.get("deadline")
                    expired = (deadline is not None
                               and now > float(deadline))
                except Exception as e:      # noqa: BLE001 — bad record
                    self._count("decode_errors")
                    self.broker.put_result(item_id, encode_payload(
                        np.zeros(0), meta={"error": f"bad payload: {e}"}))
                    continue
                if expired:
                    self._count("shed_expired")
                    STATS.add("serving.shed_expired")
                    self.broker.put_result(item_id, encode_payload(
                        np.zeros(0),
                        meta={"error": "deadline exceeded",
                              "shed": "expired"}))
                    continue
                # sparse ingress (reference: http/domains.scala:100)
                # densifies at batch assembly — the TPU executable wants
                # static dense. Per-item like the decode: a record that
                # decodes but won't densify (out-of-range sparse indices)
                # fails itself, not its batchmates
                try:
                    live.append((item_id, densify(data), meta))
                except Exception as e:      # noqa: BLE001 — bad record
                    self._count("decode_errors")
                    self.broker.put_result(item_id, encode_payload(
                        np.zeros(0), meta={"error": f"bad payload: {e}"}))
        return live, batch_tok

    def _process(self, live, batch_tok=None):
        arrays = [a for _, a, _ in live]
        # one batch = one trace: batch/dispatch/respond parent at the same
        # token serving.decode joined (_decode_and_shed already scanned
        # every decoded item, live ones included, so there is no second
        # place to look when it found none)
        tok = batch_tok
        with _trace.span_under(tok, "serving.batch", n=len(live)), \
                self.timer.time("batch"):
            first = arrays[0]
            if isinstance(first, list):
                stacked = [np.stack([a[i] for a in arrays])
                           for i in range(len(first))]
            elif isinstance(first, dict):
                # named multi-tensor records: stack per key (values
                # fetched BY NAME per record) and feed the model
                # positionally in the record's own key order — the
                # reference's LinkedHashMap insertion-order semantics
                # (http/domains.scala:102), i.e. clients declare tensors
                # in the model's input order. Records that disagree on
                # that order cannot be bound unambiguously: fail the
                # batch with a clear message instead of silently feeding
                # someone's tensors into the wrong inputs.
                order = tuple(first.keys())
                for a in arrays:
                    if tuple(a.keys()) != order:
                        raise ValueError(
                            f"named-tensor records disagree on key order "
                            f"({order} vs {tuple(a.keys())}); all clients "
                            "of one stream must enqueue tensors in the "
                            "model's input order")
                stacked = [np.stack([a[k] for a in arrays]) for k in order]
            else:
                stacked = np.stack(arrays)
        with _trace.span_under(tok, "serving.dispatch", n=len(live)), \
                self.timer.time("inference"):
            preds = self.model.predict(stacked)
        with _trace.span_under(tok, "serving.respond"), \
                self.timer.time("encode"):
            multi = isinstance(preds, (list, tuple))
            for i, (item_id, _arr, _meta) in enumerate(live):
                if multi:
                    out = [np.asarray(p[i]) for p in preds]
                else:
                    out = np.asarray(preds[i])
                self.broker.put_result(item_id, encode_payload(out))
        self.records_out += len(live)

    # --- lifecycle ----------------------------------------------------------
    def start(self, example=None):
        """Start worker threads. With ``example`` (a batch-shaped array, or
        list of arrays, matching real traffic's record shape/dtype), every
        shape bucket up to ``batch_size`` is compiled BEFORE serving begins —
        the XLA analogue of the reference pre-filling its model-copy queue
        (InferenceModel.scala:580-626). Without it, timeout-sized partial
        batches hit cold buckets and compiles land in the latency tail."""
        if example is not None:
            with self.timer.time("precompile"):
                # precompile rounds batch_size up to the bucket steady-state
                # full batches actually land in
                self.model.precompile(example, max_bucket=self.batch_size)
        for i in range(self.num_workers):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"serving-worker-{i}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        # drop this instance's series from the process exposition —
        # rebuilt engines must not leak dead-uuid series into every
        # scrape. The cached children keep serving metrics()'s view.
        self._res_events.close()

    def drain(self, timeout_s: float = 30.0) -> Dict:
        """Graceful shutdown (the SIGTERM path, shared with the training
        supervisor via ``PreemptionWatcher(on_signal=...)``): stop
        *accepting* (the frontend 503s while ``draining``), let the workers
        finish every already-admitted request — in-flight batches AND the
        queued backlog — then stop and return the final metrics snapshot
        (flushed to the log, the Flink analogue of a savepoint-stop)."""
        self._draining.set()
        STATS.add("serving.drains")
        deadline = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        # short final joins — a wedged worker must not stretch the
        # caller's SIGTERM grace budget by stop()'s 5s-per-thread joins
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1)
        # drop this instance's registry series like stop() does — a
        # supervisor that drain()s and rebuilds must not accumulate
        # dead-uuid series scrape after scrape; metrics() keeps working
        # off the cached children for the returned snapshot
        self._res_events.close()
        snap = self.metrics()
        logger.info("serving drained (records_out=%d, pending=%s): %s",
                    self.records_out,
                    self._safe_pending(), snap.get("resilience"))
        return snap

    def _safe_pending(self):
        try:
            return self.broker.pending()
        except Exception:       # noqa: BLE001 — broker may already be down
            return None

    def metrics(self) -> Dict:
        """(reference observability: Flink numRecordsOutPerSecond +
        Timer stats)"""
        # the dict is a view over the registry children (obs plane): same
        # keys and int values as the pre-registry per-engine dict
        res = {k: int(c.value) for k, c in self._res_children.items()}
        res["breaker"] = self.breaker.snapshot()
        res["draining"] = self.draining
        out = {"records_out": self.records_out,
               # batch-dim sharding spreads every batch over these chips
               # (reference scales with model replicas / Flink parallelism);
               # 1 for eager/call_tf models, which compute host-side
               "devices": getattr(self.model, "device_count", 1),
               "stages": self.timer.summary(),
               # overload/fault counters: expired requests shed before
               # dispatch, open-circuit sheds, breaker state — the serving
               # face of the resilience plane
               "resilience": res}
        if hasattr(self.model, "transfer_stats"):
            # transfer-plane counters: serving-ingress h2d seconds/bytes/
            # MB/s from the sharded device_put path (native/transfer.py)
            snap = self.model.transfer_stats()
            if snap and snap.get("h2d_n"):
                out["transfer"] = snap
        if hasattr(self.model, "compile_stats"):
            # compiles vs cache/disk hits — read next to the "precompile"
            # stage timer to see whether warmup paid real compilation or
            # reused executables (in-process or from the disk cache). Empty
            # when this model's plane is off: omit rather than clobber the
            # process-wide counters the HTTP /metrics handler surfaces.
            snap = self.model.compile_stats()
            if snap:
                out["compile"] = snap
        if hasattr(self.model, "ckpt_stats"):
            # checkpoint-plane hot-reload counters (weights swapped into
            # the live model; full_reloads > 0 means a structure change
            # forced bucket recompiles). Empty until the first reload.
            snap = self.model.ckpt_stats()
            if snap:
                out["ckpt"] = snap
        return out

    def reset_metrics(self):
        """Zero the stage timers and record counter — call after warmup so
        ``metrics()`` reports steady-state percentiles."""
        self.timer.reset()
        self.records_out = 0

    def update_model(self, model: InferenceModel):
        """Hot-swap the served model (the reference rolls a new model by
        restarting the Flink job, ClusterServingGuide 'model update'; here
        the swap is a reference assignment — in-flight batches finish on
        the old executables, the next claim uses the new ones)."""
        self.model = model
        return self
