"""Scale-out serving tier: N frontends x M workers over one broker.

PR 13 made ONE engine continuous and multiplexed; this module goes
horizontal. M worker *processes* fan over one stream as a consumer group
(disjoint claims, PEL redelivery on death), each running its own
ContinuousScheduler + ModelMultiplexer against its own chip set —
shared-nothing, so aggregate goodput scales with workers until the
broker or the chips saturate. A :class:`ServingFleet` supervisor spawns
and monitors the workers; an :class:`Autoscaler` control loop reads the
occupancy each worker heartbeats through the broker
(``zoo_serving_sched_busy_seconds_total`` deltas) plus the broker
backlog, and adds a worker on sustained saturation / retires one on
sustained idle, with cooldown hysteresis. Frontends shed on queue age
BEFORE enqueue (429 + Retry-After, ``http_frontend``), so the stream
holds work that will be served, not work that will expire.

Topology::

    client -> frontend-1 \\                    / worker-1 (chips 0..k)
    client -> frontend-2 --> broker (stream) --> worker-2 (chips k..2k)
    client -> frontend-N /    one group       \\ worker-M ...
                  ^                                 |
                  '------ results (hash/out dir) <--'

Everything crosses the broker: requests, results, worker heartbeats.
The supervisor holds no state a worker crash can lose — a SIGKILLed
worker's in-flight claims sit in the PEL until a surviving consumer's
idle-reclaim re-delivers them.
"""

from __future__ import annotations

import argparse
import functools
import json
import logging
import multiprocessing as mp
import os
import pickle
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..common import knobs
from ..obs import trace as _trace
from ..obs.registry import REGISTRY, InstancedEvents
from ..shm import sweep_spec as _shm_sweep_spec
from .queue_api import make_broker

logger = logging.getLogger("analytics_zoo_tpu")


def _dumps(obj) -> bytes:
    """Pickle a model factory for the spawn boundary — cloudpickle when
    available (lambdas/closures), plain pickle otherwise."""
    try:
        import cloudpickle
        return cloudpickle.dumps(obj)
    except ImportError:
        return pickle.dumps(obj)


def _loads(blob: bytes):
    # cloudpickle output is plain-pickle loadable; no import needed here
    return pickle.loads(blob)


class SleepModel:
    """Host-side stand-in for a chip-bound model: ``predict`` sleeps
    ``batch_ms`` (the GIL is released, so M worker processes on one host
    scale like M chip sets would) and returns ``x * k``. The fleet bench
    and CI smoke run on this — per-worker capacity is
    ``batch_size / batch_ms``, so linear-scaling gates measure the
    *topology*, not the host's arithmetic throughput."""

    def __init__(self, k: float = 2.0, batch_ms: float = 20.0):
        self.k = float(k)
        self.batch_ms = float(batch_ms)

    def predict(self, x):
        time.sleep(self.batch_ms / 1e3)
        return np.asarray(x) * self.k


def sleep_model_factory(k: float = 2.0, batch_ms: float = 20.0):
    """Module-level factory (plain-pickleable for spawn)."""
    return SleepModel(k=k, batch_ms=batch_ms)


class Autoscaler:
    """Occupancy-driven worker-count controller with hysteresis.

    One decision per :meth:`observe` tick, from three guards that all
    must agree before the count moves:

    - **threshold**: mean occupancy >= ``up_occupancy`` (or backlog >=
      ``depth_per_worker`` x workers) is *saturated*; occupancy <=
      ``down_occupancy`` AND empty backlog is *idle*;
    - **sustain**: the condition must hold continuously for
      ``up_sustain_s`` / ``down_sustain_s`` (one-tick spikes and gaps
      don't move capacity);
    - **cooldown**: after any action, hold ``cooldown_s`` (a scale-up's
      occupancy drop must not immediately argue for scale-down — the
      flap killer).

    Pure function of (now, signal): no threads, no clock reads — the
    hysteresis tests drive it with synthetic traces and an explicit
    ``now``.
    """

    def __init__(self, min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 up_occupancy: Optional[float] = None,
                 down_occupancy: Optional[float] = None,
                 up_sustain_s: Optional[float] = None,
                 down_sustain_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 depth_per_worker: int = 64):
        self.min_workers = max(1, int(min_workers))
        self.max_workers = int(knobs.get("ZOO_FLEET_MAX_WORKERS")
                               if max_workers is None else max_workers)
        self.up_occupancy = float(knobs.get("ZOO_FLEET_SCALE_OCCUPANCY")
                                  if up_occupancy is None else up_occupancy)
        self.down_occupancy = float(
            knobs.get("ZOO_FLEET_IDLE_OCCUPANCY")
            if down_occupancy is None else down_occupancy)
        self.up_sustain_s = float(
            knobs.get("ZOO_FLEET_SCALE_UP_SUSTAIN_S")
            if up_sustain_s is None else up_sustain_s)
        self.down_sustain_s = float(
            knobs.get("ZOO_FLEET_SCALE_DOWN_SUSTAIN_S")
            if down_sustain_s is None else down_sustain_s)
        self.cooldown_s = float(knobs.get("ZOO_FLEET_SCALE_COOLDOWN_S")
                                if cooldown_s is None else cooldown_s)
        self.depth_per_worker = int(depth_per_worker)
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._last_action_t: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0

    def observe(self, now: float, occupancy: float,
                queue_depth: int = 0, workers: int = 1) -> int:
        """Feed one sample; returns the target worker count (== ``workers``
        when nothing should change)."""
        saturated = (occupancy >= self.up_occupancy
                     or (self.depth_per_worker > 0 and queue_depth
                         >= self.depth_per_worker * max(1, workers)))
        idle = occupancy <= self.down_occupancy and queue_depth == 0
        if saturated:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
        elif idle:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
        else:
            self._above_since = None
            self._below_since = None
        in_cooldown = (self._last_action_t is not None
                       and now - self._last_action_t < self.cooldown_s)
        target = workers
        if (saturated and workers < self.max_workers and not in_cooldown
                and now - self._above_since >= self.up_sustain_s):
            target = workers + 1
            self.scale_ups += 1
        elif (idle and workers > self.min_workers and not in_cooldown
                and now - self._below_since >= self.down_sustain_s):
            target = workers - 1
            self.scale_downs += 1
        if target != workers:
            self._last_action_t = now
            # a fresh decision needs fresh evidence: the sustain windows
            # restart after every action
            self._above_since = None
            self._below_since = None
        return target


def _dump_spans(trace_dir: str, worker_id: str):
    """Write this process's recorded spans as JSONL — the parent stitches
    them to the frontend's spans by trace id (one trace crosses the
    process boundary through the payload meta)."""
    spans = _trace.spans()
    if not spans:
        return
    os.makedirs(trace_dir, exist_ok=True)
    path = os.path.join(trace_dir, f"spans-{worker_id}.jsonl")
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(s.to_dict()) + "\n")


def _worker_main(factory_blob: bytes, queue_spec: str, worker_id: str,
                 cfg_json: str):
    """Entry point of one fleet worker process (spawn target): build the
    model from the pickled factory, run a ClusterServing engine against
    the shared stream under this consumer id, heartbeat through the
    broker, drain gracefully on SIGTERM."""
    cfg = json.loads(cfg_json)
    for k, v in (cfg.get("env") or {}).items():
        os.environ[k] = str(v)
    if knobs.get("ZOO_TRACE"):
        _trace.arm()
    trace_dir = cfg.get("trace_dir")
    stop_ev = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_ev.set())
    factory = _loads(factory_blob)
    model = factory()
    from .engine import ClusterServing
    serving = ClusterServing(
        model, queue=queue_spec,
        batch_size=cfg.get("batch_size"),
        batch_timeout_ms=cfg.get("batch_timeout_ms"),
        policy=cfg.get("policy", "continuous"),
        max_inflight=cfg.get("max_inflight"),
        slack_ms=cfg.get("slack_ms"),
        worker_id=worker_id,
        heartbeat_s=cfg.get("heartbeat_s"))
    serving.start()
    logger.info("fleet worker %s up (pid=%d, queue=%s)", worker_id,
                os.getpid(), queue_spec)
    try:
        while not stop_ev.wait(0.2):
            pass
        serving.drain(timeout_s=float(cfg.get("drain_s", 15.0)))
    finally:
        if trace_dir:
            _dump_spans(trace_dir, worker_id)


class ServingFleet:
    """Supervisor for M shared-nothing worker processes over one broker.

    ``model_factory`` is a zero-arg callable returning the model each
    worker serves (pickled to the spawn boundary — every worker builds
    its OWN model on its own chip set; nothing is shared but the
    stream). ``queue`` must be a cross-process spec (``file://`` or
    ``redis://``; ``memory://`` cannot cross a process boundary and is
    rejected).

    The monitor thread ticks every ``poll_s``: reaps dead processes
    (respawning unexpected deaths), samples worker heartbeats into
    per-worker occupancy (busy-seconds deltas), feeds the
    :class:`Autoscaler`, and reconciles the process set to the target
    count — retire via SIGTERM (drain), crash recovery via respawn.
    """

    def __init__(self, model_factory: Callable[[], Any], queue: str,
                 *,
                 workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 policy: str = "continuous",
                 batch_size: Optional[int] = None,
                 batch_timeout_ms: Optional[float] = None,
                 max_inflight: Optional[int] = None,
                 slack_ms: Optional[float] = None,
                 autoscale: bool = True,
                 autoscaler: Optional[Autoscaler] = None,
                 heartbeat_s: Optional[float] = None,
                 worker_ttl_s: Optional[float] = None,
                 poll_s: float = 0.25,
                 drain_s: float = 15.0,
                 worker_env: Optional[Dict[str, str]] = None,
                 trace_dir: Optional[str] = None,
                 mp_start: str = "spawn"):
        if not isinstance(queue, str) or queue.startswith("memory://"):
            raise ValueError(
                "ServingFleet needs a cross-process queue spec (file:// "
                f"or redis://), got {queue!r} — memory:// lives in one "
                "process")
        self.queue = queue
        self._factory_blob = _dumps(model_factory)
        self.workers_initial = int(knobs.get("ZOO_FLEET_WORKERS")
                                   if workers is None else workers)
        self.heartbeat_s = float(knobs.get("ZOO_FLEET_HEARTBEAT_S")
                                 if heartbeat_s is None else heartbeat_s)
        self.worker_ttl_s = float(knobs.get("ZOO_FLEET_WORKER_TTL_S")
                                  if worker_ttl_s is None else worker_ttl_s)
        self.autoscale = autoscale
        self.autoscaler = autoscaler or Autoscaler(
            min_workers=max(1, self.workers_initial
                            if not autoscale else 1),
            max_workers=max_workers)
        if self.autoscaler.max_workers < self.workers_initial:
            self.autoscaler.max_workers = self.workers_initial
        self.poll_s = float(poll_s)
        self._cfg = {
            "policy": policy, "batch_size": batch_size,
            "batch_timeout_ms": batch_timeout_ms,
            "max_inflight": max_inflight, "slack_ms": slack_ms,
            "heartbeat_s": self.heartbeat_s, "drain_s": drain_s,
            "env": dict(worker_env or {}), "trace_dir": trace_dir,
        }
        self.broker = make_broker(queue)
        self._ctx = mp.get_context(mp_start)
        self._procs: Dict[str, Any] = {}
        self._retiring: set = set()
        self._target = self.workers_initial
        self._next_id = 0
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # last heartbeat stats per worker id, kept after death so the
        # fleet-wide cumulative aggregates (records_out, reclaimed)
        # survive the workers that produced them
        self._last_stats: Dict[str, Dict] = {}
        self._prev_busy: Dict[str, tuple] = {}
        self._live_now: Dict[str, Dict] = {}
        self._occupancy = 0.0
        # fleet-level obs: live/target worker gauges + lifecycle events,
        # per supervisor instance (inst label), series dropped on stop()
        self._events = InstancedEvents(
            REGISTRY.counter(
                "zoo_fleet_events_total",
                "fleet lifecycle events: worker spawns, unexpected-death "
                "respawns, autoscale decisions, graceful retirements",
                labelnames=("inst", "event")),
            ("spawned", "restarted", "scale_up", "scale_down", "retired"))
        inst = self._events.inst
        self._g_live = REGISTRY.gauge(
            "zoo_fleet_workers_live",
            "worker processes with a fresh heartbeat through the broker",
            labelnames=("inst",)).labels(inst=inst)
        self._g_target = REGISTRY.gauge(
            "zoo_fleet_workers_target",
            "worker count the supervisor is reconciling toward "
            "(autoscaler output)",
            labelnames=("inst",)).labels(inst=inst)
        self._inst = inst

    # --- lifecycle -----------------------------------------------------------
    def start(self) -> "ServingFleet":
        for _ in range(self.workers_initial):
            self._spawn()
        self._g_target.set(self._target)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="fleet-monitor")
        self._monitor.start()
        return self

    def _spawn(self):
        wid = f"w{self._next_id}"
        self._next_id += 1
        p = self._ctx.Process(
            target=_worker_main,
            args=(self._factory_blob, self.queue, wid,
                  json.dumps(self._cfg)),
            daemon=True, name=f"fleet-worker-{wid}")
        p.start()
        self._procs[wid] = p
        self._events["spawned"].inc()
        logger.info("fleet: spawned worker %s (pid=%d)", wid, p.pid)
        return wid

    def _retire(self, wid: str):
        p = self._procs.get(wid)
        if p is None or not p.is_alive():
            return
        self._retiring.add(wid)
        p.terminate()           # SIGTERM -> worker drains, dumps spans
        self._events["retired"].inc()
        logger.info("fleet: retiring worker %s (pid=%d)", wid, p.pid)

    def _monitor_loop(self):
        while not self._stop.wait(self.poll_s):
            try:
                self._tick(time.time())
            except Exception as e:  # noqa: BLE001 — supervisor must not die
                logger.warning("fleet monitor tick failed: %s", e)

    def _tick(self, now: float):
        with self._lock:
            # 1. reap: a retiring worker leaving is the plan; anything
            # else died under us and the reconcile below respawns it
            dead_pids: List[int] = []
            for wid, p in list(self._procs.items()):
                if p.is_alive():
                    continue
                p.join(timeout=0)
                del self._procs[wid]
                if p.pid is not None:
                    dead_pids.append(p.pid)
                if wid in self._retiring:
                    self._retiring.discard(wid)
                else:
                    self._events["restarted"].inc()
                    logger.warning(
                        "fleet: worker %s died (exitcode=%s) — respawning",
                        wid, p.exitcode)
            if dead_pids:
                # shm object plane: a SIGKILLed worker's slab pins die with
                # its pid — sweep its lease files so nothing leaks (unacked
                # entries replay and re-resolve their still-live blobs)
                try:
                    out = _shm_sweep_spec(self.queue, dead_pids)
                    if out.get("leases_swept") or out.get("freed"):
                        logger.info("fleet: shm sweep after reap: %s", out)
                except Exception as e:  # noqa: BLE001 — sweep is recovery,
                    logger.warning("fleet: shm sweep failed: %s", e)
            # 2. sample heartbeats -> per-worker occupancy from
            # busy-seconds deltas (rate of chip-busy wall time)
            try:
                live = self.broker.live_workers(self.worker_ttl_s)
            except Exception as e:  # noqa: BLE001 — broker blip
                logger.debug("fleet: live_workers probe failed: %s", e)
                live = {}
            self._live_now = live
            occs: List[float] = []
            for wid, stats in live.items():
                self._last_stats[wid] = stats
                busy = float(stats.get("busy_s", 0.0))
                t = float(stats.get("t", now))
                prev = self._prev_busy.get(wid)
                self._prev_busy[wid] = (t, busy)
                if prev and t > prev[0]:
                    occs.append(min(1.0, max(
                        0.0, (busy - prev[1]) / (t - prev[0]))))
            if occs:
                self._occupancy = sum(occs) / len(occs)
            elif not live:
                self._occupancy = 0.0
            # else: live workers but no fresh beat since the last tick
            # (poll_s can outrun heartbeat_s) — hold the previous
            # estimate instead of feeding a spurious zero to the
            # autoscaler, which would reset its sustain window
            try:
                depth = self.broker.pending()
            except Exception as e:  # noqa: BLE001 — broker blip
                logger.debug("fleet: pending probe failed: %s", e)
                depth = 0
            # 3. autoscale on the sampled signal
            if self.autoscale:
                new = self.autoscaler.observe(
                    now, self._occupancy, queue_depth=depth,
                    workers=self._target)
                if new > self._target:
                    self._events["scale_up"].inc()
                    logger.info(
                        "fleet: scale up %d -> %d (occ=%.2f depth=%d)",
                        self._target, new, self._occupancy, depth)
                elif new < self._target:
                    self._events["scale_down"].inc()
                    logger.info(
                        "fleet: scale down %d -> %d (occ=%.2f)",
                        self._target, new, self._occupancy)
                self._target = new
            # 4. reconcile process set to target
            active = [w for w in self._procs if w not in self._retiring]
            while len(active) < self._target:
                active.append(self._spawn())
            for wid in sorted(
                    active,
                    key=lambda w: int(w[1:]))[self._target:]:
                self._retire(wid)
            # 5. gauges
            self._g_live.set(len(live))
            self._g_target.set(self._target)

    def scale_to(self, n: int):
        """Manual override: set the reconcile target (the next tick
        spawns/retires to it). With autoscale on, the autoscaler keeps
        adjusting from the new baseline."""
        with self._lock:
            self._target = max(1, min(int(n), self.autoscaler.max_workers))

    def wait_live(self, n: int, timeout_s: float = 30.0) -> bool:
        """Block until >= n workers heartbeat as live."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            try:
                if len(self.broker.live_workers(self.worker_ttl_s)) >= n:
                    return True
            except Exception as e:  # noqa: BLE001 — broker warming up
                logger.debug("fleet: wait_live probe failed: %s", e)
            time.sleep(0.05)
        return False

    def metrics(self) -> Dict:
        with self._lock:
            live = dict(self._live_now)
            stats = {w: dict(s) for w, s in self._last_stats.items()}
            ev = {k: int(c.value) for k, c in self._events.children.items()}
            return {
                "workers_target": self._target,
                "workers_procs": len(self._procs),
                "workers_live": len(live),
                "occupancy": round(self._occupancy, 4),
                "spawned": ev["spawned"],
                "restarts": ev["restarted"],
                "retired": ev["retired"],
                "scale_ups": self.autoscaler.scale_ups,
                "scale_downs": self.autoscaler.scale_downs,
                "records_out_total": sum(
                    int(s.get("records_out", 0)) for s in stats.values()),
                "reclaimed_total": sum(
                    int(s.get("reclaimed", 0)) for s in stats.values()),
                "per_worker": stats,
            }

    def kill_worker(self, wid: Optional[str] = None) -> Optional[str]:
        """SIGKILL one worker (chaos surface: no drain, no span dump —
        its pending claims must re-deliver via the broker's idle-reclaim).
        Returns the killed worker id, or None if none alive."""
        with self._lock:
            victims = [w for w, p in self._procs.items()
                       if p.is_alive() and w not in self._retiring]
            if wid is None:
                wid = victims[0] if victims else None
            if wid is None or wid not in self._procs:
                return None
            self._procs[wid].kill()
            logger.info("fleet: SIGKILLed worker %s (chaos)", wid)
            return wid

    def drain(self, timeout_s: float = 30.0) -> Dict:
        """Graceful fleet shutdown: SIGTERM every worker (each drains its
        admitted work), join, return the final metrics snapshot."""
        return self.stop(timeout_s=timeout_s)

    def stop(self, timeout_s: float = 10.0) -> Dict:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._lock:
            procs = dict(self._procs)
        # one last heartbeat sample so the snapshot reflects final
        # cumulative counters (workers clear their hb entry on drain).
        # Liveness doesn't matter here, only the counters, so a stale
        # beat on a loaded host is still worth merging — sample with a
        # generous ttl instead of worker_ttl_s
        try:
            for wid, s in self.broker.live_workers(
                    max(self.worker_ttl_s, 60.0)).items():
                self._last_stats[wid] = s
        except Exception as e:  # noqa: BLE001 — broker may be gone
            logger.debug("fleet: final heartbeat sample failed: %s", e)
        for p in procs.values():
            if p.is_alive():
                p.terminate()
        deadline = time.time() + timeout_s
        for p in procs.values():
            p.join(timeout=max(0.1, deadline - time.time()))
        for wid, p in procs.items():
            if p.is_alive():
                logger.warning("fleet: worker %s ignored SIGTERM — "
                               "SIGKILL", wid)
                p.kill()
                p.join(timeout=2)
        # final shm sweep: no worker pid survives stop(), so any lease a
        # SIGKILLed worker left behind is dropped here
        try:
            _shm_sweep_spec(self.queue,
                            [p.pid for p in procs.values()
                             if p.pid is not None])
        except Exception as e:  # noqa: BLE001 — sweep is best-effort
            logger.warning("fleet: shm sweep on stop failed: %s", e)
        snap = self.metrics()
        self._events.close()
        REGISTRY.gauge("zoo_fleet_workers_live",
                       labelnames=("inst",)).remove(inst=self._inst)
        REGISTRY.gauge("zoo_fleet_workers_target",
                       labelnames=("inst",)).remove(inst=self._inst)
        logger.info("fleet stopped: %s", {
            k: snap[k] for k in ("workers_target", "records_out_total",
                                 "restarts", "scale_ups", "scale_downs")})
        return snap


def _model_loader(path: str, tf_inputs: Optional[str],
                  tf_outputs: Optional[str]):
    """Module-level factory for real models (plain-pickleable): each
    worker loads its own copy from ``path`` on its own chip set."""
    from ..pipeline.inference import InferenceModel
    model = InferenceModel()
    if (path.endswith(".pb") or path.endswith(".h5")
            or os.path.isdir(path)):
        model.load_tf(
            path,
            input_names=tf_inputs.split(",") if tf_inputs else None,
            output_names=tf_outputs.split(",") if tf_outputs else None)
    else:
        model.load(path)
    return model


def main(argv=None):
    """``zoo-serving-fleet``: supervise M serving workers over one broker.

    Pair with one or more ``zoo-serving`` frontends on the same
    ``--queue`` spec (frontends enqueue + fetch; this process only runs
    workers)."""
    p = argparse.ArgumentParser(description=main.__doc__)
    p.add_argument("--queue", required=True,
                   help="cross-process broker spec: file:///dir or "
                        "redis://host:port/stream (optionally "
                        "?claim_idle_ms=...)")
    p.add_argument("--model", default=None,
                   help="model path each worker loads (InferenceModel."
                        "save dir/.pkl, SavedModel/.pb/.h5); default: a "
                        "SleepModel toy (topology testing)")
    p.add_argument("--tf-inputs", default=None)
    p.add_argument("--tf-outputs", default=None)
    p.add_argument("--workers", type=int, default=None,
                   help="initial worker count (ZOO_FLEET_WORKERS)")
    p.add_argument("--max-workers", type=int, default=None,
                   help="autoscale ceiling (ZOO_FLEET_MAX_WORKERS)")
    p.add_argument("--no-autoscale", action="store_true",
                   help="pin the worker count (no occupancy control loop)")
    p.add_argument("--policy", choices=("continuous", "fixed"),
                   default="continuous")
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--batch-timeout-ms", type=float, default=None)
    p.add_argument("--max-inflight", type=int, default=None)
    p.add_argument("--slack-ms", type=float, default=None)
    args = p.parse_args(argv)

    if args.model:
        factory = functools.partial(_model_loader, args.model,
                                    args.tf_inputs, args.tf_outputs)
    else:
        factory = sleep_model_factory
    fleet = ServingFleet(
        factory, args.queue, workers=args.workers,
        max_workers=args.max_workers, policy=args.policy,
        batch_size=args.batch_size,
        batch_timeout_ms=args.batch_timeout_ms,
        max_inflight=args.max_inflight, slack_ms=args.slack_ms,
        autoscale=not args.no_autoscale).start()
    stop_ev = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_ev.set())
    signal.signal(signal.SIGINT, lambda *_: stop_ev.set())
    try:
        while not stop_ev.wait(1.0):
            pass
    finally:
        snap = fleet.drain()
        print(json.dumps(snap, default=str))


if __name__ == "__main__":
    main()
