"""HTTP frontend for Cluster Serving — aiohttp app mirroring the reference's
akka-http FrontEndApp (zoo/.../serving/http/FrontEndApp.scala:41: GET /,
PUT /predict with JSON instances; domain schema http/domains.scala).

POST/PUT /predict body: {"instances": [{"t": [[...]]}, ...]} — each instance's
tensors are enqueued onto the serving broker; the handler awaits results and
returns {"predictions": [...]}.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Optional

import numpy as np

from .codecs import decode_payload, encode_payload
from .queue_api import Broker, make_broker


def create_app(queue="memory://serving_stream", timeout_s: float = 30.0,
               serving=None):
    """``serving``: optional ClusterServing engine to expose under
    GET /metrics (the reference surfaces Flink numRecordsOutPerSecond +
    stage timers the same way, ClusterServingGuide:525)."""
    from aiohttp import web

    broker: Broker = make_broker(queue) if isinstance(queue, str) else queue

    async def index(request):
        return web.Response(text="welcome to analytics zoo tpu serving "
                                 "frontend")

    async def metrics(request):
        # pending() can block (Redis XLEN round-trip, spool-dir listing) —
        # keep it off the event loop like the predict handler's fetches
        loop = asyncio.get_running_loop()
        pending = await loop.run_in_executor(None, broker.pending)
        body = {"pending": pending}
        if serving is not None:
            body.update(serving.metrics())
        return web.json_response(body)

    async def predict(request):
        body = await request.json()
        instances = body.get("instances")
        if not isinstance(instances, list):
            return web.json_response({"error": "missing 'instances' list"},
                                     status=400)
        loop = asyncio.get_running_loop()
        uris = []
        for inst in instances:
            uri = uuid.uuid4().hex
            if isinstance(inst, dict):
                named = {k: np.asarray(v, dtype=np.float32)
                         for k, v in inst.items()}
                data = next(iter(named.values())) if len(named) == 1 else named
            else:
                data = np.asarray(inst, dtype=np.float32)
            broker.enqueue(uri, encode_payload(data, meta={"uri": uri}))
            uris.append(uri)

        def fetch(uri):
            raw = broker.get_result(uri, timeout_s)
            if raw is None:
                return None
            arr, meta = decode_payload(raw)
            if meta.get("error"):
                return {"error": meta["error"]}
            if isinstance(arr, (list, tuple)):
                return [a.tolist() for a in arr]
            return arr.tolist()

        results = await asyncio.gather(
            *[loop.run_in_executor(None, fetch, u) for u in uris])
        return web.json_response({"predictions": results})

    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/predict", predict)
    app.router.add_put("/predict", predict)
    return app


def run_frontend(queue="memory://serving_stream", host: str = "0.0.0.0",
                 port: int = 10020):
    from aiohttp import web
    web.run_app(create_app(queue), host=host, port=port)


def main(argv=None):
    """Console entry point (``zoo-serving``) — mirrors the reference's
    cluster-serving-start script (scripts/cluster-serving/)."""
    import argparse

    p = argparse.ArgumentParser(description="analytics-zoo-tpu serving "
                                            "HTTP frontend")
    p.add_argument("--queue", default="memory://serving_stream",
                   help="broker URI (memory://<stream> or "
                        "redis://host:port/<stream>)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=10020)
    p.add_argument("--model", default=None,
                   help="also start an embedded ClusterServing worker on "
                        "the same broker: estimator checkpoint pickle "
                        "(InferenceModel.save), SavedModel/.h5 keras model, "
                        "or an export_tf folder (frozen_inference_graph.pb "
                        "+ graph_meta.json) — single-container serving. A "
                        "bare frozen .pb needs tensor names: use "
                        "--tf-inputs/--tf-outputs")
    p.add_argument("--tf-inputs", default=None,
                   help="comma-separated input tensor names for a bare "
                        "frozen .pb (e.g. 'input:0')")
    p.add_argument("--tf-outputs", default=None,
                   help="comma-separated output tensor names for a bare "
                        "frozen .pb")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--batch-timeout-ms", type=float, default=5.0)
    args = p.parse_args(argv)

    serving = None
    if args.model:
        import os

        from ..pipeline.inference import InferenceModel
        from .engine import ClusterServing

        model = InferenceModel()
        path = args.model
        if (path.endswith(".pb") or path.endswith(".h5")
                or os.path.isdir(path)):
            model.load_tf(
                path,
                input_names=(args.tf_inputs.split(",")
                             if args.tf_inputs else None),
                output_names=(args.tf_outputs.split(",")
                              if args.tf_outputs else None))
        else:
            model.load(path)
        serving = ClusterServing(
            model, queue=args.queue, batch_size=args.batch_size,
            batch_timeout_ms=args.batch_timeout_ms).start()
    try:
        run_frontend(queue=args.queue, host=args.host, port=args.port)
    finally:
        if serving is not None:
            serving.stop()


if __name__ == "__main__":
    main()
