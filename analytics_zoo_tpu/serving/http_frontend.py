"""HTTP frontend for Cluster Serving — aiohttp app mirroring the reference's
akka-http FrontEndApp (zoo/.../serving/http/FrontEndApp.scala:41: GET /,
PUT /predict with JSON instances; domain schema http/domains.scala).

POST/PUT /predict body: {"instances": [{"t": [[...]]}, ...]} — each instance's
tensors are enqueued onto the serving broker; the handler awaits results and
returns {"predictions": [...]}. A tensor value may also be a sparse triple
{"shape": [...], "data": [...], "indices": [[...]]} (reference:
http/domains.scala:100 SparseTensor).

Transport security (reference FrontEndApp.scala:230-235 httpsEnabled +
:145-157 model-secure): ``run_frontend(ssl_certfile=, ssl_keyfile=)`` serves
HTTPS, ``auth_token`` requires ``Authorization: Bearer <token>`` on every
route but GET /, and POST /model-secure stores the secret/salt an encrypted
model artifact needs (utils/crypto.py sealed checkpoints).
"""

from __future__ import annotations

import asyncio
import hmac
import time
import uuid
from typing import Optional

import numpy as np

from ..common import knobs
from ..obs import trace as _trace
from ..obs.export import prometheus_text
from ..obs.registry import REGISTRY, InstancedEvents
from ..shm import arena_for_spec as _shm_arena_for_spec
from .codecs import (SparseTensor, decode_payload, encode_payload,
                     encode_payload_ref)
from .queue_api import Broker, make_broker


def _parse_tensor_value(v):
    """A JSON instance value: nested list (dense) or {shape,data,indices}
    (sparse, reference http/domains.scala:100)."""
    if isinstance(v, dict) and {"shape", "data", "indices"} <= set(v):
        return SparseTensor(shape=tuple(v["shape"]),
                            data=np.asarray(v["data"], np.float32),
                            indices=np.asarray(v["indices"]))
    return np.asarray(v, dtype=np.float32)


def create_app(queue="memory://serving_stream", timeout_s: float = 30.0,
               serving=None, auth_token: Optional[str] = None,
               max_pending: Optional[int] = None,
               worker_ttl_s: Optional[float] = None,
               queue_age_shed_ms: Optional[float] = None):
    """``serving``: optional ClusterServing engine to expose under
    GET /metrics (the reference surfaces Flink numRecordsOutPerSecond +
    stage timers the same way, ClusterServingGuide:525). ``auth_token``:
    when set, every route but GET / requires
    ``Authorization: Bearer <auth_token>``.

    Overload safety (resilience plane): ``max_pending`` bounds the broker
    backlog — a predict that would push it past the bound is rejected with
    429 + ``Retry-After`` *before* anything is enqueued. Every admitted
    instance carries an absolute deadline (``timeout_s``, or the request's
    ``X-Timeout-S`` header if tighter) in its payload meta; the engine
    sheds expired requests before device dispatch. ``GET /healthz`` is
    process liveness, ``GET /readyz`` flips 503 while draining or while
    the serving circuit breaker is open.

    Fleet mode (scale-out tier): with ``worker_ttl_s`` set and no
    embedded engine, this frontend is one of N doors to a worker fleet —
    ``/readyz`` 503s when the broker is unreachable or ZERO workers have
    a fresh heartbeat (an orchestrator must not route traffic into a
    stream nobody consumes), and ``metrics()`` / ``/metrics.prom``
    surface the live-worker count. ``queue_age_shed_ms`` (default: the
    ``ZOO_FLEET_QUEUE_AGE_SHED_MS`` knob; 0 disables) sheds BEFORE
    enqueue when the broker's head-of-line entry is older than the
    bound: head age lower-bounds what a new arrival will wait, so a 429
    + ``Retry-After`` now beats an answer that expires later.

    Observability (obs plane): ``GET /metrics.prom`` serves the unified
    registry as Prometheus text exposition next to the byte-compatible
    JSON body; with tracing armed (``ZOO_TRACE=1``) each predict opens a
    ``serving.request`` span whose token rides the payload meta so the
    engine's decode/batch/dispatch spans chain to it."""
    from aiohttp import web

    broker: Broker = make_broker(queue) if isinstance(queue, str) else queue
    # shm object plane: on a local ZOO_SHM-enabled stream this door writes
    # each request's raw tensor bytes into arena slabs once and enqueues
    # descriptors — the engine maps them instead of re-decoding b64(arrow)
    arena = _shm_arena_for_spec(
        queue if isinstance(queue, str) else getattr(broker, "spec", None))
    shed_age_s = float(knobs.get("ZOO_FLEET_QUEUE_AGE_SHED_MS")
                       if queue_age_shed_ms is None
                       else queue_age_shed_ms) / 1e3
    # admission counters live in the unified metrics registry (obs plane),
    # labeled per app instance so this app's JSON /metrics body still
    # starts at 0 (byte-compatible with the pre-registry per-app dict)
    # while /metrics.prom exposes the same series
    events = InstancedEvents(
        REGISTRY.counter(
            "zoo_serving_http_events_total",
            "HTTP-frontend admission events: 429 rejections (backlog "
            "bound and queue-age shed), expired results observed at "
            "fetch", labelnames=("inst", "event")),
        ("rejected_429", "expired_results", "shed_queue_age"))
    counters = events.children
    g_workers = REGISTRY.gauge(
        "zoo_serving_frontend_workers_live",
        "fleet workers with a fresh broker heartbeat, as seen from this "
        "frontend's readiness/metrics probes",
        labelnames=("inst",)).labels(inst=events.inst)

    def _live_worker_count() -> int:
        # executor-side probe (broker round trip / dir scan)
        n = len(broker.live_workers(worker_ttl_s))
        g_workers.set(n)
        return n

    async def _drop_counter_series(app):
        # app teardown drops this instance's series from the exposition so
        # rebuilt apps never leak dead-uuid series (cached children keep
        # serving the JSON view if anything still holds the app)
        events.close()
        REGISTRY.gauge("zoo_serving_frontend_workers_live",
                       labelnames=("inst",)).remove(inst=events.inst)

    @web.middleware
    async def auth_middleware(request, handler):
        # liveness/readiness probes run tokenless (orchestrator probes
        # cannot carry secrets), like GET /
        if auth_token and request.path not in ("/", "/healthz", "/readyz"):
            header = request.headers.get("Authorization", "")
            # compare as bytes: str compare_digest raises on non-ASCII
            # header values, which must 401, not 500
            ok = header.startswith("Bearer ") and hmac.compare_digest(
                header[len("Bearer "):].encode("utf-8", "surrogateescape"),
                auth_token.encode("utf-8"))
            if not ok:
                return web.json_response({"error": "unauthorized"},
                                         status=401)
        return await handler(request)

    async def index(request):
        return web.Response(text="welcome to analytics zoo tpu serving "
                                 "frontend")

    async def healthz(request):
        # liveness: the process answers — orchestrators restart on failure
        return web.json_response({"status": "ok"})

    async def readyz(request):
        # readiness: stop routing traffic here while draining (SIGTERM
        # grace window) or while the breaker has the model circuit open
        if serving is not None:
            if serving.draining:
                return web.json_response(
                    {"status": "draining"}, status=503)
            if serving.breaker.snapshot()["state"] == "open":
                return web.json_response(
                    {"status": "circuit_open"}, status=503)
        # fleet health: ready means a predict can actually complete —
        # the broker answers AND someone is consuming the stream
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(None, broker.pending)
        except Exception as e:  # noqa: BLE001 — broker down = not ready
            return web.json_response(
                {"status": "broker_unreachable", "error": str(e)},
                status=503)
        body = {"status": "ready"}
        if worker_ttl_s is not None and serving is None:
            n = await loop.run_in_executor(None, _live_worker_count)
            if n == 0:
                return web.json_response(
                    {"status": "no_workers"}, status=503)
            body["workers_live"] = n
        return web.json_response(body)

    async def metrics(request):
        # pending() can block (Redis XLEN round-trip, spool-dir listing) —
        # keep it off the event loop like the predict handler's fetches
        loop = asyncio.get_running_loop()
        pending = await loop.run_in_executor(None, broker.pending)
        from ..compile import compile_stats
        from ..resilience.stats import resilience_snapshot
        # compile-plane counters are surfaced even without an embedded
        # worker (an external worker in this process shares the cache);
        # serving.metrics() refines them with the served model's own view
        # and adds the transfer-plane snapshot ("transfer": h2d MB/s etc.)
        body = {"pending": pending, "compile": compile_stats()}
        if serving is not None:
            body.update(serving.metrics())
        # admission-layer overload counters (429 rejections, expired
        # results observed at fetch) merge into the engine's resilience
        # section; process-wide fault/retry/watchdog counters ride along
        res = dict(body.get("resilience") or {})
        res.update({k: int(c.value) for k, c in counters.items()})
        glob = resilience_snapshot()
        if glob:
            res["process"] = glob
        body["resilience"] = res
        if worker_ttl_s is not None:
            # fleet view from this door: who is consuming the stream
            try:
                live = await loop.run_in_executor(
                    None, broker.live_workers, worker_ttl_s)
            except Exception as e:  # noqa: BLE001 — broker blip
                live, body["fleet_error"] = {}, str(e)
            g_workers.set(len(live))
            body["fleet"] = {"workers_live": len(live),
                             "workers": sorted(live)}
        return web.json_response(body)

    async def metrics_prom(request):
        # Prometheus text exposition of the unified registry (obs plane):
        # every plane's counters — serving admission/engine events,
        # resilience events, compile/transfer/ckpt collector adapters —
        # next to the byte-compatible JSON body above. Serialization walks
        # in-process counters only (no broker round-trip), so it stays on
        # the event loop.
        return web.Response(text=prometheus_text(),
                            content_type="text/plain")

    async def predict(request):
        # root span of the serving trace: request → decode → batch →
        # device-dispatch → respond. Its token rides each instance's
        # payload meta so the engine's worker-thread spans chain to it.
        with _trace.span("serving.request", method=request.method):
            return await _predict(request)

    async def _predict(request):
        if serving is not None and serving.draining:
            # stop accepting during the SIGTERM grace window; admitted
            # requests are still drained to completion
            return web.json_response({"error": "draining"}, status=503,
                                     headers={"Retry-After": "5"})
        body = await request.json()
        instances = body.get("instances")
        if not isinstance(instances, list):
            return web.json_response({"error": "missing 'instances' list"},
                                     status=400)
        # multi-model multiplexing: a body-level "model" field (or the
        # X-Model header) routes every instance to one of the engine's
        # co-served models; unknown names 404 here, before anything is
        # enqueued, when an embedded engine can tell us
        model_name = body.get("model") or request.headers.get("X-Model")
        if model_name is not None and not isinstance(model_name, str):
            return web.json_response(
                {"error": f"bad 'model': {model_name!r}"}, status=400)
        if model_name and serving is not None and \
                model_name not in serving.mux:
            return web.json_response(
                {"error": f"unknown model {model_name!r}",
                 "models": sorted(serving.mux.names())}, status=404)
        loop = asyncio.get_running_loop()
        if shed_age_s > 0:
            # queue-age shed (fleet overload policy): when the stream's
            # head entry has waited longer than the bound, a new arrival
            # will wait at least that long — shed it BEFORE enqueue so
            # the backlog drains instead of compounding. Cheaper than
            # admitting work the engine will only deadline-shed later.
            age_s = await loop.run_in_executor(None, broker.oldest_age_s)
            if age_s > shed_age_s:
                counters["shed_queue_age"].inc()
                return web.json_response(
                    {"error": "queue too old",
                     "queue_age_ms": round(age_s * 1e3, 1),
                     "shed_ms": round(shed_age_s * 1e3, 1)},
                    status=429, headers={"Retry-After": "1"})
        if max_pending is not None:
            # bounded admission: reject BEFORE enqueuing anything, so an
            # overloaded broker never grows past the bound from this door.
            # Retry-After is a coarse hint: one batch-drain interval.
            backlog = await loop.run_in_executor(None, broker.pending)
            if backlog + len(instances) > max_pending:
                counters["rejected_429"].inc()
                return web.json_response(
                    {"error": "queue full", "pending": backlog,
                     "max_pending": max_pending},
                    status=429, headers={"Retry-After": "1"})
        # parse + validate EVERY instance before enqueuing any: a malformed
        # instance mid-list must 400 without having orphaned earlier
        # instances' work/results on the broker
        parsed = []
        for inst in instances:
            try:
                if isinstance(inst, dict):
                    named = {k: _parse_tensor_value(v)
                             for k, v in inst.items()}
                    parsed.append(next(iter(named.values()))
                                  if len(named) == 1 else named)
                else:
                    parsed.append(np.asarray(inst, dtype=np.float32))
            except (ValueError, TypeError) as e:
                # malformed instance (bad sparse triple, ragged list):
                # client error, not a 500
                return web.json_response(
                    {"error": f"bad instance: {e}"}, status=400)
        # deadline propagation: the engine sheds any request still queued
        # past this instant instead of wasting device time on an answer
        # nobody is waiting for. X-Timeout-S may only tighten the app-level
        # timeout — a client cannot hold a slot longer than the server
        # allows.
        eff_timeout = timeout_s
        hdr = request.headers.get("X-Timeout-S")
        if hdr:
            try:
                eff_timeout = min(timeout_s, max(float(hdr), 0.0))
            except ValueError:
                return web.json_response(
                    {"error": f"bad X-Timeout-S: {hdr!r}"}, status=400)
        deadline = time.time() + eff_timeout
        # trace handoff: the request span's token rides the payload meta so
        # the batcher thread's decode/dispatch spans chain to this request
        tok = _trace.token()
        uris = []
        items = []
        for data in parsed:
            uri = uuid.uuid4().hex
            meta = {"uri": uri, "deadline": deadline}
            if model_name:
                meta["model"] = model_name
            if tok:
                meta["trace"] = tok
            if arena is not None:
                payload, _refs = encode_payload_ref(data, meta=meta,
                                                    arena=arena)
            else:
                payload = encode_payload(data, meta=meta)
            items.append((uri, payload))
            uris.append(uri)
        # one broker batch for the whole request: the file transport pays
        # a single spool-dir fsync for N instances instead of N
        broker.publish_many(items)

        def fetch(uri):
            raw = broker.get_result(uri, eff_timeout)
            if raw is None:
                return None, False
            arr, meta = decode_payload(raw)
            if meta.get("error"):
                return ({"error": meta["error"]},
                        meta.get("shed") == "expired")
            if isinstance(arr, (list, tuple)):
                return [a.tolist() for a in arr], False
            return arr.tolist(), False

        fetched = await asyncio.gather(
            *[loop.run_in_executor(None, fetch, u) for u in uris])
        # registry children are internally locked, so this is safe from
        # any thread (the old bare-dict increment had to stay on the loop)
        n_expired = sum(exp for _, exp in fetched)
        if n_expired:
            counters["expired_results"].inc(n_expired)
        return web.json_response({"predictions": [r for r, _ in fetched]})

    async def model_secure(request):
        """Store the secret/salt an encrypted model artifact is sealed with
        (reference FrontEndApp.scala:145-157 posts them to redis; here they
        land in app state for the embedded worker / operator to read).
        Body: ``secret=xxx&salt=yyy`` like the reference (form-decoded, so
        percent-encoded secrets survive)."""
        form = await request.post()
        if "secret" not in form or "salt" not in form:
            return web.json_response(
                {"error": "please post a content like secret=xxx&salt=yyy"},
                status=400)
        # aiohttp forbids assigning new Application keys after startup —
        # mutate the dict registered before run_app instead of app["..."]
        request.app["model_secure"].update(secret=form["secret"],
                                           salt=form["salt"])
        return web.Response(text="model secured secret and salt succeed "
                                 "to put in app state")

    app = web.Application(middlewares=[auth_middleware])
    app.on_cleanup.append(_drop_counter_series)
    app["model_secure"] = {}        # mutable holder, registered pre-startup
    app.router.add_get("/", index)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/readyz", readyz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/metrics.prom", metrics_prom)
    app.router.add_post("/predict", predict)
    app.router.add_put("/predict", predict)
    app.router.add_post("/model-secure", model_secure)
    return app


def make_ssl_context(certfile: str, keyfile: str):
    """Server TLS context (reference: FrontEndApp defineServerContext over a
    PKCS12 keystore, FrontEndApp.scala:230-235; here a PEM cert/key pair)."""
    import ssl
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


def run_frontend(queue="memory://serving_stream", host: str = "0.0.0.0",
                 port: int = 10020, serving=None,
                 auth_token: Optional[str] = None,
                 ssl_certfile: Optional[str] = None,
                 ssl_keyfile: Optional[str] = None,
                 max_pending: Optional[int] = None,
                 timeout_s: float = 30.0,
                 worker_ttl_s: Optional[float] = None,
                 queue_age_shed_ms: Optional[float] = None,
                 graceful_sigterm: bool = True):
    """Serve the app. With ``graceful_sigterm`` (default), SIGTERM drains
    the embedded serving engine before the server exits — the one signal
    entry point shared with the training supervisor
    (``PreemptionWatcher(on_signal=...)``). aiohttp's own signal handlers
    are disabled in that mode: ``run_app`` would otherwise install a
    SIGTERM handler *after* ours (silently replacing it) and exit without
    draining."""
    import threading

    from aiohttp import web

    from ..orca.learn.preemption import PreemptionWatcher

    ssl_ctx = (make_ssl_context(ssl_certfile, ssl_keyfile)
               if ssl_certfile and ssl_keyfile else None)
    app = create_app(queue, timeout_s=timeout_s, serving=serving,
                     auth_token=auth_token, max_pending=max_pending,
                     worker_ttl_s=worker_ttl_s,
                     queue_age_shed_ms=queue_age_shed_ms)
    if not graceful_sigterm:
        web.run_app(app, host=host, port=port, ssl_context=ssl_ctx)
        return
    loop = asyncio.new_event_loop()

    def _graceful_exit():
        # GracefulExit is a SystemExit subclass: raising it inside a loop
        # callback breaks run_app's run_until_complete exactly like
        # aiohttp's own signal handler does
        raise web.GracefulExit()

    def _on_sigterm(signum):
        def work():
            try:
                if serving is not None:
                    serving.drain()
            finally:
                try:
                    loop.call_soon_threadsafe(_graceful_exit)
                except RuntimeError:    # loop already closed
                    pass
        # drain off the signal context: finish the admitted backlog, then
        # stop the server
        threading.Thread(target=work, daemon=True,
                         name="serving-drain").start()

    with PreemptionWatcher(on_signal=_on_sigterm):
        web.run_app(app, host=host, port=port, ssl_context=ssl_ctx,
                    loop=loop, handle_signals=False)


def main(argv=None):
    """Console entry point (``zoo-serving``) — mirrors the reference's
    cluster-serving-start script (scripts/cluster-serving/)."""
    import argparse

    p = argparse.ArgumentParser(description="analytics-zoo-tpu serving "
                                            "HTTP frontend")
    p.add_argument("--queue", default="memory://serving_stream",
                   help="broker URI (memory://<stream> or "
                        "redis://host:port/<stream>)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=10020)
    p.add_argument("--model", default=None,
                   help="also start an embedded ClusterServing worker on "
                        "the same broker: estimator checkpoint pickle "
                        "(InferenceModel.save), SavedModel/.h5 keras model, "
                        "or an export_tf folder (frozen_inference_graph.pb "
                        "+ graph_meta.json) — single-container serving. A "
                        "bare frozen .pb needs tensor names: use "
                        "--tf-inputs/--tf-outputs")
    p.add_argument("--tf-inputs", default=None,
                   help="comma-separated input tensor names for a bare "
                        "frozen .pb (e.g. 'input:0')")
    p.add_argument("--tf-outputs", default=None,
                   help="comma-separated output tensor names for a bare "
                        "frozen .pb")
    p.add_argument("--batch-size", type=int, default=None,
                   help="max records per dispatched batch (default: the "
                        "ZOO_SERVING_BATCH_SIZE knob)")
    p.add_argument("--batch-timeout-ms", type=float, default=None,
                   help="broker idle-claim poll / legacy fixed-policy "
                        "stall (default: the ZOO_SERVING_BATCH_TIMEOUT_MS "
                        "knob)")
    p.add_argument("--policy", choices=("continuous", "fixed"),
                   default="continuous",
                   help="batch former: continuous deadline-aware EDF "
                        "scheduler (default) or the legacy fixed "
                        "claim-up-to-batch-size loop")
    p.add_argument("--max-inflight", type=int, default=None,
                   help="bound on admitted in-flight requests (default: "
                        "the ZOO_SERVING_MAX_INFLIGHT knob)")
    p.add_argument("--slack-ms", type=float, default=None,
                   help="dispatch-now deadline-slack threshold (default: "
                        "the ZOO_SERVING_SLACK_MS knob)")
    p.add_argument("--max-pending", type=int, default=None,
                   help="bounded admission: reject predicts with 429 + "
                        "Retry-After once the broker backlog would exceed "
                        "this (default unbounded)")
    p.add_argument("--timeout-s", type=float, default=30.0,
                   help="per-request deadline: results are awaited this "
                        "long, and the engine sheds any request still "
                        "queued past it before device dispatch")
    p.add_argument("--worker-ttl-s", type=float, default=None,
                   help="fleet mode: /readyz 503s when no worker has a "
                        "broker heartbeat fresher than this (pair with "
                        "zoo-serving-fleet on the same --queue)")
    p.add_argument("--queue-age-shed-ms", type=float, default=None,
                   help="shed predicts with 429 before enqueue when the "
                        "broker's head-of-line entry is older than this "
                        "(default: the ZOO_FLEET_QUEUE_AGE_SHED_MS knob; "
                        "0 disables)")
    p.add_argument("--auth-token", default=None,
                   help="require 'Authorization: Bearer <token>' on every "
                        "route but GET / (reference model-secure/secured "
                        "serving, FrontEndApp.scala:145)")
    p.add_argument("--https-cert", default=None,
                   help="PEM certificate: serve HTTPS (reference "
                        "httpsEnabled, FrontEndApp.scala:230)")
    p.add_argument("--https-key", default=None,
                   help="PEM private key for --https-cert")
    args = p.parse_args(argv)
    if bool(args.https_cert) != bool(args.https_key):
        p.error("--https-cert and --https-key must be given together")

    serving = None
    if args.model:
        import os

        from ..pipeline.inference import InferenceModel
        from .engine import ClusterServing

        model = InferenceModel()
        path = args.model
        if (path.endswith(".pb") or path.endswith(".h5")
                or os.path.isdir(path)):
            model.load_tf(
                path,
                input_names=(args.tf_inputs.split(",")
                             if args.tf_inputs else None),
                output_names=(args.tf_outputs.split(",")
                              if args.tf_outputs else None))
        else:
            model.load(path)
        serving = ClusterServing(
            model, queue=args.queue, batch_size=args.batch_size,
            batch_timeout_ms=args.batch_timeout_ms, policy=args.policy,
            max_inflight=args.max_inflight,
            slack_ms=args.slack_ms).start()

    # run_frontend owns graceful SIGTERM handling: stop accepting (readyz
    # flips 503, predict 503s), finish every admitted request, flush the
    # final metrics snapshot, then exit. A second SIGTERM falls through to
    # the prior handler (force stop) via the watcher's chaining.
    try:
        run_frontend(queue=args.queue, host=args.host, port=args.port,
                     serving=serving, auth_token=args.auth_token,
                     ssl_certfile=args.https_cert,
                     ssl_keyfile=args.https_key,
                     max_pending=args.max_pending,
                     timeout_s=args.timeout_s,
                     worker_ttl_s=args.worker_ttl_s,
                     queue_age_shed_ms=args.queue_age_shed_ms)
    finally:
        if serving is not None:
            if serving.draining:
                serving.drain()     # finish in-flight before exiting
            serving.stop()


if __name__ == "__main__":
    main()
