"""Serving queue backends.

The reference's transport is Redis streams + consumer groups
(FlinkRedisSource.scala:78-104 xreadGroup; results via pipelined HSET,
FlinkRedisSink.scala:29). This module provides the same contract —
append-only input stream with group consumption + keyed result store — with
two TPU-host-friendly backends:

* InMemoryBroker  — intra-process (tests, embedded serving)
* FileBroker      — spool-directory stream + result files; works across
  processes on one host or over a shared filesystem, no external service

A Redis backend can slot in later behind the same three methods
(enqueue/claim_batch/put_result) when deployments have Redis available.
"""

from __future__ import annotations

import glob
import json
import os
import pickle
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple


class Broker:
    def enqueue(self, item_id: str, payload: bytes) -> None:
        raise NotImplementedError

    def claim_batch(self, max_items: int, timeout_s: float
                    ) -> List[Tuple[str, bytes]]:
        """Blocking claim of up to max_items; returns [] on timeout."""
        raise NotImplementedError

    def put_result(self, item_id: str, payload: bytes) -> None:
        raise NotImplementedError

    def get_result(self, item_id: str, timeout_s: float = 10.0
                   ) -> Optional[bytes]:
        raise NotImplementedError

    def pending(self) -> int:
        raise NotImplementedError


class InMemoryBroker(Broker):
    _instances: Dict[str, "InMemoryBroker"] = {}

    @classmethod
    def get(cls, name: str = "serving_stream") -> "InMemoryBroker":
        if name not in cls._instances:
            cls._instances[name] = cls()
        return cls._instances[name]

    def __init__(self):
        self._q: List[Tuple[str, bytes]] = []
        self._results: Dict[str, bytes] = {}
        self._cv = threading.Condition()

    def enqueue(self, item_id, payload):
        with self._cv:
            self._q.append((item_id, payload))
            self._cv.notify_all()

    def claim_batch(self, max_items, timeout_s):
        deadline = time.time() + timeout_s
        with self._cv:
            while not self._q:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return []
                self._cv.wait(remaining)
            batch = self._q[:max_items]
            del self._q[:len(batch)]
            return batch

    def put_result(self, item_id, payload):
        with self._cv:
            self._results[item_id] = payload
            self._cv.notify_all()

    def get_result(self, item_id, timeout_s=10.0):
        deadline = time.time() + timeout_s
        with self._cv:
            while item_id not in self._results:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)
            return self._results.pop(item_id)

    def pending(self):
        with self._cv:
            return len(self._q)


class FileBroker(Broker):
    """Spool-dir stream: input items are files under in/, claimed atomically
    by rename into claimed/, results under out/<id>."""

    def __init__(self, root: str):
        self.root = root
        for sub in ("in", "claimed", "out"):
            os.makedirs(os.path.join(root, sub), exist_ok=True)

    def enqueue(self, item_id, payload):
        tmp = os.path.join(self.root, "in", f".tmp-{uuid.uuid4().hex}")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(
            self.root, "in", f"{time.time_ns()}-{item_id}"))

    def claim_batch(self, max_items, timeout_s):
        deadline = time.time() + timeout_s
        while True:
            names = sorted(n for n in os.listdir(
                os.path.join(self.root, "in")) if not n.startswith("."))
            batch = []
            for n in names[:max_items]:
                src = os.path.join(self.root, "in", n)
                dst = os.path.join(self.root, "claimed", n)
                try:
                    os.replace(src, dst)  # atomic claim
                except OSError:
                    continue  # another worker won
                with open(dst, "rb") as f:
                    payload = f.read()
                os.unlink(dst)
                item_id = n.split("-", 1)[1]
                batch.append((item_id, payload))
            if batch or time.time() >= deadline:
                return batch
            time.sleep(0.005)

    def put_result(self, item_id, payload):
        tmp = os.path.join(self.root, "out", f".tmp-{uuid.uuid4().hex}")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(self.root, "out", item_id))

    def get_result(self, item_id, timeout_s=10.0):
        path = os.path.join(self.root, "out", item_id)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if os.path.exists(path):
                with open(path, "rb") as f:
                    data = f.read()
                os.unlink(path)
                return data
            time.sleep(0.005)
        return None

    def pending(self):
        return len([n for n in os.listdir(os.path.join(self.root, "in"))
                    if not n.startswith(".")])


def make_broker(spec: str = "memory://serving_stream") -> Broker:
    if spec.startswith("memory://"):
        return InMemoryBroker.get(spec[len("memory://"):] or "serving_stream")
    if spec.startswith("file://"):
        return FileBroker(spec[len("file://"):])
    raise ValueError(f"unknown broker spec {spec} (memory:// or file://)")
