"""Serving queue backends.

The reference's transport is Redis streams + consumer groups
(FlinkRedisSource.scala:78-104 xreadGroup; results via pipelined HSET,
FlinkRedisSink.scala:29). This module provides the same contract —
append-only input stream with group consumption + keyed result store — with
two TPU-host-friendly backends:

* InMemoryBroker  — intra-process (tests, embedded serving)
* FileBroker      — spool-directory stream + result files; works across
  processes on one host or over a shared filesystem, no external service
* RedisBroker     — the reference's actual transport: XADD onto a stream,
  XREADGROUP/XACK consumer-group claims, HSET results — over our own RESP2
  client (redis_protocol.py), so it works against real Redis or the bundled
  MiniRedisServer with no redis-py dependency.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("analytics_zoo_tpu")


class Broker:
    #: entries this consumer stole back from a dead/stalled consumer's
    #: pending set (the XAUTOCLAIM-parity counter the SIGKILL chaos gate
    #: reads: reclaimed > 0 proves redelivery, lost == 0 proves nothing
    #: fell through)
    reclaimed: int = 0

    #: the broker spec string this handle was made from (set by
    #: :func:`make_broker`); the shm object plane derives the arena every
    #: process sharing the stream agrees on from its base
    spec: Optional[str] = None

    def enqueue(self, item_id: str, payload: bytes) -> None:
        raise NotImplementedError

    def publish_many(self, items) -> None:
        """Batch enqueue of ``[(item_id, payload), ...]`` pairs. Default:
        loop over :meth:`enqueue`; transports with per-message durability
        cost override it to amortize (the file broker pays ONE spool-dir
        fsync per call instead of one per message)."""
        for item_id, payload in items:
            self.enqueue(item_id, payload)

    def claim_batch(self, max_items: int, timeout_s: float
                    ) -> List[Tuple[str, bytes]]:
        """Blocking claim of up to max_items; returns [] on timeout."""
        raise NotImplementedError

    def put_result(self, item_id: str, payload: bytes) -> None:
        raise NotImplementedError

    def get_result(self, item_id: str, timeout_s: float = 10.0
                   ) -> Optional[bytes]:
        raise NotImplementedError

    def pending(self) -> int:
        raise NotImplementedError

    def ack(self, item_id: str) -> None:
        """Acknowledge a claimed entry WITHOUT publishing a result — the
        training-stream consumption path (streaming plane): records are
        acked only after the window that trained them is durably
        committed. All three brokers now share the Redis discipline:
        claimed entries stay pending until ``put_result``/``ack``, and a
        consumer that dies mid-batch leaves them where a live consumer's
        idle-reclaim (XAUTOCLAIM parity) re-delivers them."""
        return None

    def ack_many(self, item_ids) -> None:
        """Batch form of :meth:`ack` (a streaming window commit acks its
        whole window at once; the Redis broker turns this into ONE
        XACK + ONE XDEL instead of two round trips per record)."""
        for item_id in item_ids:
            self.ack(item_id)

    # --- fleet surface (scale-out serving tier) ----------------------------
    def oldest_age_s(self) -> float:
        """Age (seconds) of the oldest entry still on the stream —
        claimed-but-unacked included — or 0.0 when empty. The frontends'
        queue-age shed reads this: head-of-line age is a lower bound on
        what a new arrival will wait, so shedding on it (429 +
        Retry-After, before enqueue) beats admitting work that will only
        expire."""
        return 0.0

    def heartbeat(self, worker_id: str,
                  stats: Optional[Dict] = None) -> None:
        """Publish worker liveness + occupancy stats through the broker
        itself (no side channel): the fleet supervisor's autoscale signal
        and the frontend ``/readyz`` live-worker count both read
        :meth:`live_workers`. Default: no-op (exotic brokers stay
        compatible)."""
        return None

    def clear_heartbeat(self, worker_id: str) -> None:
        """Drop a worker's heartbeat (graceful drain/retire — the worker
        disappears from ``live_workers`` immediately instead of aging out
        over the TTL)."""
        return None

    def live_workers(self, ttl_s: float = 3.0) -> Dict[str, Dict]:
        """``worker_id -> last heartbeat stats`` for workers whose
        heartbeat is younger than ``ttl_s``."""
        return {}


class InMemoryBroker(Broker):
    """Intra-process broker with Redis consumer-group parity: a claim
    moves entries into a shared pending set (PEL) stamped with the
    claiming consumer + claim time; ``put_result``/``ack`` releases them;
    entries idle past ``claim_idle_s`` are stolen by whichever consumer
    claims next (XAUTOCLAIM parity, counted in :attr:`reclaimed`).
    :meth:`view` returns a handle over the SAME stream under a distinct
    consumer id, so multi-consumer fleet semantics (disjoint claims,
    dead-consumer reclaim) are testable without a Redis server."""

    _instances: Dict[str, "InMemoryBroker"] = {}

    @classmethod
    def get(cls, name: str = "serving_stream") -> "InMemoryBroker":
        if name not in cls._instances:
            cls._instances[name] = cls()
        return cls._instances[name]

    def __init__(self, claim_idle_s: float = 30.0,
                 consumer: Optional[str] = None):
        # stream rows: [seq, item_id, payload, t_enq]
        self._q: List[List] = []
        # PEL rows: seq -> [item_id, payload, t_enq, consumer, t_claim]
        self._pel: Dict[int, List] = {}
        self._by_item: Dict[str, List[int]] = {}
        self._results: Dict[str, bytes] = {}
        self._hb: Dict[str, Tuple[float, Dict]] = {}
        self._cv = threading.Condition()
        self._seq = itertools.count()
        self.claim_idle_s = float(claim_idle_s)
        self.consumer = consumer or f"mem-{uuid.uuid4().hex[:8]}"
        self.reclaimed = 0

    def view(self, consumer: Optional[str] = None,
             claim_idle_s: Optional[float] = None) -> "InMemoryBroker":
        """A second consumer over the SAME stream/results/PEL (the
        in-memory analogue of two XREADGROUP connections in one group)."""
        b = object.__new__(InMemoryBroker)
        b._q = self._q
        b._pel = self._pel
        b._by_item = self._by_item
        b._results = self._results
        b._hb = self._hb
        b._cv = self._cv
        b._seq = self._seq
        b.claim_idle_s = (self.claim_idle_s if claim_idle_s is None
                          else float(claim_idle_s))
        b.consumer = consumer or f"mem-{uuid.uuid4().hex[:8]}"
        b.reclaimed = 0
        return b

    def enqueue(self, item_id, payload):
        with self._cv:
            self._q.append([next(self._seq), item_id, payload, time.time()])
            self._cv.notify_all()

    def _steal_stale(self, max_items: int) -> List[Tuple[str, bytes]]:
        # caller holds self._cv; XAUTOCLAIM parity: re-deliver entries
        # whose claim went idle (their consumer died mid-batch, or wedged)
        now = time.time()
        out = []
        for seq in sorted(self._pel):
            if len(out) >= max_items:
                break
            row = self._pel[seq]
            if now - row[4] >= self.claim_idle_s:
                row[3] = self.consumer
                row[4] = now
                out.append((row[0], row[1]))
        return out

    def claim_batch(self, max_items, timeout_s):
        deadline = time.time() + timeout_s
        # bounded waits, not one long one: a PEL entry becoming stale
        # fires no notify, so the reclaim scan must get its turn
        poll = max(min(self.claim_idle_s / 4.0, 0.05), 0.002)
        with self._cv:
            while True:
                batch = self._steal_stale(max_items)
                self.reclaimed += len(batch)
                take = self._q[:max_items - len(batch)]
                del self._q[:len(take)]
                now = time.time()
                for seq, item_id, payload, t_enq in take:
                    self._pel[seq] = [item_id, payload, t_enq,
                                      self.consumer, now]
                    self._by_item.setdefault(item_id, []).append(seq)
                    batch.append((item_id, payload))
                if batch:
                    return batch
                remaining = deadline - time.time()
                if remaining <= 0:
                    return []
                self._cv.wait(min(remaining, poll))

    def _release(self, item_id: str, all_entries: bool):
        # caller holds self._cv
        seqs = self._by_item.get(item_id)
        if not seqs:
            return
        take = seqs if all_entries else seqs[:1]
        for seq in take:
            self._pel.pop(seq, None)
        left = seqs[len(take):]
        if left:
            self._by_item[item_id] = left
        else:
            self._by_item.pop(item_id, None)

    def put_result(self, item_id, payload):
        with self._cv:
            # one entry per result, like the Redis broker: a duplicate
            # enqueue of the same uri keeps its own pending entry until
            # its own result publishes
            self._release(item_id, all_entries=False)
            self._results[item_id] = payload
            self._cv.notify_all()

    def ack(self, item_id):
        with self._cv:
            self._release(item_id, all_entries=True)

    def ack_many(self, item_ids):
        with self._cv:
            for item_id in item_ids:
                self._release(item_id, all_entries=True)

    def get_result(self, item_id, timeout_s=10.0):
        deadline = time.time() + timeout_s
        with self._cv:
            while item_id not in self._results:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)
            return self._results.pop(item_id)

    def pending(self):
        with self._cv:
            return len(self._q)

    def oldest_age_s(self):
        with self._cv:
            ts = [row[3] for row in self._q]
            ts += [row[2] for row in self._pel.values()]
        return max(0.0, time.time() - min(ts)) if ts else 0.0

    def heartbeat(self, worker_id, stats=None):
        with self._cv:
            self._hb[worker_id] = (time.time(), dict(stats or {}))

    def clear_heartbeat(self, worker_id):
        with self._cv:
            self._hb.pop(worker_id, None)

    def live_workers(self, ttl_s=3.0):
        now = time.time()
        with self._cv:
            return {w: dict(s) for w, (t, s) in self._hb.items()
                    if now - t <= ttl_s}


class FileBroker(Broker):
    """Spool-dir stream: input items are files under in/, claimed
    atomically by rename into claimed/ (kept there, named
    ``<consumer>~<entry>``, until the result publishes or the entry is
    acked — the filesystem PEL), results under out/<id>, heartbeats under
    hb/. A claimed file whose mtime goes idle past ``claim_idle_s`` is
    requeued into in/ by the next claimer (XAUTOCLAIM parity), so a
    SIGKILLed worker's in-flight entries re-deliver to survivors."""

    def __init__(self, root: str, consumer: Optional[str] = None,
                 claim_idle_s: float = 30.0, fsync: bool = True):
        self.root = root
        for sub in ("in", "claimed", "out", "hb"):
            os.makedirs(os.path.join(root, sub), exist_ok=True)
        self.consumer = consumer or f"fs-{uuid.uuid4().hex[:8]}"
        self.claim_idle_s = float(claim_idle_s)
        self.fsync = bool(fsync)
        self.reclaimed = 0
        # claimed paths per item, this handle only (the Redis broker's
        # _pending_acks twin): a crashed process loses the map but its
        # files stay in claimed/ where the idle requeue finds them
        self._claimed: Dict[str, List[str]] = {}
        self._lock = threading.Lock()

    def _stage(self, item_id, payload) -> Tuple[str, str]:
        """Write payload to a tmp spool file (fsynced when durability is
        on) and return ``(tmp, final)`` — the rename is the publish."""
        tmp = os.path.join(self.root, "in", f".tmp-{uuid.uuid4().hex}")
        with open(tmp, "wb") as f:
            f.write(payload)
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        return tmp, os.path.join(
            self.root, "in", f"{time.time_ns()}-{item_id}")

    def _fsync_in_dir(self):
        fd = os.open(os.path.join(self.root, "in"), os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def enqueue(self, item_id, payload):
        tmp, final = self._stage(item_id, payload)
        os.replace(tmp, final)
        if self.fsync:
            self._fsync_in_dir()

    def publish_many(self, items):
        """Batched spool publish: every payload staged + fsynced, every
        rename issued, then ONE directory fsync covers the whole batch —
        N-1 fewer metadata flushes than N enqueues on the transport the
        FLEET snapshot rides."""
        staged = [self._stage(item_id, payload) for item_id, payload
                  in items]
        for tmp, final in staged:
            os.replace(tmp, final)
        if self.fsync and staged:
            self._fsync_in_dir()

    def _requeue_stale(self):
        # XAUTOCLAIM parity: a claimed file idle past claim_idle_s goes
        # BACK into in/ under its original (timestamped) name, so the
        # redelivery keeps its original stream position
        cl_dir = os.path.join(self.root, "claimed")
        now = time.time()
        for n in os.listdir(cl_dir):
            if "~" not in n:
                continue
            path = os.path.join(cl_dir, n)
            try:
                idle = now - os.path.getmtime(path)
            except OSError:
                continue        # acked/requeued by another consumer
            if idle < self.claim_idle_s:
                continue
            try:
                os.replace(path, os.path.join(
                    self.root, "in", n.split("~", 1)[1]))
            except OSError:
                continue        # another consumer won the steal
            self.reclaimed += 1

    def claim_batch(self, max_items, timeout_s):
        deadline = time.time() + timeout_s
        in_dir = os.path.join(self.root, "in")
        while True:
            self._requeue_stale()
            names = sorted(n for n in os.listdir(in_dir)
                           if not n.startswith("."))
            batch = []
            for n in names[:max_items]:
                src = os.path.join(in_dir, n)
                dst = os.path.join(self.root, "claimed",
                                   f"{self.consumer}~{n}")
                try:
                    os.replace(src, dst)  # atomic claim
                except OSError:
                    continue  # another worker won
                # rename preserves mtime — restamp so idle time counts
                # from the CLAIM, not the enqueue
                os.utime(dst, None)
                with open(dst, "rb") as f:
                    payload = f.read()
                item_id = n.split("-", 1)[1]
                with self._lock:
                    self._claimed.setdefault(item_id, []).append(dst)
                batch.append((item_id, payload))
            if batch or time.time() >= deadline:
                return batch
            time.sleep(0.005)

    def _unlink_claimed(self, item_id: str, all_entries: bool):
        with self._lock:
            paths = self._claimed.get(item_id)
            if not paths:
                return
            take = list(paths) if all_entries else paths[:1]
            left = paths[len(take):]
            if left:
                self._claimed[item_id] = left
            else:
                del self._claimed[item_id]
        for path in take:
            try:
                os.unlink(path)
            except OSError:
                # requeued by another consumer after our claim went
                # idle — the redelivery owns the entry now
                logger.debug("file broker: claimed entry %s already "
                             "requeued", path)

    def put_result(self, item_id, payload):
        tmp = os.path.join(self.root, "out", f".tmp-{uuid.uuid4().hex}")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(self.root, "out", item_id))
        self._unlink_claimed(item_id, all_entries=False)

    def ack(self, item_id):
        self._unlink_claimed(item_id, all_entries=True)

    def ack_many(self, item_ids):
        for item_id in item_ids:
            self._unlink_claimed(item_id, all_entries=True)

    def get_result(self, item_id, timeout_s=10.0):
        path = os.path.join(self.root, "out", item_id)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if os.path.exists(path):
                with open(path, "rb") as f:
                    data = f.read()
                os.unlink(path)
                return data
            time.sleep(0.005)
        return None

    def pending(self):
        return len([n for n in os.listdir(os.path.join(self.root, "in"))
                    if not n.startswith(".")])

    def oldest_age_s(self):
        oldest = None
        for sub in ("in", "claimed"):
            for n in os.listdir(os.path.join(self.root, sub)):
                if n.startswith("."):
                    continue
                base = n.split("~", 1)[1] if "~" in n else n
                try:
                    ts = int(base.split("-", 1)[0]) / 1e9
                except ValueError:
                    continue
                oldest = ts if oldest is None else min(oldest, ts)
        return max(0.0, time.time() - oldest) if oldest is not None else 0.0

    def heartbeat(self, worker_id, stats=None):
        doc = dict(stats or {})
        doc["t"] = time.time()
        tmp = os.path.join(self.root, "hb", f".tmp-{uuid.uuid4().hex}")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, os.path.join(self.root, "hb", worker_id))

    def clear_heartbeat(self, worker_id):
        try:
            os.unlink(os.path.join(self.root, "hb", worker_id))
        except OSError:
            logger.debug("file broker: heartbeat %s already gone",
                         worker_id)

    def live_workers(self, ttl_s=3.0):
        hb_dir = os.path.join(self.root, "hb")
        now = time.time()
        out = {}
        for n in os.listdir(hb_dir):
            if n.startswith("."):
                continue
            path = os.path.join(hb_dir, n)
            try:
                if now - os.path.getmtime(path) > ttl_s:
                    continue
                with open(path) as f:
                    out[n] = json.load(f)
            except (OSError, ValueError):
                continue        # mid-replace or torn read: not live yet
        return out


class RedisBroker(Broker):
    """Redis-streams transport (reference: FlinkRedisSource.scala:78-104).

    Input records are XADDed to ``<stream>`` with fields ``uri``/``data``;
    the engine side claims them with XREADGROUP on consumer group ``group``
    and XACKs/XDELs only after the result is published (``put_result``), so
    a worker that crashes mid-inference leaves its claims in the group PEL
    where XAUTOCLAIM steals them — at-least-once delivery end to end.
    Results go to hash ``result:<id>`` field
    ``value`` (reference sink pipelines HSETs, FlinkRedisSink.scala:29) and
    are deleted on read, matching the reference client's get-then-forget
    polling loop (pyzoo client.py:250-282).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 stream: str = "serving_stream", group: str = "serving",
                 consumer: Optional[str] = None,
                 claim_idle_ms: int = 30000,
                 retry_policy=None):
        from ..resilience.retry import RetryPolicy
        from .redis_protocol import RedisClient, RedisError
        self._RedisClient = RedisClient
        self._RedisError = RedisError
        # broker-loss resilience: a dropped/refused connection is retried
        # through the shared RetryPolicy (reconnect happens inside
        # RedisClient on the next call) instead of surfacing a raw
        # ConnectionError to the serving worker loop. Stream semantics stay
        # at-least-once: a retried XADD may duplicate an entry whose reply
        # was lost, a retried XREADGROUP's lost claims land in the PEL
        # where XAUTOCLAIM recovers them, HSET results are idempotent.
        # the knob counts RETRIES (what its name says); max_attempts is
        # total tries, so +1 — RETRIES=1 means one reconnect, not none
        self._retry = retry_policy if retry_policy is not None else \
            RetryPolicy(
                max_attempts=1 + max(0, int(os.environ.get(
                    "ZOO_BROKER_RECONNECT_RETRIES", "4"))),
                base_delay_s=float(os.environ.get(
                    "ZOO_BROKER_RECONNECT_BACKOFF_S", "0.2")),
                max_delay_s=5.0, jitter_frac=0.1,
                transient=(ConnectionError, TimeoutError, OSError),
                name="broker.connect")
        self.host, self.port = host, port
        self.stream = stream.encode()
        self.group = group.encode()
        self.consumer = (consumer or f"cs-{uuid.uuid4().hex[:8]}").encode()
        # one connection per calling thread: blocking XREADGROUP claims from
        # one serving worker must not serialize the other workers (or
        # put_result calls) behind a shared socket lock
        self._tls = threading.local()
        self._clients: List = []
        self._clients_lock = threading.Lock()
        # stale-pending recovery: a consumer that died between XREADGROUP
        # and XACK leaves its entries in the group PEL forever (they are
        # past the group's last-delivered id, so '>' never re-delivers).
        # Periodic XAUTOCLAIM steals entries idle >= claim_idle_ms back to
        # a live consumer, restoring at-least-once delivery.
        self._claim_idle_ms = claim_idle_ms
        self._last_autoclaim = 0.0
        # entry ids claimed but not yet acked: acked/deleted only after the
        # result is published (put_result), so a worker that dies mid-batch
        # leaves its entries in the group PEL where XAUTOCLAIM can steal them
        self._pending_acks: Dict[str, List[bytes]] = {}
        self._pending_lock = threading.Lock()
        self.reclaimed = 0
        self._hb_key = b"fleet:" + self.stream + b":hb"
        try:
            # the connect itself must ride the retry policy too (not just
            # the command): _conn() evaluated as an argument would put the
            # first connection OUTSIDE the backoff loop, so a broker
            # coming up just after a restart would fail construction
            self._retry.call(
                lambda: self._conn().execute(
                    "XGROUP", "CREATE", self.stream, self.group, "0",
                    "MKSTREAM"))
        except RedisError as e:
            if "BUSYGROUP" not in str(e):
                raise

    def _conn(self):
        c = getattr(self._tls, "client", None)
        if c is None:
            c = self._RedisClient(self.host, self.port)
            self._tls.client = c
            with self._clients_lock:
                self._clients.append(c)
        return c

    def enqueue(self, item_id, payload):
        self._retry.call(self._conn().execute, "XADD", self.stream, "*",
                         "uri", item_id, "data", payload)

    def claim_batch(self, max_items, timeout_s):
        # reconnect-with-backoff around the whole claim: lost claims whose
        # reply vanished sit in the PEL until XAUTOCLAIM steals them back,
        # so a retry cannot drop work
        return self._retry.call(self._claim_batch, max_items, timeout_s)

    def _claim_batch(self, max_items, timeout_s):
        # BLOCK 0 means "block forever" on real Redis — clamp to >=1ms so a
        # zero/sub-ms timeout stays a poll, matching the other brokers
        block_ms = max(1, int(timeout_s * 1000))
        c = self._conn()
        batch, ids = [], []
        now = time.time()
        if now - self._last_autoclaim > self._claim_idle_ms / 2000.0:
            self._last_autoclaim = now
            try:
                stolen = c.execute(
                    "XAUTOCLAIM", self.stream, self.group, self.consumer,
                    self._claim_idle_ms, "0-0", "COUNT", max_items)
                for eid, fields in (stolen[1] if stolen else []):
                    kv = {fields[i]: fields[i + 1]
                          for i in range(0, len(fields), 2)}
                    batch.append((kv[b"uri"].decode(), kv[b"data"]))
                    ids.append(eid)
                    self.reclaimed += 1
            except self._RedisError:
                pass  # pre-6.2 Redis has no XAUTOCLAIM; skip recovery
        if len(batch) < max_items:
            # read fresh entries even when XAUTOCLAIM returned some: a
            # consumer configured with a small claim_idle_ms (streaming
            # restart recovery) would otherwise re-steal the same pending
            # entries every poll and STARVE the new-traffic read — stolen
            # entries merge ahead of fresh ones (PEL order, then stream
            # order), the order a replay reproduces
            reply = c.execute(
                "XREADGROUP", "GROUP", self.group, self.consumer,
                "COUNT", max_items - len(batch),
                "BLOCK", 1 if batch else block_ms,
                "STREAMS", self.stream, ">",
                timeout_s=timeout_s + 5.0)
            for _key, entries in (reply or []):
                for eid, fields in entries:
                    kv = {fields[i]: fields[i + 1]
                          for i in range(0, len(fields), 2)}
                    batch.append((kv[b"uri"].decode(), kv[b"data"]))
                    ids.append(eid)
        if not batch:
            return []
        if ids:
            with self._pending_lock:
                for (item_id, _), eid in zip(batch, ids):
                    self._pending_acks.setdefault(item_id, []).append(eid)
        return batch

    def put_result(self, item_id, payload):
        return self._retry.call(self._put_result, item_id, payload)

    def _put_result(self, item_id, payload):
        c = self._conn()
        c.execute("HSET", b"result:" + item_id.encode(), "value", payload)
        # ack + trim only now that the result is durably published; entries
        # for crashed workers stay in the PEL until XAUTOCLAIM steals them.
        # One entry per call: if the same uri was enqueued twice, each copy's
        # ack waits for its own result, preserving at-least-once per entry.
        with self._pending_lock:
            eids = self._pending_acks.get(item_id)
            eid = eids.pop(0) if eids else None
            if eids is not None and not eids:
                del self._pending_acks[item_id]
        if eid is not None:
            c.execute("XACK", self.stream, self.group, eid)
            c.execute("XDEL", self.stream, eid)

    def ack(self, item_id):
        """Resultless acknowledgement (streaming consumption): XACK + XDEL
        every pending entry claimed under ``item_id``. All entries, not
        one — a replayed/XAUTOCLAIM-stolen duplicate of the same record
        must not leave a phantom forever-pending entry behind."""
        self.ack_many([item_id])

    def ack_many(self, item_ids):
        self._retry.call(self._ack_all, list(item_ids))

    def _ack_all(self, item_ids):
        # eids leave _pending_acks only AFTER the server acknowledged
        # them: popping first would make a transient-failure retry find
        # nothing to ack and "succeed", leaving the entries pending in
        # the PEL forever (the same argument-evaluation trap the
        # constructor's retry fixes). XACK/XDEL are idempotent, so a
        # retry that re-sends already-acked ids is harmless.
        with self._pending_lock:
            eids = [e for i in item_ids
                    for e in self._pending_acks.get(i, ())]
        if not eids:
            return
        c = self._conn()
        # one XACK + one XDEL for the whole batch (a 1024-record window
        # commit is 2 round trips, not 2048)
        c.execute("XACK", self.stream, self.group, *eids)
        c.execute("XDEL", self.stream, *eids)
        done = set(eids)
        with self._pending_lock:
            for i in item_ids:
                cur = self._pending_acks.get(i)
                if not cur:
                    continue
                left = [e for e in cur if e not in done]
                if left:
                    self._pending_acks[i] = left
                else:
                    del self._pending_acks[i]

    def get_result(self, item_id, timeout_s=10.0):
        key = b"result:" + item_id.encode()
        deadline = time.time() + timeout_s
        while True:
            # HGET/DEL are idempotent — each poll rides the reconnect
            # policy individually so the deadline math stays honest
            val = self._retry.call(self._conn().execute, "HGET", key,
                                   "value")
            if val is not None:
                self._retry.call(self._conn().execute, "DEL", key)
                return val
            if time.time() >= deadline:
                return None
            time.sleep(0.005)

    def pending(self):
        """Backlog = stream length minus claimed-but-unacked entries, so it
        means the same thing as the other brokers' pending() (entries now
        stay in the stream until their result publishes)."""
        return self._retry.call(self._pending)

    def _pending(self):
        c = self._conn()
        backlog = int(c.execute("XLEN", self.stream))
        try:
            p = c.execute("XPENDING", self.stream, self.group)
            in_flight = int(p[0]) if p else 0
        except self._RedisError:
            in_flight = 0
        return max(backlog - in_flight, 0)

    def oldest_age_s(self):
        return self._retry.call(self._oldest_age_s)

    def _oldest_age_s(self):
        reply = self._conn().execute(
            "XRANGE", self.stream, "-", "+", "COUNT", 1)
        if not reply:
            return 0.0
        eid = reply[0][0]
        ms = int(eid.split(b"-", 1)[0])
        return max(0.0, time.time() - ms / 1000.0)

    def heartbeat(self, worker_id, stats=None):
        doc = dict(stats or {})
        doc["t"] = time.time()
        self._retry.call(self._conn().execute, "HSET", self._hb_key,
                         worker_id, json.dumps(doc))

    def clear_heartbeat(self, worker_id):
        self._retry.call(self._conn().execute, "HDEL", self._hb_key,
                         worker_id)

    def live_workers(self, ttl_s=3.0):
        flat = self._retry.call(self._conn().execute, "HGETALL",
                                self._hb_key) or []
        now = time.time()
        out = {}
        for i in range(0, len(flat), 2):
            try:
                doc = json.loads(flat[i + 1])
            except ValueError:
                continue
            if now - float(doc.get("t", 0.0)) <= ttl_s:
                out[flat[i].decode()] = doc
        return out

    def close(self):
        with self._clients_lock:
            clients, self._clients = self._clients, []
        for c in clients:
            c.close()


class PartitionedBroker(Broker):
    """Producer-side fan-out over N keyed sub-streams of one broker spec.

    ``make_broker("redis://h:p/s?partitions=4")`` returns one of these:
    :meth:`enqueue` routes each record to sub-stream ``s.p{k}`` by its
    routing key (``streaming.records.record_key``, CRC32-hashed — the
    same deterministic hash every consumer uses), falling back to the
    item id for keyless payloads, so all records of one key land on ONE
    partition in stream order — the invariant that keeps per-partition
    cursors and bit-exact replay meaningful at fleet scale. Consumers do
    NOT go through this class: each fleet trainer opens its own
    ``...?partition=k`` sub-broker and claims only its shard (disjoint by
    construction — different partitions are different streams).

    The aggregate read surface (:meth:`pending`, :meth:`oldest_age_s`,
    :meth:`live_workers`) merges across partitions so supervisors and
    frontends see whole-stream numbers; :meth:`claim_batch` round-robins
    the partitions (a single-consumer reader of a partitioned stream,
    used by coverage tests and drain tooling, not the fleet hot path).
    """

    def __init__(self, parts: List[Broker],
                 partition_by: Optional[str] = None):
        if not parts:
            raise ValueError("PartitionedBroker needs >= 1 partition")
        from ..common import knobs as _knobs
        self.parts = list(parts)
        self.partition_by = str(
            partition_by if partition_by is not None
            else _knobs.get("ZOO_STREAM_PARTITION_BY"))
        if self.partition_by not in ("key", "id"):
            raise ValueError(
                f"partition_by must be 'key' or 'id', "
                f"got {self.partition_by!r}")
        self._rr = 0

    @property
    def n_partitions(self) -> int:
        return len(self.parts)

    @property
    def reclaimed(self) -> int:
        # derived, read-only: the per-partition consumers own the counts
        return sum(int(getattr(p, "reclaimed", 0)) for p in self.parts)

    def partition_of(self, item_id: str, payload: bytes) -> int:
        """Partition index a record routes to: the record's stamped key
        when it carries one, else the item id (both through the same
        process-stable CRC32 hash)."""
        # lazy import: streaming.records is leaf-level, but importing the
        # streaming package from this module's top level would cycle back
        # through streaming.source -> queue_api
        from ..streaming.records import partition_for, record_key
        key = None
        if self.partition_by == "key":
            # header-only, copy-free: record_key accepts any buffer and
            # reads just the magic + JSON header; descriptor envelopes
            # (ZSHM1) carry the key in the envelope header
            head = bytes(memoryview(payload)[:5])
            if head[:4] == b"ZSR1" or head == b"ZSHM1":
                try:
                    key = record_key(payload)
                except ValueError:
                    key = None
        return partition_for(key if key is not None else item_id,
                             len(self.parts))

    def enqueue(self, item_id, payload):
        self.parts[self.partition_of(item_id, payload)].enqueue(
            item_id, payload)

    def publish_many(self, items):
        # group by partition so each sub-broker sees one batch (the file
        # transport then pays one dir fsync per partition, not per item)
        groups: Dict[int, List] = {}
        for item_id, payload in items:
            groups.setdefault(
                self.partition_of(item_id, payload), []).append(
                    (item_id, payload))
        for k, group in groups.items():
            self.parts[k].publish_many(group)

    def claim_batch(self, max_items, timeout_s):
        deadline = time.time() + timeout_s
        while True:
            for i in range(len(self.parts)):
                part = self.parts[(self._rr + i) % len(self.parts)]
                batch = part.claim_batch(max_items, 0.0)
                if batch:
                    self._rr = (self._rr + i + 1) % len(self.parts)
                    return batch
            if time.time() >= deadline:
                return []
            time.sleep(0.005)

    def ack(self, item_id):
        # the router knows where a PAYLOAD goes, not where an id was
        # claimed; ack is idempotent on every transport, so fan it out
        for p in self.parts:
            p.ack(item_id)

    def ack_many(self, item_ids):
        ids = list(item_ids)
        for p in self.parts:
            p.ack_many(ids)

    def put_result(self, item_id, payload):
        from ..streaming.records import partition_for
        self.parts[partition_for(item_id, len(self.parts))].put_result(
            item_id, payload)

    def get_result(self, item_id, timeout_s=10.0):
        from ..streaming.records import partition_for
        return self.parts[partition_for(
            item_id, len(self.parts))].get_result(item_id, timeout_s)

    def pending(self):
        return sum(p.pending() for p in self.parts)

    def oldest_age_s(self):
        return max((p.oldest_age_s() for p in self.parts), default=0.0)

    def heartbeat(self, worker_id, stats=None):
        self.parts[0].heartbeat(worker_id, stats)

    def clear_heartbeat(self, worker_id):
        self.parts[0].clear_heartbeat(worker_id)

    def live_workers(self, ttl_s=3.0):
        out: Dict[str, Dict] = {}
        for p in self.parts:
            out.update(p.live_workers(ttl_s))
        return out

    def close(self):
        for p in self.parts:
            close = getattr(p, "close", None)
            if close is not None:
                close()


def partitioned_spec(spec: str, partition: int) -> str:
    """``spec`` narrowed to one partition's sub-stream — the string a
    fleet supervisor hands each consumer process (query params carried by
    the base spec, e.g. ``claim_idle_ms``, ride along)."""
    base, _, query = spec.partition("?")
    keep = [kv for kv in query.split("&")
            if kv and kv.split("=", 1)[0] not in ("partition", "partitions")]
    keep.append(f"partition={int(partition)}")
    return base + "?" + "&".join(keep)


def make_broker(spec: str = "memory://serving_stream") -> Broker:
    """Broker factory: ``memory://<stream>``, ``file://<dir>``, or
    ``redis://host:port/<stream>`` (stream defaults to serving_stream).

    An optional ``?k=v`` query configures the transport — it rides the
    spec string so every fleet process (supervisor, spawned workers,
    frontends) that shares the spec shares the configuration:

    * ``claim_idle_s`` (memory/file) / ``claim_idle_ms`` (redis) — the
      idle threshold past which a live consumer steals a dead consumer's
      pending entries;
    * ``partition=k`` — open partition ``k``'s keyed sub-stream (memory:
      ``<name>.p<k>``; file: ``<dir>/p<k>``; redis: ``<stream>.p<k>`` —
      the same naming on all three transports, so tests move freely
      between them). This is the consumer-side handle: a fleet trainer
      claims only its shard;
    * ``partitions=N`` — the producer-side fan-out: a
      :class:`PartitionedBroker` routing each record onto one of the N
      sub-streams by its stamped key (id hash for keyless payloads).

    ``partition`` and ``partitions`` are mutually exclusive (a handle is
    either one shard or the router over all of them)."""
    spec_full = spec
    spec, _, query = spec.partition("?")
    params: Dict[str, str] = {}
    if query:
        for kv in query.split("&"):
            k, _, v = kv.partition("=")
            if k:
                params[k] = v

    for prefix in ("memory://", "file://", "redis://"):
        if spec.startswith(prefix):
            transport = prefix[:-3]
            break
    else:
        raise ValueError(f"unknown broker spec {spec} "
                         "(memory:// file:// or redis://)")

    def _int_param(name: str, minimum: int) -> Optional[int]:
        raw = params.get(name)
        if raw is None:
            return None
        try:
            v = int(raw)
        except ValueError:
            raise ValueError(
                f"{transport} broker: ?{name}={raw!r} is not an integer "
                f"(spec {spec_full!r})") from None
        if v < minimum:
            raise ValueError(
                f"{transport} broker: ?{name}={v} must be >= {minimum} "
                f"(spec {spec_full!r})")
        return v

    partition = _int_param("partition", 0)
    partitions = _int_param("partitions", 1)
    if partition is not None and partitions is not None:
        raise ValueError(
            f"{transport} broker: ?partition= (one shard) and "
            f"?partitions= (the fan-out router) are mutually exclusive "
            f"(spec {spec_full!r})")
    if partitions is not None:
        b: Broker = PartitionedBroker(
            [make_broker(partitioned_spec(spec_full, k))
             for k in range(partitions)])
        b.spec = spec_full
        return b

    if transport == "memory":
        name = spec[len("memory://"):] or "serving_stream"
        if partition is not None:
            name = f"{name}.p{partition}"
        b = InMemoryBroker.get(name)
        if "claim_idle_s" in params:
            b.claim_idle_s = float(params["claim_idle_s"])
        b.spec = spec_full
        return b
    if transport == "file":
        root = spec[len("file://"):]
        if partition is not None:
            root = os.path.join(root, f"p{partition}")
        b = FileBroker(
            root, claim_idle_s=float(params.get("claim_idle_s", 30.0)),
            fsync=params.get("fsync", "1") not in ("0", "false", "no"))
        b.spec = spec_full
        return b
    rest = spec[len("redis://"):]
    hostport, _, stream = rest.partition("/")
    host, _, port = hostport.partition(":")
    stream = stream or "serving_stream"
    if partition is not None:
        stream = f"{stream}.p{partition}"
    b = RedisBroker(host or "127.0.0.1", int(port or 6379), stream,
                    claim_idle_ms=int(
                        params.get("claim_idle_ms", 30000)))
    b.spec = spec_full
    return b
