"""Minimal Redis wire protocol (RESP2) client + embeddable mini-server.

The reference's Cluster Serving transport is Redis streams with consumer
groups: ingestion XADDs records onto a stream, the serving engine claims them
via XREADGROUP/XACK, and results land in per-item hashes via pipelined HSET
(reference: serving/engine/FlinkRedisSource.scala:78-104,
FlinkRedisSink.scala:29, pyzoo/zoo/serving/client.py:82-282).

This module supplies the same transport with zero external dependencies:

* ``RedisClient`` — a RESP2 socket client speaking exactly the command subset
  the broker needs (XADD/XREADGROUP/XACK/XGROUP/XLEN/HSET/HGETALL/DEL/PING).
  It talks to any real Redis server.
* ``MiniRedisServer`` — a pure-Python, threaded RESP2 server implementing the
  same subset, so multi-process serving works on hosts with no Redis
  installed (and tests exercise the real wire path).

Design note: the client is deliberately not a general Redis library — every
command is a list of byte-string arguments encoded as a RESP array, and
replies are parsed into bytes/int/list/None. That is all the broker contract
requires.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..resilience import faults as _faults

_CRLF = b"\r\n"


# --------------------------------------------------------------------------
# RESP2 encoding / decoding
# --------------------------------------------------------------------------

def encode_command(*args) -> bytes:
    """Encode a command as a RESP array of bulk strings."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        elif isinstance(a, (int, float)):
            a = str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


class _Reader:
    """Incremental RESP parser over a socket (blocking)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def _fill(self):
        chunk = self._sock.recv(65536)
        if not chunk:
            raise ConnectionError("redis connection closed")
        self._buf += chunk

    def _read_line(self) -> bytes:
        while True:
            i = self._buf.find(_CRLF)
            if i >= 0:
                line, self._buf = self._buf[:i], self._buf[i + 2:]
                return line
            self._fill()

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            self._fill()
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n < 0 else self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self.read_reply() for _ in range(n)]
        raise RedisError(f"bad RESP type byte {kind!r}")


class RedisError(Exception):
    pass


class RedisClient:
    """Thread-safe RESP2 client (one socket, command lock)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout_s: float = 30.0):
        self.host, self.port = host, port
        self._timeout = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[_Reader] = None
        self._connect()

    def _connect(self):
        _faults.fire("broker.connect")  # chaos hook: model a dead broker
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = socket.create_connection((self.host, self.port),
                                              timeout=self._timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = _Reader(self._sock)

    def execute(self, *args, timeout_s: Optional[float] = None):
        """Send one command and return its reply.

        On a connection failure the socket is re-established for the NEXT
        call and the error re-raised — we never silently re-send, because a
        command like XADD may have executed server-side before the reply was
        lost, and a blind retry would duplicate it. Callers with idempotent
        commands (result polling loops) retry at their level.
        """
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                self._sock.settimeout(
                    timeout_s if timeout_s is not None else self._timeout)
                self._sock.sendall(encode_command(*args))
                return self._reader.read_reply()
            except (ConnectionError, OSError):
                try:
                    self._connect()
                except OSError:
                    self._sock = None  # reconnect again on next call
                raise

    def close(self):
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def ping(self) -> bool:
        return self.execute("PING") == b"PONG"


# --------------------------------------------------------------------------
# Embeddable mini Redis server (streams + hashes subset)
# --------------------------------------------------------------------------

class _Stream:
    def __init__(self):
        self.entries: List[Tuple[bytes, List[bytes]]] = []  # (id, fields)
        self.seq = 0
        self.groups: Dict[bytes, Dict] = {}  # name -> {"next": idx, "pel": {}}


class _State:
    def __init__(self):
        self.streams: Dict[bytes, _Stream] = {}
        self.hashes: Dict[bytes, Dict[bytes, bytes]] = {}
        self.cv = threading.Condition()


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        st: _State = self.server.state  # type: ignore[attr-defined]
        reader = _Reader(self.request)
        while True:
            try:
                cmd = reader.read_reply()
            except (ConnectionError, OSError):
                return
            if not isinstance(cmd, list) or not cmd:
                self._send(b"-ERR protocol error\r\n")
                continue
            name = cmd[0].upper()
            try:
                fn = getattr(self, "_cmd_" + name.decode().lower(), None)
                if fn is None:
                    self._send(b"-ERR unknown command '%s'\r\n" % name)
                else:
                    fn(st, cmd[1:])
            except (ConnectionError, OSError):
                return
            except Exception as e:  # command bug → error reply, keep serving
                self._send(b"-ERR %s\r\n" % str(e).encode())

    # --- reply helpers ---
    def _send(self, raw: bytes):
        self.request.sendall(raw)

    def _simple(self, s: bytes):
        self._send(b"+%s\r\n" % s)

    def _int(self, n: int):
        self._send(b":%d\r\n" % n)

    def _bulk(self, b: Optional[bytes]):
        if b is None:
            self._send(b"$-1\r\n")
        else:
            self._send(b"$%d\r\n%s\r\n" % (len(b), b))

    def _array(self, items):
        if items is None:
            self._send(b"*-1\r\n")
            return
        self._send(b"*%d\r\n" % len(items))
        for it in items:
            if isinstance(it, list):
                self._array(it)
            elif isinstance(it, int):
                self._int(it)
            else:
                self._bulk(it)

    # --- commands ---
    def _cmd_ping(self, st, args):
        self._simple(b"PONG")

    def _cmd_xadd(self, st, args):
        key, eid, fields = args[0], args[1], args[2:]
        with st.cv:
            s = st.streams.setdefault(key, _Stream())
            if eid == b"*":
                s.seq += 1
                eid = b"%d-%d" % (int(time.time() * 1000), s.seq)
            s.entries.append((eid, list(fields)))
            st.cv.notify_all()
        self._bulk(eid)

    @staticmethod
    def _id_key(eid: bytes):
        ms, _, seq = eid.partition(b"-")
        return (int(ms), int(seq or b"0"))

    def _cmd_xrange(self, st, args):
        # XRANGE key start end [COUNT n] — enough for the brokers'
        # head-of-line age probe (start '-', end '+', COUNT 1)
        key, start, end = args[0], args[1], args[2]
        count = None
        for i, a in enumerate(args[3:]):
            if a.upper() == b"COUNT":
                count = int(args[3 + i + 1])
        lo = None if start == b"-" else self._id_key(start)
        hi = None if end == b"+" else self._id_key(end)
        out = []
        with st.cv:
            s = st.streams.get(key)
            for e in (s.entries if s else []):
                if e is None:
                    continue
                k = self._id_key(e[0])
                if (lo is None or k >= lo) and (hi is None or k <= hi):
                    out.append([e[0], list(e[1])])
                    if count is not None and len(out) >= count:
                        break
        self._array(out)

    def _cmd_xlen(self, st, args):
        with st.cv:
            s = st.streams.get(args[0])
            n = sum(e is not None for e in s.entries) if s else 0
        self._int(n)

    def _cmd_xgroup(self, st, args):
        sub = args[0].upper()
        if sub != b"CREATE":
            raise ValueError("only XGROUP CREATE supported")
        key, group, start = args[1], args[2], args[3]
        mkstream = any(a.upper() == b"MKSTREAM" for a in args[4:])
        with st.cv:
            s = st.streams.get(key)
            if s is None:
                if not mkstream:
                    self._send(b"-ERR The XGROUP subcommand requires the key"
                               b" to exist\r\n")
                    return
                s = st.streams.setdefault(key, _Stream())
            if group in s.groups:
                self._send(b"-BUSYGROUP Consumer Group name already "
                           b"exists\r\n")
                return
            nxt = 0 if start == b"0" else len(s.entries)
            s.groups[group] = {"next": nxt, "pel": {}}
        self._simple(b"OK")

    def _cmd_xreadgroup(self, st, args):
        # XREADGROUP GROUP g c [COUNT n] [BLOCK ms] STREAMS key >
        it = iter(args)
        group = consumer = None
        count, block_ms, keys = 1, None, []
        tok = next(it)
        while True:
            u = tok.upper()
            if u == b"GROUP":
                group, consumer = next(it), next(it)
            elif u == b"COUNT":
                count = int(next(it))
            elif u == b"BLOCK":
                block_ms = int(next(it))
            elif u == b"STREAMS":
                keys = list(it)
                break
            try:
                tok = next(it)
            except StopIteration:
                break
        key = keys[0]  # single-stream use only
        # Redis semantics: no BLOCK → return immediately; BLOCK 0 → forever
        deadline = None
        if block_ms is None:
            deadline = time.time()
        elif block_ms > 0:
            deadline = time.time() + block_ms / 1000.0
        reply = error = None
        with st.cv:
            while True:
                s = st.streams.get(key)
                g = s.groups.get(group) if s else None
                if g is None:
                    error = b"-NOGROUP No such consumer group\r\n"
                    break
                avail = len(s.entries) - g["next"]
                if avail > 0:
                    take = min(avail, count)
                    window = s.entries[g["next"]:g["next"] + take]
                    ents = [e for e in window if e is not None]
                    g["next"] += take
                    now = time.time()
                    for eid, _ in ents:
                        g["pel"][eid] = (consumer, now)
                    reply = [[key, [[eid, f] for eid, f in ents]]]
                    break
                if deadline is not None and time.time() >= deadline:
                    break
                st.cv.wait(None if deadline is None
                           else max(0.0, deadline - time.time()))
        # send outside the state lock: a slow client draining a large reply
        # must not stall every other connection
        if error is not None:
            self._send(error)
        else:
            self._array(reply)

    def _cmd_xautoclaim(self, st, args):
        # XAUTOCLAIM key group consumer min-idle-time start [COUNT n]
        key, group, consumer, min_idle_ms = args[0], args[1], args[2], \
            int(args[3])
        count = 100
        rest = args[5:]
        for i, a in enumerate(rest):
            if a.upper() == b"COUNT":
                count = int(rest[i + 1])
        claimed = []
        with st.cv:
            s = st.streams.get(key)
            g = s.groups.get(group) if s else None
            if g is None:
                pass
            else:
                now = time.time()
                by_id = {e[0]: e[1] for e in s.entries if e is not None}
                for eid in list(g["pel"]):
                    owner, t = g["pel"][eid]
                    if (now - t) * 1000 < min_idle_ms:
                        continue
                    fields = by_id.get(eid)
                    if fields is None:      # XDELed while pending
                        del g["pel"][eid]
                        continue
                    g["pel"][eid] = (consumer, now)
                    claimed.append([eid, fields])
                    if len(claimed) >= count:
                        break
        self._array([b"0-0", claimed])

    def _cmd_xdel(self, st, args):
        """Tombstone entries, then drop the consumed prefix (the broker XDELs
        in claim order, so acked history compacts away and memory stays
        bounded)."""
        key, ids = args[0], set(args[1:])
        n = 0
        with st.cv:
            s = st.streams.get(key)
            if s:
                for i, e in enumerate(s.entries):
                    if e is not None and e[0] in ids:
                        s.entries[i] = None
                        n += 1
                drop = 0
                min_next = min((g["next"] for g in s.groups.values()),
                               default=len(s.entries))
                while drop < min_next and s.entries[drop] is None:
                    drop += 1
                if drop:
                    del s.entries[:drop]
                    for g in s.groups.values():
                        g["next"] -= drop
        self._int(n)

    def _cmd_xpending(self, st, args):
        # XPENDING key group — summary form: [count, min-id, max-id,
        # [[consumer, count-as-string], ...]]
        key, group = args[0], args[1]
        with st.cv:
            s = st.streams.get(key)
            g = s.groups.get(group) if s else None
            pel = dict(g["pel"]) if g else {}
        if not pel:
            self._array([0, None, None, None])
            return
        ids = sorted(pel)
        per: Dict[bytes, int] = {}
        for _eid, (consumer, _t) in pel.items():
            per[consumer] = per.get(consumer, 0) + 1
        self._array([len(pel), ids[0], ids[-1],
                     [[c, str(n).encode()] for c, n in sorted(per.items())]])

    def _cmd_xack(self, st, args):
        key, group, ids = args[0], args[1], args[2:]
        n = 0
        with st.cv:
            s = st.streams.get(key)
            g = s.groups.get(group) if s else None
            if g:
                for eid in ids:
                    if g["pel"].pop(eid, None) is not None:
                        n += 1
        self._int(n)

    def _cmd_hset(self, st, args):
        key, pairs = args[0], args[1:]
        with st.cv:
            h = st.hashes.setdefault(key, {})
            added = 0
            for i in range(0, len(pairs), 2):
                if pairs[i] not in h:
                    added += 1
                h[pairs[i]] = pairs[i + 1]
            st.cv.notify_all()
        self._int(added)

    def _cmd_hgetall(self, st, args):
        with st.cv:
            h = st.hashes.get(args[0], {})
            flat = []
            for k, v in h.items():
                flat += [k, v]
        self._array(flat)

    def _cmd_hget(self, st, args):
        with st.cv:
            h = st.hashes.get(args[0], {})
            self._bulk(h.get(args[1]))

    def _cmd_hdel(self, st, args):
        key, fields = args[0], args[1:]
        n = 0
        with st.cv:
            h = st.hashes.get(key)
            if h:
                for f in fields:
                    if h.pop(f, None) is not None:
                        n += 1
                if not h:
                    st.hashes.pop(key, None)
        self._int(n)

    def _cmd_del(self, st, args):
        n = 0
        with st.cv:
            for k in args:
                if st.hashes.pop(k, None) is not None:
                    n += 1
                if st.streams.pop(k, None) is not None:
                    n += 1
        self._int(n)


class MiniRedisServer:
    """Threaded RESP2 server for the streams/hashes subset.

    Start one per host to get cross-process serving without installing
    Redis: ``MiniRedisServer(port=6379).start()``; point brokers at
    ``redis://127.0.0.1:6379/stream``.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv((host, port), _Handler)
        self._srv.state = _State()  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MiniRedisServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="mini-redis", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
