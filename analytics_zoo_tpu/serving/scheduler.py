"""Continuous, deadline-aware batch forming + multi-model multiplexing.

The original engine ran the reference's fixed discipline — claim up to
``batch_size`` records, waiting at most ``batch_timeout_ms`` — which either
idles the chip (the timeout fires on shallow queues) or lets one model's
backlog monopolize the device. This module is the serving twin of the comms
plane's fill-the-device-by-hiding-latency discipline (Horovod-style overlap,
PAPERS.md arXiv:1802.05799): never let the chip wait on batch formation, and
never let batch formation wait on a single model's queue.

Two pieces:

* :class:`ContinuousScheduler` — per-(model, input-signature) admission
  queues ordered earliest-deadline-first (the PR-7 absolute-deadline stamps
  are the priority), with a global ``max_inflight`` bound that backpressures
  the broker claim pump so admitted memory stays bounded ahead of the
  deadline shedder. A queue becomes *ripe* (dispatchable) when its shape
  bucket is full, when its head request's slack drops to ``slack_s``
  (dispatch-now: waiting longer risks the deadline), when arrivals pause for
  one forming quantum (the chip must not idle on a queue nobody is still
  feeding), or when the engine is draining. Among ripe queues, the earliest
  head deadline wins (depth breaks ties) — a slow model's backlog cannot
  starve a fast model past its deadline, because the fast model's requests
  ripen and outrank on slack.

* :class:`ModelMultiplexer` — N loaded models on ONE chip set, each with its
  own circuit breaker and precompile example. Model switch costs no
  compiles: every model's shape buckets ride the compile plane's warmed
  executable cache (PR 3), and hot-reload (PR 6) swaps weights without
  touching executables — so the scheduler is free to interleave (model,
  bucket) dispatches purely by deadline slack and queue depth.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["ServingRequest", "ContinuousScheduler", "ModelMultiplexer",
           "request_signature"]

_INF = float("inf")


def request_signature(data) -> Tuple:
    """Hashable shape/dtype signature of one decoded (densified) record —
    requests batch together only when stacking them is well-defined. Named
    records keep key ORDER (the engine feeds tensors positionally in the
    record's own key order, reference LinkedHashMap semantics)."""
    if isinstance(data, dict):
        return ("dict",) + tuple(
            (k, tuple(v.shape), str(v.dtype)) for k, v in data.items())
    if isinstance(data, (list, tuple)):
        return ("list",) + tuple(
            (tuple(v.shape), str(v.dtype)) for v in data)
    return ("arr", tuple(data.shape), str(data.dtype))


class ServingRequest:
    """One admitted record: decoded, densified, deadline-stamped, routed."""

    __slots__ = ("item_id", "data", "meta", "deadline", "model", "sig",
                 "trace", "t_admit", "shm_refs")

    def __init__(self, item_id: str, data, meta: Dict, model: str,
                 shm_refs=()):
        self.item_id = item_id
        self.data = data
        self.meta = meta
        d = meta.get("deadline")
        self.deadline = float(d) if d is not None else None
        self.model = model
        self.sig = request_signature(data)
        self.trace = meta.get("trace")
        self.t_admit = time.time()
        # shm object plane: slab descriptors this request's data is mapped
        # from — the engine done()s them strictly after the item's answer
        # is published (empty for inline/legacy payloads)
        self.shm_refs = tuple(shm_refs)

    @property
    def expired(self) -> bool:
        return self.deadline is not None and time.time() > self.deadline


class _Q:
    """One (model, signature) admission queue: an EDF heap plus the arrival
    bookkeeping the ripeness rules read."""

    __slots__ = ("heap", "last_arrival", "arrivals")

    def __init__(self):
        self.heap: List[Tuple[float, int, ServingRequest]] = []
        self.last_arrival = 0.0
        self.arrivals = 0

    def push(self, seq: int, req: ServingRequest, now: float):
        heapq.heappush(self.heap,
                       (req.deadline if req.deadline is not None else _INF,
                        seq, req))
        self.last_arrival = now
        self.arrivals += 1

    @property
    def head_deadline(self) -> float:
        return self.heap[0][0]

    def __len__(self):
        return len(self.heap)


class ContinuousScheduler:
    """EDF batch former over per-(model, signature) admission queues.

    Thread contract: the claim pump calls :meth:`offer` (blocking while the
    ``max_inflight`` bound is hit), dispatch workers call :meth:`next_batch`
    and pair every returned request with exactly one :meth:`done`.
    :meth:`finish_input` (drain: the pump will offer no more) lets
    ``next_batch`` return ``None`` once the queues empty; :meth:`close`
    (stop) wakes and releases everyone immediately.
    """

    def __init__(self, max_inflight: int = 256, slack_s: float = 0.005,
                 form_s: float = 0.002,
                 on_inflight: Optional[Callable[[int], None]] = None,
                 on_depth: Optional[Callable[[str, int], None]] = None):
        self.max_inflight = max(1, int(max_inflight))
        self.slack_s = max(0.0, float(slack_s))
        self.form_s = max(1e-4, float(form_s))
        self._cv = threading.Condition()
        self._queues: Dict[Tuple[str, Tuple], _Q] = {}
        self._inflight = 0          # admitted: queued + mid-dispatch
        self._seq = itertools.count()
        self._closed = False
        self._no_more = False
        # obs hooks (engine wires gauges); called OUTSIDE the lock
        self._on_inflight = on_inflight
        self._on_depth = on_depth

    # --- intake (claim pump) ------------------------------------------------
    def offer(self, req: ServingRequest) -> bool:
        """Admit one request, blocking while the inflight bound is hit —
        the backpressure that stops the claim pump (and with the Redis
        broker, leaves the backlog on the stream where the PEL keeps it
        at-least-once). False when the scheduler was closed meanwhile."""
        with self._cv:
            while self._inflight >= self.max_inflight and not self._closed:
                self._cv.wait(0.05)
            if self._closed:
                return False
            q = self._queues.get((req.model, req.sig))
            if q is None:
                q = self._queues.setdefault((req.model, req.sig), _Q())
            q.push(next(self._seq), req, time.time())
            self._inflight += 1
            inflight, depth = self._inflight, self._model_depth(req.model)
            self._cv.notify_all()
        if self._on_inflight:
            self._on_inflight(inflight)
        if self._on_depth:
            self._on_depth(req.model, depth)
        return True

    def offer_many(self, reqs: List[ServingRequest]) -> int:
        """Admit a whole claimed batch under one lock acquisition per
        inflight-window — the pump's hot path (per-record :meth:`offer`
        costs a lock round-trip, a ``notify_all`` and two gauge pushes
        EACH, which closed-loop saturation measures as real throughput).
        Blocks at the bound like :meth:`offer`; returns how many were
        admitted (short only when closed mid-way)."""
        admitted = 0
        while admitted < len(reqs):
            with self._cv:
                while self._inflight >= self.max_inflight \
                        and not self._closed:
                    self._cv.wait(0.05)
                if self._closed:
                    return admitted
                now = time.time()
                room = self.max_inflight - self._inflight
                chunk = reqs[admitted:admitted + room]
                for req in chunk:
                    q = self._queues.get((req.model, req.sig))
                    if q is None:
                        q = self._queues.setdefault(
                            (req.model, req.sig), _Q())
                    q.push(next(self._seq), req, now)
                self._inflight += len(chunk)
                inflight = self._inflight
                depths = {m: self._model_depth(m)
                          for m in {r.model for r in chunk}}
                self._cv.notify_all()
            if self._on_inflight:
                self._on_inflight(inflight)
            if self._on_depth:
                for m, d in depths.items():
                    self._on_depth(m, d)
            admitted += len(chunk)
        return admitted

    def admit(self, n: int = 1):
        """Account ``n`` requests admitted OUTSIDE the queues (the legacy
        fixed policy dispatches claim-order batches directly but still
        pairs each request with one :meth:`done`)."""
        with self._cv:
            self._inflight += n
            inflight = self._inflight
        if self._on_inflight:
            self._on_inflight(inflight)

    def done(self, n: int = 1):
        """A dispatch finished (or shed) ``n`` admitted requests."""
        with self._cv:
            self._inflight -= n
            inflight = self._inflight
            self._cv.notify_all()
        if self._on_inflight:
            self._on_inflight(inflight)

    # --- lifecycle ----------------------------------------------------------
    def finish_input(self):
        with self._cv:
            self._no_more = True
            self._cv.notify_all()

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # --- introspection ------------------------------------------------------
    def _model_depth(self, model: str) -> int:
        return sum(len(q) for (m, _), q in self._queues.items()
                   if m == model)

    def depths(self) -> Dict[str, int]:
        with self._cv:
            out: Dict[str, int] = {}
            for (m, _), q in self._queues.items():
                out[m] = out.get(m, 0) + len(q)
            return out

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    def queued(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    def oldest_wait_s(self) -> float:
        """Seconds the longest-waiting admitted request has sat queued —
        the worker-local half of the fleet's queue-age signal (the broker
        half is ``oldest_age_s``: entries not yet claimed)."""
        with self._cv:
            t = min((req.t_admit for q in self._queues.values()
                     for _, _, req in q.heap), default=None)
        return 0.0 if t is None else max(0.0, time.time() - t)

    # --- batch forming (dispatch workers) -----------------------------------
    def next_batch(self, cap_fn: Callable[[str], int], idle_wait: float = 0.05
                   ) -> Optional[Tuple[str, List[ServingRequest]]]:
        """Block until a (model, batch) is dispatchable; return
        ``(model_name, requests)`` with all requests sharing one input
        signature, in EDF order. ``None`` means stop (closed, or draining
        with nothing left). ``cap_fn(model)`` is the shape-bucket cap."""
        while True:
            with self._cv:
                if self._closed:
                    return None
                now = time.time()
                best_key = None
                best_rank = (_INF, 0)
                soonest = _INF
                for key, q in self._queues.items():
                    if not len(q):
                        continue
                    head = q.head_deadline
                    cap = max(1, cap_fn(key[0]))
                    ripe_at = min(
                        # slack gate: must dispatch before the head misses
                        head - self.slack_s if head != _INF else _INF,
                        # forming gate: arrivals paused for one quantum —
                        # nobody is still feeding this queue, don't idle
                        q.last_arrival + self.form_s)
                    if len(q) >= cap or self._no_more or ripe_at <= now:
                        rank = (head, -len(q))
                        if best_key is None or rank < best_rank:
                            best_key, best_rank = key, rank
                    else:
                        soonest = min(soonest, ripe_at)
                if best_key is not None:
                    return self._take(best_key,
                                      max(1, cap_fn(best_key[0])))
                if soonest != _INF:
                    self._cv.wait(min(max(soonest - now, 1e-4), idle_wait))
                    continue
                # every queue empty
                if self._no_more:
                    return None
                self._cv.wait(idle_wait)

    def _take(self, key, cap: int):
        q = self._queues[key]
        reqs = [heapq.heappop(q.heap)[2] for _ in range(min(len(q), cap))]
        depth = self._model_depth(key[0])
        if self._on_depth:
            # inside the lock is fine: gauge .set is a micro-lock
            self._on_depth(key[0], depth)
        return key[0], reqs


class _ModelEntry:
    __slots__ = ("name", "model", "breaker", "example", "records_out",
                 "batches")

    def __init__(self, name, model, breaker, example):
        self.name = name
        self.model = model
        self.breaker = breaker
        self.example = example
        self.records_out = 0
        self.batches = 0


class ModelMultiplexer:
    """N named models co-served on one chip set.

    Each entry keeps its own :class:`~..resilience.retry.CircuitBreaker`
    (a wedged model sheds ITS requests fast without opening the circuit on
    its healthy neighbours) and an optional precompile ``example`` the
    engine warms at :meth:`ClusterServing.start`. The first added model is
    the default route for requests that carry no ``model`` meta."""

    def __init__(self, breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 30.0):
        from ..resilience.retry import CircuitBreaker
        self._CircuitBreaker = CircuitBreaker
        self._threshold = breaker_threshold
        self._cooldown = breaker_cooldown_s
        self._entries: Dict[str, _ModelEntry] = {}
        self._default: Optional[str] = None
        self._lock = threading.Lock()

    def add_model(self, name: str, model, example=None) -> "ModelMultiplexer":
        with self._lock:
            entry = self._entries.get(name)
            if entry is not None:
                entry.model = model
                if example is not None:
                    entry.example = example
            else:
                self._entries[name] = _ModelEntry(
                    name, model,
                    self._CircuitBreaker(threshold=self._threshold,
                                         cooldown_s=self._cooldown,
                                         name=f"serving.{name}"),
                    example)
                if self._default is None:
                    self._default = name
        return self

    @property
    def default_name(self) -> str:
        if self._default is None:
            raise RuntimeError("ModelMultiplexer has no models; add_model "
                               "first")
        return self._default

    @property
    def default(self) -> _ModelEntry:
        return self._entries[self.default_name]

    def get(self, name: str) -> Optional[_ModelEntry]:
        return self._entries.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def entries(self) -> List[_ModelEntry]:
        with self._lock:
            return list(self._entries.values())

    def __len__(self):
        return len(self._entries)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def bucket_cap(self, name: str, batch_size: int) -> int:
        """Shape-bucket cap for one model's batches: the configured
        ``batch_size``, device-rounded by the model's own bucket table
        when it has one (plain ``predict``-only objects don't)."""
        entry = self._entries.get(name)
        if entry is None:
            return batch_size
        buckets = getattr(entry.model, "buckets", None)
        if not buckets:
            return batch_size
        from ..pipeline.inference.inference_model import _bucket
        return _bucket(batch_size, buckets)

    def compile_stats(self) -> Dict:
        """Per-model warmed-executable signature counts. Executables live
        in the ONE process-wide compile plane (separate per-model compile
        counters don't exist by design — sharing is the point), so the
        per-model zero-churn receipt is this count staying flat while
        traffic interleaves, read next to the plane's global ``compiles``."""
        out = {}
        for entry in self.entries():
            cache = getattr(entry.model, "_cache", None)
            if cache is not None:
                out[entry.name] = {"warmed_signatures": len(cache)}
        return out

    def snapshot(self) -> Dict:
        return {name: {"records_out": e.records_out, "batches": e.batches,
                       "breaker": e.breaker.snapshot()}
                for name, e in ((n, self._entries[n]) for n in self.names())}
