"""Zero-copy shared-memory object plane.

``BlobArena`` + ``ObjectRef`` descriptors let the serving, streaming and
checkpoint fleets move tensors between host processes by reference
instead of by copy (``docs/performance_notes.md`` PR-20). Gated by
``ZOO_SHM``; off, every wire stays byte-identical to the inline formats.
"""

from .arena import (ArenaFull, BlobArena, ObjectRef, StaleObjectRef,
                    arena_for, arena_root_for, default_control_root,
                    shm_available)
from .wire import (arena_for_spec, envelope_key, is_envelope, min_shm_bytes,
                   peek_refs, publish_blob, resolve_blob,
                   shm_enabled_for_spec, sweep_spec, unwrap, wrap_inline,
                   wrap_ref)

__all__ = [
    "ArenaFull", "BlobArena", "ObjectRef", "StaleObjectRef",
    "arena_for", "arena_root_for", "default_control_root", "shm_available",
    "arena_for_spec", "envelope_key", "is_envelope", "min_shm_bytes",
    "peek_refs",
    "publish_blob", "resolve_blob", "shm_enabled_for_spec", "sweep_spec",
    "unwrap", "wrap_inline", "wrap_ref",
]
