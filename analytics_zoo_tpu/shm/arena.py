"""Ref-counted shared-memory object plane (the host-side zero-copy tier).

The reference stack rides Ray's object store so tensors move between
processes by reference; our fleet hops (frontend -> broker -> worker,
producer -> trainer, checkpoint -> reloader) still ship payload *bytes*
through the broker, copying each request several times on the host before
it reaches HBM. This module is the missing plane: a :class:`BlobArena`
carves named ``multiprocessing.shared_memory`` segments into aligned
slabs, producers ``put`` payload bytes once, and everything after that
moves an :class:`ObjectRef` descriptor (segment/offset/length/dtype/
shape/generation) — consumers map the slab read-only and feed the view
straight to batch assembly / ``sharded_put``.

Crash-safe ref-counting, no daemon:

* every pin lives in the pinning process's **lease file**
  (``leases/<pid>-<uuid>.json``). A SIGKILL cannot unwind Python, but it
  also cannot keep a lease file relevant: :meth:`BlobArena.sweep` drops
  leases whose pid is gone, so the fleet supervisors reclaim a dead
  worker's pins on reap and a killed consumer leaks zero segments;
* an allocation is freed when it has been **consumed** (a consumer
  called :meth:`BlobArena.done` after acking it) and no lease pins it.
  A producer that releases right after enqueue therefore keeps the blob
  alive until a consumer really finished with it — and a *reclaimed*
  broker delivery (PEL replay) re-resolves the same generation-checked
  slab bytes;
* every allocation carries a **generation** from a monotonic arena
  counter. Mapping a freed (or reused) slab raises a typed
  :class:`StaleObjectRef`, never returns garbage.

All metadata mutations serialize through one ``flock`` per arena; the
index is a small JSON document rewritten atomically, so any process (or
the ``zoo-shm`` CLI) can inspect and repair an arena after a crash.
"""

from __future__ import annotations

import contextlib
import errno
import hashlib
import json
import logging
import os
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["ObjectRef", "StaleObjectRef", "ArenaFull", "BlobArena",
           "arena_root_for", "arena_for", "shm_available",
           "default_control_root"]

_MAX_SEGMENTS = 8


class StaleObjectRef(Exception):
    """The descriptor's generation no longer matches the slab: the blob
    was freed (and possibly reused) after the descriptor was minted."""


class ArenaFull(Exception):
    """No contiguous slab run satisfies the allocation and the arena is
    at its segment cap — callers fall back to the inline wire."""


@dataclass(frozen=True)
class ObjectRef:
    """Descriptor of one blob in a :class:`BlobArena`: everything a
    consumer needs to map it, nothing that requires the producer to stay
    alive. ``dtype``/``shape`` are optional tensor semantics — set, the
    checkout returns a shaped ndarray view; unset, a flat byte view."""
    segment: str
    offset: int
    length: int
    generation: int
    dtype: Optional[str] = None
    shape: Optional[Tuple[int, ...]] = None

    @property
    def key(self) -> str:
        return f"{self.segment}:{self.offset}"

    def to_dict(self) -> Dict:
        d = {"seg": self.segment, "off": self.offset, "len": self.length,
             "gen": self.generation}
        if self.dtype is not None:
            d["dtype"] = self.dtype
        if self.shape is not None:
            d["shape"] = list(self.shape)
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "ObjectRef":
        return cls(segment=str(d["seg"]), offset=int(d["off"]),
                   length=int(d["len"]), generation=int(d["gen"]),
                   dtype=d.get("dtype"),
                   shape=(tuple(int(s) for s in d["shape"])
                          if d.get("shape") is not None else None))


def shm_available() -> bool:
    """POSIX shared memory usable on this host?"""
    if os.name != "posix":
        return False
    try:
        from multiprocessing import shared_memory  # noqa: F401
    except ImportError:         # pragma: no cover — stdlib since 3.8
        return False
    return True


def default_control_root() -> str:
    """Directory arenas keep their control plane (index/lock/leases)
    under. ``/dev/shm`` when writable — metadata updates are on the
    message hot path and tmpfs keeps them off the disk — else tmpdir."""
    if os.path.isdir("/dev/shm") and os.access("/dev/shm", os.W_OK):
        return "/dev/shm/zoo_shm"
    return os.path.join(tempfile.gettempdir(), "zoo_shm")


def arena_root_for(key: str) -> str:
    """Deterministic control-dir path for a logical arena key (e.g. a
    broker spec's base) — every process that shares the key shares the
    arena without any rendezvous beyond the string itself."""
    digest = hashlib.sha1(key.encode("utf-8")).hexdigest()[:12]
    return os.path.join(default_control_root(), digest)


def _untrack(seg) -> None:
    # resource_tracker would unlink every attached segment when the FIRST
    # attaching process exits, yanking live slabs out from under the rest
    # of the fleet (and spamming "leaked shared_memory" warnings for
    # segments the arena owns deliberately). Lifetime is the arena
    # index's job; 3.13's track=False is not available on 3.10.
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(seg._name, "shared_memory")
    except Exception as e:  # noqa: BLE001 — tracker internals shifted; the
        # worst case is a spurious "leaked shared_memory" warning at exit
        logger.debug("shm: resource_tracker unregister failed: %s", e)


def _counters():
    """Lazy obs handles (import cycles: obs.registry is leaf-safe but
    keep the arena importable before the registry configures)."""
    global _C
    if _C is None:
        from ..obs.registry import REGISTRY
        _C = {
            "put": REGISTRY.counter(
                "zoo_shm_bytes_put_total",
                "payload bytes copied INTO arena slabs by producers "
                "(the one copy the descriptor wire pays)"),
            "mapped": REGISTRY.counter(
                "zoo_shm_bytes_mapped_total",
                "payload bytes resolved as zero-copy slab mappings by "
                "consumers (bytes the inline wire would have copied)"),
            "inline": REGISTRY.counter(
                "zoo_shm_bytes_inline_total",
                "payload bytes that fell back to the inline wire "
                "(arena full / oversized / shm unavailable)"),
            "allocs": REGISTRY.counter(
                "zoo_shm_allocs_total", "arena slab allocations"),
            "stale": REGISTRY.counter(
                "zoo_shm_stale_total",
                "descriptor checkouts rejected by the generation check "
                "(StaleObjectRef raised instead of returning garbage)"),
            "swept": REGISTRY.counter(
                "zoo_shm_leases_swept_total",
                "dead-process lease files swept by supervisors/gc"),
            "live": REGISTRY.gauge(
                "zoo_shm_slabs_live", "slabs currently allocated",
                labelnames=("arena",)),
        }
    return _C


_C = None


class BlobArena:
    """One shared-memory arena: N named segments, each carved into
    ``slab_bytes`` slabs; allocation = a contiguous slab run.

    Thread-safe within a process and crash-safe across processes: all
    index/lease mutations run under the arena's ``flock``.
    """

    def __init__(self, root: str, *, slab_bytes: int = 1 << 20,
                 segment_bytes: int = 64 << 20, create: bool = True):
        if slab_bytes <= 0 or segment_bytes < slab_bytes:
            raise ValueError(
                f"need segment_bytes >= slab_bytes > 0, got "
                f"{segment_bytes}/{slab_bytes}")
        self.root = root
        self.slab_bytes = int(slab_bytes)
        self.segment_bytes = (int(segment_bytes) // self.slab_bytes
                              * self.slab_bytes)
        self._seg_name_base = "zooshm_" + hashlib.sha1(
            os.path.abspath(root).encode()).hexdigest()[:10]
        self._segs: Dict[str, object] = {}     # name -> SharedMemory
        self._pins: Dict[str, int] = {}        # "seg:off:gen" -> count
        self._lock = threading.Lock()
        self._lease_path = None
        self._closed = False
        if create:
            os.makedirs(os.path.join(root, "leases"), exist_ok=True)

    # --- index / lock plumbing ---------------------------------------------
    @property
    def _index_path(self) -> str:
        return os.path.join(self.root, "index.json")

    @contextlib.contextmanager
    def _flock(self):
        import fcntl
        os.makedirs(self.root, exist_ok=True)
        fd = os.open(os.path.join(self.root, "lock"),
                     os.O_CREAT | os.O_RDWR, 0o666)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            os.close(fd)    # releases the flock

    def _load_index(self) -> Dict:
        try:
            with open(self._index_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"gen": 0, "segments": [], "allocs": {}}

    def _save_index(self, idx: Dict) -> None:
        tmp = self._index_path + f".tmp-{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            json.dump(idx, f)
        os.replace(tmp, self._index_path)

    # --- lease (per-process pin) file --------------------------------------
    def _write_lease(self) -> None:
        lease_dir = os.path.join(self.root, "leases")
        if self._lease_path is None:
            os.makedirs(lease_dir, exist_ok=True)
            self._lease_path = os.path.join(
                lease_dir, f"{os.getpid()}-{uuid.uuid4().hex[:8]}.json")
        tmp = self._lease_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "pins": self._pins}, f)
        os.replace(tmp, self._lease_path)
        if not self._pins:
            with contextlib.suppress(OSError):
                os.unlink(self._lease_path)
            self._lease_path = None

    def _pin(self, tag: str) -> None:
        self._pins[tag] = self._pins.get(tag, 0) + 1
        self._write_lease()

    def _unpin(self, tag: str) -> bool:
        n = self._pins.get(tag, 0)
        if n <= 1:
            self._pins.pop(tag, None)
        else:
            self._pins[tag] = n - 1
        self._write_lease()
        return tag not in self._pins

    def _pinned_anywhere(self, tag: str) -> bool:
        lease_dir = os.path.join(self.root, "leases")
        try:
            names = os.listdir(lease_dir)
        except OSError:
            return False
        for n in names:
            if n.endswith(".tmp"):
                continue
            try:
                with open(os.path.join(lease_dir, n)) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if int(doc.get("pins", {}).get(tag, 0)) > 0:
                return True
        return False

    # --- segments -----------------------------------------------------------
    def _attach(self, name: str, create: bool = False):
        from multiprocessing import shared_memory
        seg = self._segs.get(name)
        if seg is None:
            if create:
                try:
                    seg = shared_memory.SharedMemory(
                        name=name, create=True, size=self.segment_bytes)
                except FileExistsError:
                    seg = shared_memory.SharedMemory(name=name)
            else:
                seg = shared_memory.SharedMemory(name=name)
            _untrack(seg)
            self._segs[name] = seg
        return seg

    @property
    def _slabs_per_seg(self) -> int:
        return self.segment_bytes // self.slab_bytes

    def _find_run(self, idx: Dict, need: int) -> Optional[Tuple[str, int]]:
        """First contiguous free run of ``need`` slabs, growing the
        segment list up to the cap when every existing one is packed."""
        for seg in idx["segments"]:
            used = [False] * self._slabs_per_seg
            for key, rec in idx["allocs"].items():
                s, off = key.rsplit(":", 1)
                if s != seg:
                    continue
                first = int(off) // self.slab_bytes
                for i in range(first, first + int(rec["slabs"])):
                    used[i] = True
            run = 0
            for i, u in enumerate(used):
                run = 0 if u else run + 1
                if run == need:
                    return seg, (i - need + 1) * self.slab_bytes
        if need <= self._slabs_per_seg \
                and len(idx["segments"]) < _MAX_SEGMENTS:
            name = f"{self._seg_name_base}_{len(idx['segments'])}"
            self._attach(name, create=True)
            idx["segments"].append(name)
            return name, 0
        return None

    # --- public API ---------------------------------------------------------
    def put(self, data, *, dtype: Optional[str] = None,
            shape: Optional[Tuple[int, ...]] = None) -> ObjectRef:
        """Copy ``data`` (any buffer) into the arena once and pin it in
        this process's lease. Raises :class:`ArenaFull` when no slab run
        fits — callers fall back to the inline wire."""
        view = memoryview(data).cast("B")
        length = view.nbytes
        need = max(1, -(-length // self.slab_bytes))
        with self._lock, self._flock():
            idx = self._load_index()
            spot = self._find_run(idx, need)
            if spot is None:
                raise ArenaFull(
                    f"{length} B needs {need} contiguous slabs; arena at "
                    f"segment cap ({len(idx['segments'])})")
            seg_name, offset = spot
            idx["gen"] = gen = int(idx["gen"]) + 1
            idx["allocs"][f"{seg_name}:{offset}"] = {
                "gen": gen, "slabs": need, "len": length,
                "consumed": False, "t": round(time.time(), 3)}
            self._save_index(idx)
            seg = self._attach(seg_name)
            seg.buf[offset:offset + length] = view
            self._pin(f"{seg_name}:{offset}:{gen}")
            c = _counters()
            c["put"].inc(length)
            c["allocs"].inc()
            c["live"].labels(arena=self._seg_name_base).set(
                sum(int(r["slabs"]) for r in idx["allocs"].values()))
        return ObjectRef(segment=seg_name, offset=offset, length=length,
                         generation=gen, dtype=dtype, shape=shape)

    def _validate(self, idx: Dict, ref: ObjectRef) -> None:
        rec = idx["allocs"].get(ref.key)
        if rec is None or int(rec["gen"]) != ref.generation:
            _counters()["stale"].inc()
            raise StaleObjectRef(
                f"{ref.key} gen {ref.generation} is "
                f"{'freed' if rec is None else 'reused (gen %d)' % rec['gen']}")

    def checkout(self, ref: ObjectRef, *, pin: bool = True):
        """Map the blob read-only. Returns a C-contiguous numpy view
        (shaped when the descriptor carries dtype/shape, else uint8) —
        zero copy; the view stays valid while the pin holds. Raises
        :class:`StaleObjectRef` on a freed/reused generation."""
        import numpy as np
        with self._lock, self._flock():
            self._validate(self._load_index(), ref)
            if pin:
                self._pin(f"{ref.key}:{ref.generation}")
        seg = self._attach(ref.segment)
        arr = np.frombuffer(seg.buf, dtype=np.uint8, count=ref.length,
                            offset=ref.offset)
        if ref.dtype is not None:
            arr = arr.view(np.dtype(ref.dtype))
            if ref.shape is not None:
                arr = arr.reshape(ref.shape)
        arr.flags.writeable = False
        _counters()["mapped"].inc(ref.length)
        return arr

    def _maybe_free(self, idx: Dict, ref: ObjectRef) -> bool:
        rec = idx["allocs"].get(ref.key)
        if rec is None or int(rec["gen"]) != ref.generation:
            return False
        if rec.get("consumed") \
                and not self._pinned_anywhere(f"{ref.key}:{ref.generation}"):
            del idx["allocs"][ref.key]
            return True
        return False

    def release(self, ref: ObjectRef) -> None:
        """Drop this process's pin (producer done handing off, or a
        consumer abandoning an unacked claim). Idempotent; frees the
        slabs when the blob is both consumed and unpinned."""
        with self._lock, self._flock():
            self._unpin(f"{ref.key}:{ref.generation}")
            idx = self._load_index()
            if self._maybe_free(idx, ref):
                self._save_index(idx)

    def done(self, ref: ObjectRef) -> None:
        """Consumer finished with the blob (data copied out / result
        published / entry acked): unpin AND mark consumed, freeing the
        slabs once every other pin is gone. Idempotent — a double ack or
        an already-freed blob is a no-op."""
        with self._lock, self._flock():
            self._unpin(f"{ref.key}:{ref.generation}")
            idx = self._load_index()
            rec = idx["allocs"].get(ref.key)
            if rec is not None and int(rec["gen"]) == ref.generation:
                rec["consumed"] = True
                self._maybe_free(idx, ref)
                self._save_index(idx)

    def sweep(self, dead_pids: Optional[List[int]] = None) -> Dict:
        """Crash recovery: drop lease files of dead processes (the given
        pids, else every lease whose pid no longer exists), then free
        allocations that became consumed-and-unpinned. Fleet supervisors
        call this when they reap a worker; ``zoo-shm gc`` calls it for
        orphaned arenas."""
        swept = freed = 0
        with self._lock, self._flock():
            lease_dir = os.path.join(self.root, "leases")
            try:
                names = os.listdir(lease_dir)
            except OSError:
                names = []
            for n in names:
                if n.endswith(".tmp"):
                    continue
                path = os.path.join(lease_dir, n)
                try:
                    with open(path) as f:
                        pid = int(json.load(f).get("pid", -1))
                except (OSError, ValueError):
                    continue
                dead = pid in dead_pids if dead_pids is not None \
                    else not _pid_alive(pid)
                if dead:
                    with contextlib.suppress(OSError):
                        os.unlink(path)
                    swept += 1
            idx = self._load_index()
            for key in list(idx["allocs"]):
                rec = idx["allocs"][key]
                if rec.get("consumed") and not self._pinned_anywhere(
                        f"{key}:{rec['gen']}"):
                    del idx["allocs"][key]
                    freed += 1
            self._save_index(idx)
            if swept:
                _counters()["swept"].inc(swept)
            _counters()["live"].labels(arena=self._seg_name_base).set(
                sum(int(r["slabs"]) for r in idx["allocs"].values()))
        return {"leases_swept": swept, "freed": freed}

    def gc(self, grace_s: float = 300.0) -> Dict:
        """:meth:`sweep` plus: free *unconsumed* allocations older than
        ``grace_s`` with no live pin anywhere — blobs whose producer died
        before any consumer saw them (nothing will ever consume these)."""
        out = self.sweep()
        orphans = 0
        now = time.time()
        with self._lock, self._flock():
            idx = self._load_index()
            for key in list(idx["allocs"]):
                rec = idx["allocs"][key]
                if not rec.get("consumed") \
                        and now - float(rec.get("t", 0)) >= grace_s \
                        and not self._pinned_anywhere(f"{key}:{rec['gen']}"):
                    del idx["allocs"][key]
                    orphans += 1
            self._save_index(idx)
        out["orphans_freed"] = orphans
        return out

    def stats(self) -> Dict:
        with self._lock, self._flock():
            idx = self._load_index()
            live = sum(int(r["slabs"]) for r in idx["allocs"].values())
            leases = [n for n in os.listdir(os.path.join(
                self.root, "leases"))] if os.path.isdir(
                os.path.join(self.root, "leases")) else []
            return {
                "segments": len(idx["segments"]),
                "slabs_total": len(idx["segments"]) * self._slabs_per_seg,
                "slabs_live": live,
                "allocs_live": len(idx["allocs"]),
                "bytes_live": sum(int(r["len"])
                                  for r in idx["allocs"].values()),
                "leases": len([n for n in leases
                               if not n.endswith(".tmp")]),
                "gen": int(idx["gen"])}

    def close(self) -> None:
        """Graceful per-process detach: drop this process's pins (their
        lease file with them), free what that makes freeable, and close
        the local segment mappings. The arena itself survives for the
        other processes."""
        if self._closed:
            return
        self._closed = True
        with self._lock, self._flock():
            self._pins.clear()
            self._write_lease()     # pins now empty -> unlinks the file
            idx = self._load_index()
            changed = False
            for key in list(idx["allocs"]):
                rec = idx["allocs"][key]
                if rec.get("consumed") and not self._pinned_anywhere(
                        f"{key}:{rec['gen']}"):
                    del idx["allocs"][key]
                    changed = True
            if changed:
                self._save_index(idx)
        for seg in self._segs.values():
            with contextlib.suppress(Exception):
                seg.close()
        self._segs.clear()

    def destroy(self) -> int:
        """Unlink every segment and remove the control dir — the
        ``zoo-shm gc`` end state for a dead arena. Returns the number of
        segments unlinked."""
        n = 0
        with self._lock, self._flock():
            idx = self._load_index()
            for name in idx["segments"]:
                seg = self._segs.pop(name, None)
                if seg is not None:
                    # live numpy views keep the mmap exported; the views
                    # die with the process, the name must die now
                    with contextlib.suppress(BufferError, Exception):
                        seg.close()
                try:
                    _shm_unlink(name)
                    n += 1
                except FileNotFoundError:
                    pass
        self._segs.clear()
        self._closed = True
        import shutil
        shutil.rmtree(self.root, ignore_errors=True)
        return n


def _shm_unlink(name: str) -> None:
    """Remove a segment NAME without routing through resource_tracker
    (we unregistered at attach; SharedMemory.unlink would ping the
    tracker about a name it no longer knows)."""
    try:
        import _posixshmem
        _posixshmem.shm_unlink("/" + name)
    except ImportError:     # pragma: no cover — non-CPython fallback
        from multiprocessing import shared_memory
        seg = shared_memory.SharedMemory(name=name)
        seg.close()
        seg.unlink()


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:     # exists, owned by someone else
        return True
    except OSError as e:        # pragma: no cover — exotic kernels
        return e.errno != errno.ESRCH
    return True


_ARENAS: Dict[str, BlobArena] = {}
_ARENAS_LOCK = threading.Lock()


def arena_for(key: str, *, slab_bytes: Optional[int] = None,
              segment_bytes: Optional[int] = None) -> BlobArena:
    """Process-cached arena for a logical key (one per broker spec base).
    Sizing comes from ``ZOO_SHM_SLAB_MB`` / ``ZOO_SHM_ARENA_MB`` unless
    overridden."""
    from ..common import knobs
    root = arena_root_for(key)
    with _ARENAS_LOCK:
        a = _ARENAS.get(root)
        if a is None or a._closed:
            a = BlobArena(
                root,
                slab_bytes=int(slab_bytes if slab_bytes is not None
                               else knobs.get("ZOO_SHM_SLAB_MB") * (1 << 20)),
                segment_bytes=int(
                    segment_bytes if segment_bytes is not None
                    else knobs.get("ZOO_SHM_ARENA_MB") * (1 << 20)))
            _ARENAS[root] = a
        return a
