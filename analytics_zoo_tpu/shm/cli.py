"""``zoo-shm`` — operator CLI for the shared-memory object plane.

``zoo-shm gc`` sweeps every arena under the control root: leases of dead
processes are dropped, consumed-and-unpinned blobs are freed, unconsumed
blobs past the grace window (their producer died before any consumer saw
them) are reclaimed, and arenas left with no blobs and no leases are
destroyed with ``--purge-empty`` — the recovery path after a host crash
or a SIGKILLed fleet whose supervisor never ran its sweep.

``zoo-shm stats`` prints one JSON line per arena.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from .arena import BlobArena, default_control_root


def _arena_roots(root: str) -> List[str]:
    if not os.path.isdir(root):
        return []
    return sorted(os.path.join(root, n) for n in os.listdir(root)
                  if os.path.isdir(os.path.join(root, n)))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="zoo-shm", description="shared-memory object plane tooling")
    sub = p.add_subparsers(dest="cmd", required=True)
    g = sub.add_parser("gc", help="sweep dead leases + orphaned segments")
    g.add_argument("--root", default=default_control_root(),
                   help="control root holding the arenas "
                        "(default: %(default)s)")
    g.add_argument("--grace", type=float, default=300.0,
                   help="seconds an unconsumed, unpinned blob survives "
                        "before it is reclaimed as an orphan "
                        "(default: %(default)s)")
    g.add_argument("--purge-empty", action="store_true",
                   help="destroy arenas left with no blobs and no leases "
                        "(unlinks their segments)")
    s = sub.add_parser("stats", help="per-arena occupancy")
    s.add_argument("--root", default=default_control_root())
    args = p.parse_args(argv)

    roots = _arena_roots(args.root)
    if not roots:
        print(f"no arenas under {args.root}")
        return 0
    rc = 0
    for root in roots:
        try:
            arena = BlobArena(root, create=False)
            if args.cmd == "stats":
                print(json.dumps({"arena": root, **arena.stats()}))
                continue
            out = arena.gc(grace_s=args.grace)
            st = arena.stats()
            purged = False
            if args.purge_empty and st["allocs_live"] == 0 \
                    and st["leases"] == 0:
                arena.destroy()
                purged = True
            print(json.dumps({"arena": root, **out, "purged": purged,
                              "allocs_live": st["allocs_live"],
                              "leases": st["leases"]}))
        except Exception as e:  # noqa: BLE001 — keep sweeping the rest
            print(f"{root}: {type(e).__name__}: {e}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
