"""Descriptor envelope riding the existing broker wire.

A payload on a shm-enabled stream is either a **descriptor frame**
(magic + small JSON header naming :class:`~.arena.ObjectRef` slabs) or
an **inline frame** (the same magic with the ``I`` flag, followed by
today's payload byte for byte — the fallback when the arena is full, the
blob is oversized, or shm is unavailable). Legacy payloads without the
magic pass through untouched, so a shm-enabled consumer drains a mixed
stream and ``ZOO_SHM=0`` keeps the wire bit-identical to before this
plane existed.

The header carries the record's routing key (``k``) when the wrapped
payload had one, so the partitioned broker's key-sharding survives the
descriptor wire without touching the slab.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ..common import knobs
from .arena import (ArenaFull, BlobArena, ObjectRef, StaleObjectRef,
                    arena_for, shm_available)

__all__ = ["MAGIC", "is_envelope", "wrap_inline", "wrap_ref", "unwrap",
           "min_shm_bytes",
           "envelope_key", "peek_refs", "publish_blob", "resolve_blob",
           "shm_enabled_for_spec", "arena_for_spec", "sweep_spec"]

MAGIC = b"ZSHM1"
_FLAG_INLINE = b"I"
_FLAG_REF = b"R"

_LOCAL_HOSTS = ("127.0.0.1", "localhost", "::1", "")


def is_envelope(buf) -> bool:
    return bytes(memoryview(buf)[:5]) == MAGIC


def _frame(flag: bytes, header: Dict, payload: bytes = b"") -> bytes:
    head = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return b"".join([MAGIC, flag, len(head).to_bytes(4, "big"), head,
                     payload])


def wrap_inline(payload, key: Optional[str] = None) -> bytes:
    """Inline frame: the original payload embedded byte for byte."""
    header = {} if key is None else {"k": str(key)}
    return _frame(_FLAG_INLINE, header, bytes(payload))


def wrap_ref(refs: List[ObjectRef], meta: Optional[Dict] = None,
             key: Optional[str] = None, kind: str = "blob") -> bytes:
    header: Dict = {"kind": kind, "refs": [r.to_dict() for r in refs]}
    if meta:
        header["meta"] = meta
    if key is not None:
        header["k"] = str(key)
    return _frame(_FLAG_REF, header)


def unwrap(buf) -> Tuple[str, Dict, memoryview]:
    """Envelope -> ``(flag, header, payload_view)`` where flag is
    ``"I"``/``"R"`` and payload_view is the embedded inline payload
    (empty for descriptor frames). Raises ValueError on a non-envelope."""
    view = memoryview(buf)
    if bytes(view[:5]) != MAGIC:
        raise ValueError("not a shm envelope")
    flag = bytes(view[5:6]).decode("ascii")
    hlen = int.from_bytes(bytes(view[6:10]), "big")
    header = json.loads(bytes(view[10:10 + hlen]))
    return flag, header, view[10 + hlen:]


def envelope_key(buf) -> Optional[str]:
    """Routing key stamped on an envelope, header-only (the partition
    router's hot path)."""
    _, header, _ = unwrap(buf)
    k = header.get("k")
    return None if k is None else str(k)


def peek_refs(buf) -> List[ObjectRef]:
    """Descriptors named by an envelope WITHOUT checking them out — the
    consume-without-decode paths (dedup replay, shed) use this to mark
    the blob done."""
    if not is_envelope(buf):
        return []
    flag, header, _ = unwrap(buf)
    if flag != "R":
        return []
    return [ObjectRef.from_dict(d) for d in header.get("refs", [])]


def min_shm_bytes() -> int:
    """Descriptor-path size floor (``ZOO_SHM_MIN_BYTES``): below it the
    fixed per-object cost — a whole slab burned, the index flock, two
    lease-file rewrites per side — exceeds the copy it saves, so small
    payloads stay on the inline wire even with the plane on."""
    return int(knobs.get("ZOO_SHM_MIN_BYTES"))


# --- whole-blob convenience (streaming records, opaque payloads) ------------
def publish_blob(arena: Optional[BlobArena], payload: bytes,
                 key: Optional[str] = None) -> bytes:
    """Producer side: payload -> descriptor frame (one copy, into the
    slab), falling back to an inline frame when the arena cannot take it
    and to the bare payload when there is no arena at all or the payload
    is under the :func:`min_shm_bytes` floor."""
    if arena is None or len(payload) < min_shm_bytes():
        return payload
    try:
        ref = arena.put(payload)
    except (ArenaFull, OSError, ValueError):
        from .arena import _counters
        _counters()["inline"].inc(len(payload))
        return wrap_inline(payload, key=key)
    frame = wrap_ref([ref], key=key)
    # handoff complete: the frame is self-contained, so drop the producer
    # pin — the blob stays alive (unconsumed) until a consumer done()s it,
    # and a producer crash after enqueue leaks nothing past gc grace
    arena.release(ref)
    return frame


def resolve_blob(buf, arena: Optional[BlobArena]
                 ) -> Tuple[memoryview, Optional[ObjectRef]]:
    """Consumer side: broker payload -> ``(bytes_view, ref)``.

    Legacy payloads and inline frames return their bytes (ref None);
    descriptor frames check out the slab (pinning it in this process's
    lease) and return the read-only mapping — the caller owes
    ``arena.done(ref)`` after it acked the entry, or ``release`` to
    abandon. Raises :class:`StaleObjectRef` on a freed generation and
    ValueError on a descriptor frame with no arena to resolve against."""
    if not is_envelope(buf):
        return memoryview(buf), None
    flag, header, payload = unwrap(buf)
    if flag == "I":
        return payload, None
    if arena is None:
        raise ValueError("descriptor frame on a stream with no shm arena "
                         "(consumer has ZOO_SHM off or shm unavailable)")
    refs = [ObjectRef.from_dict(d) for d in header.get("refs", [])]
    if len(refs) != 1:
        raise ValueError(f"blob frame must carry one ref, got {len(refs)}")
    arr = arena.checkout(refs[0])
    return memoryview(arr).cast("B"), refs[0]


# --- broker-spec plumbing ---------------------------------------------------
def _spec_base(spec: str) -> str:
    return spec.partition("?")[0]


def shm_enabled_for_spec(spec: Optional[str]) -> bool:
    """Descriptor wire active for this broker spec? Requires ``ZOO_SHM=1``
    plus a transport whose producer and consumer share a host: memory and
    file always qualify locally; redis only when it points at localhost
    (the operator's colocation assertion — a cross-host consumer cannot
    map this host's segments)."""
    if not spec or not knobs.get("ZOO_SHM") or not shm_available():
        return False
    base = _spec_base(spec)
    if base.startswith(("memory://", "file://")):
        return True
    if base.startswith("redis://"):
        hostport = base[len("redis://"):].partition("/")[0]
        return hostport.rpartition(":")[0] in _LOCAL_HOSTS \
            or hostport in _LOCAL_HOSTS
    return False


def arena_for_spec(spec: Optional[str]) -> Optional[BlobArena]:
    """The (process-cached) arena every process sharing this broker spec
    base agrees on, or None when the descriptor wire is off for it."""
    if not shm_enabled_for_spec(spec):
        return None
    return arena_for(_spec_base(spec))


def sweep_spec(spec: Optional[str],
               dead_pids: Optional[List[int]] = None) -> Dict:
    """Supervisor hook: sweep the spec's arena after reaping workers (a
    SIGKILLed consumer's lease pins die with its pid, not with its
    Python). No-op when the spec has no descriptor wire."""
    arena = arena_for_spec(spec)
    if arena is None:
        return {"leases_swept": 0, "freed": 0}
    return arena.sweep(dead_pids)
