"""Streaming plane — online learning on the request stream, hot-reloaded
into serving.

Closes the reference platform's headline loop (PAPER.md L2 data plane;
Cluster Serving streaming) end to end:

    producer XADD -> StreamingXShards (windowed ChunkedArray
    micro-batches over the Redis/RESP2 transport) -> StreamingTrainer
    (incremental fit on the scan-fused engine, one warm executable) ->
    CheckpointPlane commit (stream cursor + trace token in the manifest)
    -> StreamingReloader (CheckpointWatcher hot-swap into a live
    InferenceModel, zero new compiles) -> fresher predictions, in
    seconds.

At fleet scale (PR 19) the single trainer becomes a
:class:`~analytics_zoo_tpu.streaming.fleet.StreamingFleet`: records
carry a partition key (``encode_record(key=...)``), the stream shards
into keyed sub-streams (``?partitions=N``), N shared-nothing trainer
processes each run the loop above on their shard, and
:class:`~analytics_zoo_tpu.streaming.fleet.FleetReloaders` adopts each
partition's freshest committed step — optionally through a
:class:`~analytics_zoo_tpu.streaming.guardrail.GuardrailEvaluator` that
scores every commit on a holdout window and rejects regressions before
they reach traffic.

See ``docs/guides/streaming.md`` for window/watermark semantics, the
cursor contract, scale-out partitioning and the freshness SLO;
``examples/streaming/online_ncf.py`` runs the single-trainer tree in
one process against the bundled MiniRedisServer, and
``examples/streaming/zouwu_forecast.py`` rides a Zouwu forecaster on
the same plane.
"""

from .fleet import FleetReloaders, StreamingFleet          # noqa: F401
from .guardrail import (GuardrailEvaluator,                # noqa: F401
                        GuardrailRejected, module_loss_scorer)
from .records import (decode_record, encode_record,        # noqa: F401
                      partition_for, record_key, seq_id)
from .serve import StreamingReloader                       # noqa: F401
from .source import (StreamCursor, StreamingXShards,       # noqa: F401
                     Window)
from .stats import StreamingStats                          # noqa: F401
from .trainer import StreamingTrainer                      # noqa: F401

__all__ = ["encode_record", "decode_record", "seq_id", "record_key",
           "partition_for", "StreamCursor", "Window", "StreamingXShards",
           "StreamingTrainer", "StreamingReloader", "StreamingStats",
           "StreamingFleet", "FleetReloaders", "GuardrailEvaluator",
           "GuardrailRejected", "module_loss_scorer"]
