"""Streaming plane — online learning on the request stream, hot-reloaded
into serving.

Closes the reference platform's headline loop (PAPER.md L2 data plane;
Cluster Serving streaming) end to end:

    producer XADD -> StreamingXShards (windowed ChunkedArray
    micro-batches over the Redis/RESP2 transport) -> StreamingTrainer
    (incremental fit on the scan-fused engine, one warm executable) ->
    CheckpointPlane commit (stream cursor + trace token in the manifest)
    -> StreamingReloader (CheckpointWatcher hot-swap into a live
    InferenceModel, zero new compiles) -> fresher predictions, in
    seconds.

See ``docs/guides/streaming.md`` for window/watermark semantics, the
cursor contract, and the freshness SLO; ``examples/streaming/
online_ncf.py`` runs the whole tree in one process against the bundled
MiniRedisServer.
"""

from .records import decode_record, encode_record, seq_id  # noqa: F401
from .serve import StreamingReloader                       # noqa: F401
from .source import (StreamCursor, StreamingXShards,       # noqa: F401
                     Window)
from .stats import StreamingStats                          # noqa: F401
from .trainer import StreamingTrainer                      # noqa: F401

__all__ = ["encode_record", "decode_record", "seq_id", "StreamCursor",
           "Window", "StreamingXShards", "StreamingTrainer",
           "StreamingReloader", "StreamingStats"]
