"""Streaming at fleet scale — N sharded trainer consumers + per-model
serving adoption.

PR 15's loop is one trainer on one stream; under real traffic that
single consumer IS the freshness bottleneck. This module shards it: a
:class:`StreamingFleet` supervisor spawns N shared-nothing trainer
*processes* over one partitioned stream (``?partitions=N`` at the
producer routes every record by its stamped key, ``?partition=k`` at
consumer ``k`` claims only its shard — different partitions are
different sub-streams, so claims are disjoint by construction, not by
consumer-group luck), each running the PR-15 windowed loop and
committing cursor-carrying checkpoints into its OWN per-partition
namespace ``<root>/p<k>``. The serving side
(:class:`FleetReloaders`) runs one CheckpointWatcher per partition
namespace, adopting the freshest *committed* step per model — never an
older one (the watcher's monotonic-adoption invariant) — optionally
through a per-model :class:`~analytics_zoo_tpu.streaming.guardrail.
GuardrailEvaluator` that rejects regressions before they reach traffic.

Topology::

    producer --(key hash)--> stream.p0 --> trainer-0 --> root/p0 \\
    producer --(key hash)--> stream.p1 --> trainer-1 --> root/p1 --+--> FleetReloaders
    producer --(key hash)--> stream.pN --> trainer-N --> root/pN /     (guard -> adopt
                                                                        per model)

Freshness math (docs/performance_notes.md PR-19): at a fixed aggregate
ingest rate R, each of N consumers sees R/N — so the per-consumer
``window_records`` must scale as ``aggregate_window / N`` (or windows
must be age-closed) for window close time, and therefore freshness, to
stay flat going 1 -> N. The supervisor only shards and supervises; it
holds no state a consumer crash can lose — a SIGKILLed trainer's
unacked claims sit in its partition's PEL until the respawned process
(same partition, cursor resumed from the per-partition checkpoint)
replays them into byte-identical windows.
"""

from __future__ import annotations

import json
import logging
import multiprocessing as mp
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..common import knobs as _knobs
from ..obs import trace as _trace
from ..obs.registry import REGISTRY
from ..serving.fleet import _dumps, _loads
from ..serving.queue_api import make_broker, partitioned_spec
from ..shm import sweep_spec as _shm_sweep_spec
from .guardrail import GuardrailEvaluator
from .serve import StreamingReloader
from .source import StreamingXShards
from .stats import StreamingStats
from .trainer import StreamingTrainer

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["StreamingFleet", "FleetReloaders", "linear_estimator_factory"]

#: per-consumer freshness buckets (seconds): streaming adoption on a warm
#: loop lands well under a second; the tail buckets catch stalls
_FRESHNESS_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0)


def linear_estimator_factory(dim: int = 8, seed: int = 0,
                             lr: float = 0.05):
    """Module-level toy-estimator factory (plain-pickleable by reference
    through ``functools.partial`` — the spawn boundary re-imports this
    module in the child): a Dense(1) regressor, the benches' and tests'
    stand-in for a real per-partition model."""
    import flax.linen as nn

    from ..orca.learn.estimator import TPUEstimator
    from ..orca.learn.optimizers import Adam

    class _Linear(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)[:, 0]

    return TPUEstimator(_Linear(), loss="mse", optimizer=Adam(lr=lr),
                        seed=seed)


def _consumer_main(factory_blob: bytes, queue_spec: str, partition: int,
                   root: str, cfg_json: str):
    """Entry point of one fleet trainer process (spawn target): build the
    estimator from the pickled factory, consume partition ``k``'s
    sub-stream through the PR-15 windowed loop, commit into
    ``<root>/p<k>``, heartbeat through the partition broker, stop
    gracefully on SIGTERM (the commit protocol makes ANY exit point
    replay-safe — SIGKILL included, which is the chaos gate)."""
    cfg = json.loads(cfg_json)
    for k, v in (cfg.get("env") or {}).items():
        os.environ[k] = str(v)
    if _knobs.get("ZOO_TRACE"):
        _trace.arm()
    stop_ev = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_ev.set())
    consumer_id = f"t{partition}"
    est = _loads(factory_blob)()
    src = StreamingXShards(
        partitioned_spec(queue_spec, partition),
        batch_size=int(cfg["batch_size"]),
        window_records=cfg.get("window_records"),
        window_age_s=cfg.get("window_age_s"),
        poll_timeout_s=cfg.get("poll_timeout_s"))
    model_dir = os.path.join(root, f"p{partition}")
    trainer = StreamingTrainer(est, src, model_dir,
                               commit_blocking=bool(
                                   cfg.get("commit_blocking", False)))
    resumed = trainer.resume()
    logger.info("stream-fleet consumer %s up (pid=%d, partition=%d, "
                "resumed=%s)", consumer_id, os.getpid(), partition, resumed)

    def _hb_doc(final: bool = False):
        snap = src.stats.snapshot()
        return {"partition": partition,
                "final": final,
                "windows": snap.get("windows", 0),
                "records_trained": snap.get("records_trained", 0),
                "records_deduped": snap.get("records_deduped", 0),
                "recompiles_after_warm":
                    snap.get("recompiles_after_warm", 0),
                "last_commit_step": snap.get("last_commit_step"),
                "reclaimed": int(getattr(src.broker, "reclaimed", 0)),
                # commit lag: newest trained event time -> now; the
                # supervisor-side (pre-adoption) freshness signal
                "commit_lag_s": (
                    round(time.time() - trainer.cursor.event_time_max, 3)
                    if trainer.cursor.event_time_max else None)}

    def _beat():
        while not hb_stop.wait(float(cfg.get("heartbeat_s", 0.5))):
            try:
                src.broker.heartbeat(consumer_id, _hb_doc())
            except Exception as e:  # noqa: BLE001 — liveness is advisory
                logger.debug("stream-fleet heartbeat failed: %s", e)

    hb_stop = threading.Event()
    hb = threading.Thread(target=_beat, daemon=True,
                          name=f"stream-hb-{consumer_id}")
    hb.start()
    try:
        trainer.run(max_windows=cfg.get("max_windows"),
                    idle_timeout_s=cfg.get("idle_timeout_s"),
                    stop=stop_ev)
    finally:
        hb_stop.set()
        try:
            # one FINAL beat instead of a clear: a graceful exit must not
            # erase its terminal stats before the supervisor's last
            # sample — the entry ages out through the liveness TTL, and a
            # respawn onto the partition overwrites the same key
            src.broker.heartbeat(consumer_id, _hb_doc(final=True))
        except Exception as e:  # noqa: BLE001 — broker may be gone
            logger.debug("stream-fleet final heartbeat failed: %s", e)
        est.shutdown()
        trace_dir = cfg.get("trace_dir")
        if trace_dir:
            from ..serving.fleet import _dump_spans
            _dump_spans(trace_dir, consumer_id)


class StreamingFleet:
    """Supervisor for N shared-nothing trainer consumers over one
    partitioned stream.

    ``estimator_factory`` is a zero-arg picklable callable returning a
    fresh ``TPUEstimator`` (every consumer builds its OWN — nothing is
    shared but the stream spec and the checkpoint root). ``queue`` must
    be a cross-process spec (``file://`` or ``redis://``); partition
    sub-streams are derived from it, so producers enqueue through
    ``make_broker(queue + "?partitions=N")`` and route by record key.

    The monitor thread reaps dead consumers and respawns them onto the
    SAME partition — the respawn resumes from the per-partition
    checkpoint cursor and replays its partition's PEL, which is the
    whole crash-recovery story (no rebalancing: partition count is
    fixed at fleet size, the deterministic-replay contract's price).
    """

    def __init__(self, estimator_factory: Callable[[], Any], queue: str,
                 root: str, *,
                 consumers: Optional[int] = None,
                 batch_size: int = 32,
                 window_records: Optional[int] = None,
                 window_age_s: Optional[float] = None,
                 poll_timeout_s: Optional[float] = None,
                 max_windows: Optional[int] = None,
                 idle_timeout_s: Optional[float] = None,
                 commit_blocking: bool = False,
                 heartbeat_s: float = 0.5,
                 consumer_ttl_s: float = 3.0,
                 poll_s: float = 0.25,
                 worker_env: Optional[Dict[str, str]] = None,
                 trace_dir: Optional[str] = None,
                 mp_start: str = "spawn"):
        if not isinstance(queue, str) or queue.startswith("memory://"):
            raise ValueError(
                "StreamingFleet needs a cross-process queue spec "
                f"(file:// or redis://), got {queue!r} — memory:// lives "
                "in one process")
        self.queue = queue
        self.root = root
        self.consumers = int(_knobs.get("ZOO_STREAM_CONSUMERS")
                             if consumers is None else consumers)
        if self.consumers < 1:
            raise ValueError(f"consumers must be >= 1, "
                             f"got {self.consumers}")
        self._factory_blob = _dumps(estimator_factory)
        self.heartbeat_s = float(heartbeat_s)
        self.consumer_ttl_s = float(consumer_ttl_s)
        self.poll_s = float(poll_s)
        self._cfg = {
            "batch_size": int(batch_size),
            "window_records": window_records,
            "window_age_s": window_age_s,
            "poll_timeout_s": poll_timeout_s,
            "max_windows": max_windows,
            "idle_timeout_s": idle_timeout_s,
            "commit_blocking": commit_blocking,
            "heartbeat_s": self.heartbeat_s,
            "env": dict(worker_env or {}),
            "trace_dir": trace_dir,
        }
        # the aggregate view: partitioned router over all sub-streams
        # (pending/oldest_age merge across partitions; live_workers
        # merges every consumer's heartbeat). partitioned_spec appends
        # its pin last, so swapping the tail yields the fan-out form.
        pinned = partitioned_spec(queue, 0)
        self.router = make_broker(pinned[:-len("partition=0")]
                                  + f"partitions={self.consumers}")
        self._ctx = mp.get_context(mp_start)
        self._procs: Dict[int, Any] = {}
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._last_stats: Dict[str, Dict] = {}
        self.restarts = 0

    # --- lifecycle ----------------------------------------------------------
    def partition_root(self, partition: int) -> str:
        """The checkpoint namespace consumer ``partition`` commits into
        (what a per-model reloader watches)."""
        return os.path.join(self.root, f"p{int(partition)}")

    def start(self) -> "StreamingFleet":
        os.makedirs(self.root, exist_ok=True)
        for k in range(self.consumers):
            self._spawn(k)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="stream-fleet-monitor")
        self._monitor.start()
        return self

    def _spawn(self, partition: int):
        p = self._ctx.Process(
            target=_consumer_main,
            args=(self._factory_blob, self.queue, partition, self.root,
                  json.dumps(self._cfg)),
            daemon=True, name=f"stream-consumer-t{partition}")
        p.start()
        self._procs[partition] = p
        logger.info("stream-fleet: spawned consumer t%d (pid=%d)",
                    partition, p.pid)

    def _monitor_loop(self):
        while not self._stop.wait(self.poll_s):
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — supervisor must not die
                logger.warning("stream-fleet monitor tick failed: %s", e)

    def _tick(self):
        with self._lock:
            dead_pids: List[int] = []
            for k, p in list(self._procs.items()):
                if p.is_alive():
                    continue
                p.join(timeout=0)
                del self._procs[k]
                if p.pid is not None:
                    dead_pids.append(p.pid)
                if self._stop.is_set():
                    continue
                if p.exitcode == 0:
                    # clean exit: the consumer finished its bounded run
                    # (max_windows / idle timeout) — completion, not a
                    # crash; respawning it would churn forever
                    logger.info("stream-fleet: consumer t%d completed",
                                k)
                    continue
                # a consumer CRASHED (SIGKILL, OOM, bug): respawn it onto
                # the SAME partition — the per-partition cursor + PEL
                # replay make the restart bit-exact
                self.restarts += 1
                logger.warning(
                    "stream-fleet: consumer t%d died (exitcode=%s) — "
                    "respawning onto its partition", k, p.exitcode)
                self._spawn(k)
            if dead_pids:
                # shm object plane: a SIGKILLed consumer's slab pins die
                # with its pid — sweep its lease files; its unacked claims
                # replay into the respawn and re-resolve still-live blobs
                try:
                    out = _shm_sweep_spec(self.queue, dead_pids)
                    if out.get("leases_swept") or out.get("freed"):
                        logger.info(
                            "stream-fleet: shm sweep after reap: %s", out)
                except Exception as e:  # noqa: BLE001 — sweep is recovery
                    logger.warning(
                        "stream-fleet: shm sweep failed: %s", e)
            try:
                for cid, s in self.router.live_workers(
                        self.consumer_ttl_s).items():
                    self._last_stats[cid] = s
            except Exception as e:  # noqa: BLE001 — broker blip
                logger.debug("stream-fleet: live_workers probe "
                             "failed: %s", e)

    def wait_live(self, n: Optional[int] = None,
                  timeout_s: float = 60.0) -> bool:
        """Block until >= n consumers (default: all) heartbeat as
        live."""
        need = self.consumers if n is None else int(n)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            try:
                if len(self.router.live_workers(
                        self.consumer_ttl_s)) >= need:
                    return True
            except Exception as e:  # noqa: BLE001 — broker warming up
                logger.debug("stream-fleet: wait_live probe failed: %s", e)
            time.sleep(0.05)
        return False

    def kill_consumer(self, partition: int) -> bool:
        """SIGKILL one consumer (chaos surface: no drain, no ack — its
        partition's unacked claims must replay through the PEL into the
        respawned process)."""
        with self._lock:
            p = self._procs.get(int(partition))
            if p is None or not p.is_alive():
                return False
            p.kill()
            logger.info("stream-fleet: SIGKILLed consumer t%d (chaos)",
                        partition)
            return True

    def alive(self) -> int:
        with self._lock:
            return sum(1 for p in self._procs.values() if p.is_alive())

    def join(self, timeout_s: float = 120.0) -> bool:
        """Wait for every consumer process to exit on its own (bounded
        runs: ``max_windows``/``idle_timeout_s`` set). False on
        timeout."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.alive() == 0:
                return True
            time.sleep(0.05)
        return False

    def metrics(self) -> Dict:
        with self._lock:
            stats = {c: dict(s) for c, s in self._last_stats.items()}
        return {
            "consumers": self.consumers,
            "alive": self.alive(),
            "restarts": self.restarts,
            "windows_total": sum(
                int(s.get("windows", 0)) for s in stats.values()),
            "records_trained_total": sum(
                int(s.get("records_trained", 0)) for s in stats.values()),
            "reclaimed_total": sum(
                int(s.get("reclaimed", 0)) for s in stats.values()),
            "per_consumer": stats,
        }

    def stop(self, timeout_s: float = 30.0) -> Dict:
        """Graceful shutdown: SIGTERM every consumer (each finishes its
        in-flight window commit), join, return final metrics."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        with self._lock:
            procs = dict(self._procs)
        # final heartbeat merge BEFORE the consumers clear their entries
        try:
            for cid, s in self.router.live_workers(
                    max(self.consumer_ttl_s, 60.0)).items():
                self._last_stats[cid] = s
        except Exception as e:  # noqa: BLE001 — broker may be gone
            logger.debug("stream-fleet: final heartbeat sample "
                         "failed: %s", e)
        for p in procs.values():
            if p.is_alive():
                p.terminate()
        deadline = time.time() + timeout_s
        for p in procs.values():
            p.join(timeout=max(0.1, deadline - time.time()))
        for k, p in procs.items():
            if p.is_alive():
                logger.warning("stream-fleet: consumer t%d ignored "
                               "SIGTERM — SIGKILL", k)
                p.kill()
                p.join(timeout=2)
        # final shm sweep: no consumer pid survives stop()
        try:
            _shm_sweep_spec(self.queue,
                            [p.pid for p in procs.values()
                             if p.pid is not None])
        except Exception as e:  # noqa: BLE001 — sweep is best-effort
            logger.warning("stream-fleet: shm sweep on stop failed: %s", e)
        snap = self.metrics()
        logger.info("stream-fleet stopped: %s", {
            k: snap[k] for k in ("consumers", "windows_total",
                                 "records_trained_total", "restarts")})
        return snap


class FleetReloaders:
    """Serving-side adoption for a partitioned checkpoint root: one
    :class:`StreamingReloader` per partition namespace, each hot-swapping
    its model's freshest *committed* step (monotonic — never an older
    one) and observing per-consumer freshness into the
    ``zoo_stream_fleet_freshness_s`` histogram (labels: ``inst``,
    ``consumer``).

    ``models`` maps partition index -> serving model (the
    ``apply_checkpoint`` surface); ``guards`` optionally maps partition
    index -> :class:`GuardrailEvaluator`, giving each model its own
    adoption gate (a regression on one cohort must not block the
    others' reloads).
    """

    def __init__(self, models: Dict[int, Any], root: str, *,
                 poll_s: float = 0.5,
                 guards: Optional[Dict[int, GuardrailEvaluator]] = None,
                 start_at: Optional[int] = None):
        self._hist = REGISTRY.histogram(
            "zoo_stream_fleet_freshness_s",
            "per-consumer freshness lag (newest trained event time -> "
            "serving adoption) across a streaming fleet's partitions",
            labelnames=("inst", "consumer"),
            buckets=_FRESHNESS_BUCKETS)
        self._inst = f"{id(self):x}"
        self.reloaders: Dict[int, StreamingReloader] = {}
        for k, model in models.items():
            child = self._hist.labels(inst=self._inst,
                                      consumer=f"t{int(k)}")
            self.reloaders[int(k)] = StreamingReloader(
                model, os.path.join(root, f"p{int(k)}"), poll_s=poll_s,
                start_at=start_at, stats=_ConsumerStats(child),
                guard=(guards or {}).get(int(k)))

    def start(self) -> "FleetReloaders":
        for r in self.reloaders.values():
            r.start()
        return self

    def stop(self):
        for r in self.reloaders.values():
            r.stop()
        for k in self.reloaders:
            self._hist.remove(inst=self._inst, consumer=f"t{k}")

    def poll_now(self) -> int:
        """One synchronous adoption check on every partition; returns how
        many adopted a newer step."""
        return sum(1 for r in self.reloaders.values() if r.poll_now())

    # --- telemetry ----------------------------------------------------------
    def freshness_p99_by_consumer(self) -> Dict[int, Optional[float]]:
        import numpy as np
        out: Dict[int, Optional[float]] = {}
        for k, r in self.reloaders.items():
            s = r.freshness_samples
            out[k] = float(np.percentile(s, 99)) if s else None
        return out

    def snapshot(self) -> Dict[int, Dict]:
        return {k: r.stats.snapshot() for k, r in self.reloaders.items()}


class _ConsumerStats(StreamingStats):
    """Per-partition reloader stats that mirror every freshness sample
    into the fleet histogram child for this consumer label."""

    def __init__(self, hist_child):
        super().__init__(register=False)
        self._hist_child = hist_child

    def observe_freshness(self, lag_s: float):
        super().observe_freshness(lag_s)
        self._hist_child.observe(float(lag_s))
