"""Online-eval guardrail — the adoption gate in front of hot reload.

At fleet scale a bad window (poisoned labels, a cohort drifting into
garbage, a partition replaying shed data) produces a *committed*
checkpoint like any good window does; without a gate the reloader would
swap it into live traffic within one poll. The guardrail scores every
commit on a **sliding holdout window** of recent labeled records before
serving adopts it, and rejects adoption on regression through the PR-15
rejected-step path: the :class:`~analytics_zoo_tpu.ckpt.watch.
CheckpointWatcher` treats a callback raise as "skip this step forever",
so a rejected commit can never reach live traffic — while the trainer
keeps going, and the NEXT commit is judged on its own merits
(reject-then-later-accept is the expected recovery shape).

Verdict semantics (:meth:`GuardrailEvaluator.verdict` — a pure function
of the score trace, unit-testable without a model):

* ``accept``  — score within ``regression`` of the baseline (the best
  score among the last ``baseline_window`` *accepted* commits; rejected
  scores never pollute the baseline, or one bad window would ratchet
  the bar down and auto-accept its successors);
* ``reject``  — score worse than ``baseline * (1 + regression)``
  (scores are losses: lower is better);
* ``insufficient`` — fewer than ``min_holdout`` holdout records exist;
  the commit is adopted (blocking serving on a cold holdout would stall
  bootstrap) but counted, so operators see how often the gate was open.

The holdout itself is fed by :meth:`observe` (typically a tap on the
producer or a dedicated eval stream) and scored by a pluggable
``scorer`` — :func:`module_loss_scorer` builds one from a flax module,
evaluating the *candidate* checkpoint's params without touching the
live model's weights.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional, Tuple

import numpy as np

from ..common import knobs as _knobs
from .stats import StreamingStats

__all__ = ["ACCEPT", "REJECT", "INSUFFICIENT", "GuardrailRejected",
           "GuardrailEvaluator", "module_loss_scorer"]

ACCEPT = "accept"
REJECT = "reject"
INSUFFICIENT = "insufficient"


class GuardrailRejected(RuntimeError):
    """Raised by the reloader callback on a ``reject`` verdict — the
    CheckpointWatcher's rejected-step path turns it into a permanent
    skip of that step."""


def module_loss_scorer(module, loss: str = "mse") -> Callable:
    """A scorer evaluating ``module`` under a candidate checkpoint's
    params on the holdout batch. Plain (unjitted) apply: the holdout is
    small and an eval program must not enter the compile-plane caches
    the zero-recompile gates count."""
    if loss != "mse":
        raise ValueError(f"module_loss_scorer supports mse, got {loss!r}")

    def score(state, xs, ys) -> float:
        pred = module.apply({"params": state["params"]}, *xs)
        return float(np.mean((np.asarray(pred) - np.asarray(ys[0])) ** 2))

    return score


class GuardrailEvaluator:
    """Score-every-commit gate with a sliding holdout window.

    ``scorer(state, xs, ys) -> float`` gets the candidate checkpoint's
    state and the stacked holdout columns; lower is better (a loss).
    Thread-safe: the producer tap (:meth:`observe`) and the watcher
    thread (:meth:`evaluate`) run concurrently.
    """

    def __init__(self, scorer: Optional[Callable] = None, *,
                 holdout_records: Optional[int] = None,
                 min_holdout: Optional[int] = None,
                 regression: Optional[float] = None,
                 baseline_window: Optional[int] = None,
                 stats: Optional[StreamingStats] = None):
        self.scorer = scorer
        self.holdout_records = int(
            holdout_records if holdout_records is not None
            else _knobs.get("ZOO_STREAM_GUARD_HOLDOUT"))
        self.min_holdout = int(
            min_holdout if min_holdout is not None
            else _knobs.get("ZOO_STREAM_GUARD_MIN_HOLDOUT"))
        self.regression = float(
            regression if regression is not None
            else _knobs.get("ZOO_STREAM_GUARD_REGRESSION"))
        self.baseline_window = int(
            baseline_window if baseline_window is not None
            else _knobs.get("ZOO_STREAM_GUARD_BASELINE_WINDOW"))
        if self.holdout_records < 1 or self.min_holdout < 1 \
                or self.baseline_window < 1:
            raise ValueError(
                "guardrail sizes (holdout_records, min_holdout, "
                "baseline_window) must all be >= 1")
        self.stats = stats if stats is not None else StreamingStats(
            register=False)
        self._lock = threading.Lock()
        self._holdout: deque = deque(maxlen=self.holdout_records)
        self._accepted: deque = deque(maxlen=self.baseline_window)
        self.last_score: Optional[float] = None
        self.last_verdict: Optional[str] = None

    # --- holdout feed -------------------------------------------------------
    def observe(self, x, y) -> None:
        """Add one labeled holdout example (per-example shapes, like
        ``encode_record``); the deque slides, keeping the newest
        ``holdout_records`` — the gate judges against *recent* truth, not
        the whole history."""
        xs = x if isinstance(x, tuple) else (x,)
        ys = y if isinstance(y, tuple) else (y,)
        with self._lock:
            self._holdout.append((tuple(np.asarray(a) for a in xs),
                                  tuple(np.asarray(a) for a in ys)))

    def observe_record(self, raw: bytes) -> None:
        """Tap an encoded stream record into the holdout (labelless
        records are ignored — there is nothing to score against)."""
        from .records import decode_record
        xs, ys, _ = decode_record(raw)
        if ys is not None:
            # copy out of the zero-copy views: the holdout outlives raw
            self.observe(tuple(np.array(a) for a in xs),
                         tuple(np.array(a) for a in ys))

    @property
    def holdout_size(self) -> int:
        with self._lock:
            return len(self._holdout)

    def _stacked(self) -> Optional[Tuple[tuple, tuple]]:
        with self._lock:
            if not self._holdout:
                return None
            recs = list(self._holdout)
        nx, ny = len(recs[0][0]), len(recs[0][1])
        xs = tuple(np.stack([r[0][i] for r in recs]) for i in range(nx))
        ys = tuple(np.stack([r[1][i] for r in recs]) for i in range(ny))
        return xs, ys

    # --- the decision -------------------------------------------------------
    def baseline(self) -> Optional[float]:
        """Best (lowest) score among recently accepted commits, None
        before the first accept."""
        with self._lock:
            return min(self._accepted) if self._accepted else None

    def verdict(self, score: float,
                holdout_n: Optional[int] = None) -> str:
        """Judge one commit score. Pure given (score trace, holdout
        size) — the unit tests drive this directly with synthetic
        traces. Counts the outcome on :attr:`stats`."""
        n = self.holdout_size if holdout_n is None else int(holdout_n)
        if n < self.min_holdout:
            self.stats.add(guard_insufficient=1)
            self.last_verdict = INSUFFICIENT
            return INSUFFICIENT
        with self._lock:
            base = min(self._accepted) if self._accepted else None
            if base is not None and score > base * (1.0 + self.regression):
                out = REJECT
            else:
                out = ACCEPT
                self._accepted.append(float(score))
        if out is REJECT:
            self.stats.add(guard_rejected=1)
        else:
            self.stats.add(guard_accepted=1)
        self.last_verdict = out
        return out

    def evaluate(self, state, step: int
                 ) -> Tuple[str, Optional[float]]:
        """Score a candidate checkpoint ``state`` on the current holdout
        and judge it: ``(verdict, score)``. Needs a ``scorer``; without
        holdout data the verdict is ``insufficient`` (adopt + count)."""
        if self.scorer is None:
            raise ValueError("GuardrailEvaluator.evaluate needs a scorer "
                             "(see module_loss_scorer)")
        stacked = self._stacked()
        if stacked is None or self.holdout_size < self.min_holdout:
            self.stats.add(guard_insufficient=1)
            self.last_verdict = INSUFFICIENT
            self.last_score = None
            return INSUFFICIENT, None
        score = float(self.scorer(state, *stacked))
        self.last_score = score
        return self.verdict(score), score
