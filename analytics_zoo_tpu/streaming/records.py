"""Training-record wire format for the streaming plane.

One stream entry = one training example: a tuple of feature arrays, an
optional tuple of label arrays, and an **event time** (seconds since the
epoch, stamped by the producer). The encoding is a small JSON header plus
the raw C-contiguous array bytes — no pyarrow/pickle on the hot ingest
path, and decode never copies (each leaf is a frombuffer view reshaped).

Record **ids** are the streaming cursor's unit of progress: the cursor
stores the id of the last *trained* record, and replayed entries with an
id at or below it are deduplicated (see ``source.py``). That only works
if ids are lexicographically monotonic in stream order — :func:`seq_id`
renders a producer sequence number into such an id; producers with their
own id scheme must preserve the same property (documented in
``docs/guides/streaming.md``, "cursor contract").

Records may additionally carry a **key** (``encode_record(key=...)``) —
the sharding handle of the fleet-scale plane: a producer stamps each
record with its routing identity (model name, user cohort, series id)
and :func:`partition_for` maps it deterministically onto one of N
partitions. The hash is CRC32, NOT Python ``hash()``: every producer
and consumer process must agree on the mapping across interpreter
restarts and hosts (PYTHONHASHSEED randomizes ``hash()`` per process).
:func:`record_key` reads the key header-only — the partition router on
the enqueue hot path never touches the array payload.
"""

from __future__ import annotations

import json
import zlib
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["encode_record", "decode_record", "decode_ref", "seq_id",
           "record_key", "partition_for"]

_MAGIC = b"ZSR1"
_SHM_MAGIC = b"ZSHM1"


def seq_id(seq: int) -> str:
    """A record id for producer sequence number ``seq`` that sorts
    lexicographically in numeric order (20 digits covers int64)."""
    if seq < 0:
        raise ValueError(f"record sequence must be >= 0, got {seq}")
    return f"{int(seq):020d}"


def _contig(a) -> np.ndarray:
    # NOT ascontiguousarray: that promotes 0-d scalars to 1-d, and a
    # scalar label must round-trip as a scalar (stacked batches rely on
    # per-record shapes being exact)
    a = np.asarray(a)
    return a if a.flags.c_contiguous else np.ascontiguousarray(a)


def _as_tuple(v) -> Tuple[np.ndarray, ...]:
    if v is None:
        return ()
    if isinstance(v, (list, tuple)):
        return tuple(_contig(a) for a in v)
    return (_contig(v),)


def encode_record(x, y=None, event_time: Optional[float] = None,
                  key: Optional[str] = None) -> bytes:
    """Encode one training example. ``x``/``y`` are arrays or tuples of
    arrays (per-example shape, no batch dim); ``event_time`` defaults to
    0.0 — producers should stamp their own clock so freshness lag is
    measured from the event, not from ingestion. ``key`` is the optional
    routing identity (:func:`partition_for` shards on it); keyless
    records fall back to id-hash routing at the partitioned broker."""
    xs, ys = _as_tuple(x), _as_tuple(y)
    header = {
        "t": float(event_time) if event_time is not None else 0.0,
        "x": [{"shape": list(a.shape), "dtype": a.dtype.str} for a in xs],
        "y": ([{"shape": list(a.shape), "dtype": a.dtype.str} for a in ys]
              if y is not None else None),
    }
    if key is not None:
        header["k"] = str(key)
    head = json.dumps(header, separators=(",", ":")).encode("utf-8")
    parts = [_MAGIC, len(head).to_bytes(4, "big"), head]
    for a in xs + ys:
        parts.append(a.tobytes())
    return b"".join(parts)


def decode_record(raw
                  ) -> Tuple[Tuple[np.ndarray, ...],
                             Optional[Tuple[np.ndarray, ...]], float]:
    """Decode :func:`encode_record` bytes -> (x_tuple, y_tuple|None,
    event_time). Leaves are zero-copy views into ``raw``, which may be
    any buffer — bytes, a memoryview of a received frame, or a mapped
    shared-memory slab — sliced via frombuffer, never via ``bytes()``
    materialization (only the few-hundred-byte JSON header is copied to
    parse)."""
    if not isinstance(raw, (bytes, bytearray)):
        raw = memoryview(raw).cast("B")
    if bytes(raw[:4]) != _MAGIC:
        raise ValueError("not a streaming record (bad magic)")
    hlen = int.from_bytes(raw[4:8], "big")
    header = json.loads(bytes(raw[8:8 + hlen]).decode("utf-8"))
    off = 8 + hlen

    def take(specs: Sequence[dict]) -> Tuple[np.ndarray, ...]:
        nonlocal off
        out = []
        for spec in specs:
            dt = np.dtype(spec["dtype"])
            shape = tuple(spec["shape"])
            n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
            out.append(np.frombuffer(raw, dt, count=max(
                n // dt.itemsize, 0), offset=off).reshape(shape))
            off += n
        return tuple(out)

    xs = take(header["x"])
    ys = take(header["y"]) if header["y"] is not None else None
    return xs, ys, float(header["t"])


def decode_ref(raw, arena=None):
    """Decode a broker payload that may be a shm descriptor envelope:
    returns ``(x_tuple, y_tuple|None, event_time, ref)``. A descriptor
    frame maps the slab read-only (zero copy — the leaves are frombuffer
    views straight into shared memory, C-contiguous, ready for
    ``sharded_put``) and the caller owes ``arena.done(ref)`` after the
    entry is acked; inline frames and legacy payloads decode exactly as
    :func:`decode_record` with ``ref None``."""
    from ..shm import resolve_blob
    buf, ref = resolve_blob(raw, arena)
    x, y, et = decode_record(buf)
    return x, y, et, ref


def record_key(raw) -> Optional[str]:
    """The routing key of an encoded record, or None when the producer
    stamped none. Header-only: the partition router calls this once per
    enqueue and must not pay an array decode — nor a payload copy:
    ``raw`` may be any buffer and only the header bytes are touched.
    Descriptor envelopes (shm plane) carry the key in the envelope
    header, so sharding survives the descriptor wire."""
    if not isinstance(raw, (bytes, bytearray)):
        raw = memoryview(raw).cast("B")
    if bytes(raw[:5]) == _SHM_MAGIC:
        from ..shm import envelope_key
        return envelope_key(raw)
    if bytes(raw[:4]) != _MAGIC:
        raise ValueError("not a streaming record (bad magic)")
    hlen = int.from_bytes(raw[4:8], "big")
    k = json.loads(bytes(raw[8:8 + hlen]).decode("utf-8")).get("k")
    return None if k is None else str(k)


def partition_for(key: str, n_partitions: int) -> int:
    """Deterministic key -> partition index in ``[0, n_partitions)``.

    CRC32 of the UTF-8 key, mod N — stable across processes, hosts and
    interpreter restarts (unlike ``hash()``, which PYTHONHASHSEED salts
    per process), so every producer routes a key to the same partition
    and every consumer's cursor stays meaningful across restarts."""
    n = int(n_partitions)
    if n <= 0:
        raise ValueError(f"n_partitions must be >= 1, got {n_partitions}")
    return zlib.crc32(str(key).encode("utf-8")) % n
