"""Serving side of the streaming plane — hot-reload with freshness
accounting.

A :class:`StreamingReloader` wraps the checkpoint plane's
``CheckpointWatcher`` (PR 6) around a live
:class:`~analytics_zoo_tpu.pipeline.inference.inference_model.
InferenceModel` (or a ``ClusterServing`` engine's model): each newly
committed streaming checkpoint is hot-swapped into the serving weights —
same-shape swaps touch no compiled executable, so reloads cost zero new
compiles — and the manifest's stream cursor turns into the plane's SLO
number: **freshness lag**, event time of the newest trained record ->
wall clock when serving adopted it. The manifest's trace token chains the
``stream.reload`` span under the producing window's trace, closing the
ingest -> train -> commit -> serve timeline across the process boundary.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..ckpt import format as ckpt_fmt
from ..ckpt.watch import CheckpointWatcher
from ..obs import trace as _trace
from .guardrail import REJECT, GuardrailEvaluator, GuardrailRejected
from .stats import StreamingStats

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["StreamingReloader"]


class StreamingReloader:
    """Watch ``root`` and hot-swap committed streaming checkpoints into a
    live serving model.

    ``model`` needs the ``InferenceModel`` adoption surface
    (``apply_checkpoint(path, state, step)``); ``ClusterServing`` callers
    pass their engine's model. ``start_at`` defaults to the step the
    model bootstrapped from (``load_checkpoint``), so a server never
    re-adopts the checkpoint it already serves — with streaming commit
    cadences the watcher usually polls *faster* than commits land, and
    the PR-6 skip logic plus the watcher's delivery lock keep every step
    adopted exactly once.

    ``guard`` is an optional
    :class:`~analytics_zoo_tpu.streaming.guardrail.GuardrailEvaluator`:
    every commit is scored on its holdout window BEFORE adoption, and a
    ``reject`` verdict raises through the watcher's rejected-step path —
    the step is skipped forever (no ``stream.reload`` span ever opens for
    it), the next commit is judged on its own merits, and the
    ``guard_rejected`` counter ticks on this reloader's stats.
    """

    def __init__(self, model, root: str, *, poll_s: float = 1.0,
                 passphrase: Optional[str] = None,
                 start_at: Optional[int] = None,
                 stats: Optional[StreamingStats] = None,
                 guard: Optional[GuardrailEvaluator] = None):
        self.model = model
        self.root = root
        self.stats = stats if stats is not None else StreamingStats()
        self.guard = guard
        if guard is not None:
            # one counter surface: the guard's verdicts land on the same
            # stats object the reloader exposes to the obs registry
            guard.stats = self.stats
        if start_at is None:
            start_at = getattr(model, "_loaded_step", None)
        self.watcher = CheckpointWatcher(
            root, self._on_checkpoint, poll_s=poll_s,
            passphrase=passphrase, start_at=start_at)

    # --- the watcher callback ----------------------------------------------
    def _on_checkpoint(self, path: str, state, step: int):
        meta = ckpt_fmt.manifest_meta(path) if \
            ckpt_fmt.is_plane_dir(path) else {}
        tok = meta.get("trace")
        if self.guard is not None:
            with _trace.span_under(tok, "stream.guard", step=step) as g:
                verdict, score = self.guard.evaluate(state, step)
                g.set(verdict=verdict,
                      score=round(score, 6) if score is not None else None)
            if verdict is REJECT:
                # span-asserted contract: commit -> guard.reject, and NO
                # stream.reload span ever opens for this step — the raise
                # rides the watcher's rejected-step path (skip forever)
                with _trace.span_under(tok, "guard.reject", step=step):
                    logger.warning(
                        "guardrail rejected streaming commit step %d "
                        "(score=%.6g, baseline=%.6g): adoption skipped",
                        step, score, self.guard.baseline())
                raise GuardrailRejected(
                    f"step {step} regressed on the holdout window "
                    f"(score={score:.6g})")
        with _trace.span_under(tok, "stream.reload",
                               step=step) as span:
            adopt = getattr(self.model, "apply_checkpoint", None)
            if adopt is None:               # bare callback consumers
                adopt = self.model
            adopt(path, state, step)
            cursor = meta.get("stream") or {}
            et = cursor.get("event_time_max")
            if et:
                # the plane's SLO: newest trained event -> served, seconds
                lag = time.time() - float(et)
                self.stats.observe_freshness(lag)
                span.set(freshness_lag_s=round(lag, 3))
        self.stats.add(reloads=1, last_reload_step=int(step))

    # --- lifecycle ----------------------------------------------------------
    def start(self) -> "StreamingReloader":
        self.watcher.start()
        return self

    def stop(self):
        self.watcher.stop()

    def poll_now(self) -> bool:
        """One synchronous check (tests/rollouts); True when a newer
        checkpoint was adopted."""
        return self.watcher.poll_now()

    # --- telemetry ----------------------------------------------------------
    @property
    def reload_count(self) -> int:
        return int(self.stats.snapshot().get("reloads", 0))

    @property
    def freshness_samples(self):
        return list(self.stats.freshness_samples)

    def freshness_percentiles(self):
        """(p50, p99) of per-reload freshness lag in seconds, or (None,
        None) before the first reload."""
        import numpy as np
        s = self.freshness_samples
        if not s:
            return None, None
        return (float(np.percentile(s, 50)), float(np.percentile(s, 99)))
