"""StreamingXShards — tail a request stream into windowed ChunkedArray
micro-batches.

The reference platform's L2 data plane feeds live models from streaming
big-data pipelines (PAPER.md; Cluster Serving's Redis-stream ingestion).
This module is the training-side twin of the serving broker: records are
XADDed to a stream by producers (``records.encode_record`` payloads),
claimed here through the same broker/RESP2 transport serving uses
(``serving/queue_api.py`` — consumer groups, PEL + XAUTOCLAIM recovery,
reconnect-with-backoff, the ``broker.connect`` chaos site), and assembled
into **windows**: fixed-count micro-batch groups whose leaves are
:class:`~analytics_zoo_tpu.orca.data.chunked.ChunkedArray` columns, ready
for the zero-copy XShards training path.

Window semantics (docs/guides/streaming.md):

* **count windows** — a window closes when ``window_records`` records
  (rounded up to a whole number of training batches) have accumulated;
* **age windows** — an older-than-``window_age_s`` buffer closes early
  with the largest whole-batch prefix; the remainder leads the next
  window. A buffer smaller than one batch never closes (training a
  partial batch would compile a second executable — the zero-recompile
  contract pins one batch signature);
* **watermark + late records** — the watermark trails the max event time
  seen by ``watermark_s``; a record whose event time is behind it is
  late and is dropped (acked + counted) or included per ``late_policy``;
* **backlog shedding** — when the broker backlog exceeds
  ``max_backlog``, claimed records are acked unseen until the consumer
  has caught up (freshness over completeness; sheds are counted and
  break bit-exact replay, so the bound defaults high).

At-least-once + exactly-once application: records are acked only after
the window that trained them is durably committed (the trainer calls
:meth:`ack` post-commit), so a crash replays them through the PEL/
XAUTOCLAIM path; replayed ids at or below the cursor's ``last_id`` are
deduplicated here and acked immediately. Window composition is
deterministic in stream order, which makes a replayed run's windows —
and therefore its weights — byte-identical to the uninterrupted run's.
"""

from __future__ import annotations

import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common import knobs as _knobs
from ..obs import trace as _trace
from ..orca.data.chunked import ChunkedArray
from ..orca.data.shard import HostXShards
from ..serving.queue_api import Broker, make_broker
from ..shm import StaleObjectRef
from ..shm import arena_for_spec as _shm_arena_for_spec
from ..shm import peek_refs as _shm_peek_refs
from .records import decode_ref as decode_record_ref
from .stats import StreamingStats

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["StreamCursor", "Window", "StreamingXShards"]


@dataclass
class StreamCursor:
    """Resume point of the streaming loop — rides the checkpoint manifest
    (``meta["stream"]``) so a restart continues bit-exactly.

    * ``last_id`` — id of the last record whose window was trained AND
      committed; replayed entries at or below it are duplicates.
    * ``window`` — windows completed; doubles as the shuffle-epoch
      counter (``fit(initial_epoch=window)``), so with ``shuffle=True``
      a resumed window draws the same order the uninterrupted run did —
      together with the engine step (inside the same checkpoint) this is
      the loop's entire RNG state.
    * ``records`` / ``event_time_max`` — cumulative trained records and
      the newest trained event time (the freshness-lag reference point).
    """

    last_id: str = ""
    window: int = 0
    records: int = 0
    event_time_max: float = 0.0

    def to_dict(self) -> Dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "StreamCursor":
        return cls(last_id=str(d.get("last_id", "")),
                   window=int(d.get("window", 0)),
                   records=int(d.get("records", 0)),
                   event_time_max=float(d.get("event_time_max", 0.0)))


@dataclass
class Window:
    """One closed training window: records in stream order, assembled
    into ChunkedArray columns (one chunk per training batch)."""

    index: int
    ids: List[str]
    x: Tuple[ChunkedArray, ...]
    y: Optional[Tuple[ChunkedArray, ...]]
    event_time_min: float
    event_time_max: float

    @property
    def n(self) -> int:
        return len(self.ids)

    @property
    def last_id(self) -> str:
        return self.ids[-1]

    def chunked(self) -> Dict[str, Tuple[ChunkedArray, ...]]:
        out = {"x": self.x}
        if self.y is not None:
            out["y"] = self.y
        return out

    def to_xshards(self) -> HostXShards:
        """One dict shard per chunk, so the estimator's ``chunk_shards``
        rebuilds the same ChunkedArray columns without a merge copy."""
        parts = []
        for c in range(self.x[0].num_chunks):
            part = {"x": tuple(a.chunks[c] for a in self.x)}
            if self.y is not None:
                part["y"] = tuple(a.chunks[c] for a in self.y)
            parts.append(part)
        return HostXShards(parts)


class _PendingRecord:
    __slots__ = ("rid", "x", "y", "event_time")

    def __init__(self, rid, x, y, event_time):
        self.rid = rid
        self.x = x
        self.y = y
        self.event_time = event_time


class StreamingXShards:
    """Pull-mode window source over a serving broker.

    ``broker`` is a :class:`~analytics_zoo_tpu.serving.queue_api.Broker`
    or a spec string (``redis://host:port/stream``, ``memory://name``,
    ``file://dir``). Only the Redis transport gives at-least-once replay
    (PEL + XAUTOCLAIM); the in-memory/file brokers are at-most-once and
    suit tests and single-process demos.

    Knobs (all overridable per-instance): ``ZOO_STREAM_WINDOW_RECORDS``,
    ``ZOO_STREAM_WINDOW_AGE_S``, ``ZOO_STREAM_WATERMARK_S``,
    ``ZOO_STREAM_LATE_POLICY``, ``ZOO_STREAM_MAX_BACKLOG``,
    ``ZOO_STREAM_POLL_TIMEOUT_S``.
    """

    def __init__(self, broker, batch_size: int, *,
                 window_records: Optional[int] = None,
                 window_age_s: Optional[float] = None,
                 watermark_s: Optional[float] = None,
                 late_policy: Optional[str] = None,
                 max_backlog: Optional[int] = None,
                 poll_timeout_s: Optional[float] = None,
                 claim_size: int = 256,
                 stats: Optional[StreamingStats] = None):
        self.broker: Broker = (make_broker(broker) if isinstance(broker, str)
                               else broker)
        self.batch_size = int(batch_size)
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        wr = int(window_records if window_records is not None
                 else _knobs.get("ZOO_STREAM_WINDOW_RECORDS"))
        if wr % self.batch_size:
            rounded = -(-wr // self.batch_size) * self.batch_size
            logger.warning(
                "window_records %d rounded up to %d (a whole number of "
                "%d-row training batches keeps one batch signature — the "
                "zero-recompile contract)", wr, rounded, self.batch_size)
            wr = rounded
        self.window_records = max(wr, self.batch_size)
        self.window_age_s = float(
            window_age_s if window_age_s is not None
            else _knobs.get("ZOO_STREAM_WINDOW_AGE_S"))
        self.watermark_s = float(
            watermark_s if watermark_s is not None
            else _knobs.get("ZOO_STREAM_WATERMARK_S"))
        self.late_policy = str(
            late_policy if late_policy is not None
            else _knobs.get("ZOO_STREAM_LATE_POLICY"))
        if self.late_policy not in ("drop", "include"):
            raise ValueError(
                f"late_policy must be 'drop' or 'include', "
                f"got {self.late_policy!r}")
        self.max_backlog = int(
            max_backlog if max_backlog is not None
            else _knobs.get("ZOO_STREAM_MAX_BACKLOG"))
        self.poll_timeout_s = float(
            poll_timeout_s if poll_timeout_s is not None
            else _knobs.get("ZOO_STREAM_POLL_TIMEOUT_S"))
        self.claim_size = int(claim_size)
        self.stats = stats if stats is not None else StreamingStats()
        # decoded records awaiting a window close, in stream order; the
        # buffer survives an age-close (whole-batch prefix trains, the
        # tail leads the next window) but NOT a crash — unacked entries
        # replay through the PEL instead
        self._buf: List[_PendingRecord] = []
        self._buf_ids: set = set()
        self._buf_t0: Optional[float] = None    # wall clock of first buffer
        self._watermark = float("-inf")
        # acks owed for records consumed WITHOUT training (dedup replays,
        # late drops, backlog sheds) — flushed once per claim batch so the
        # overload-recovery path pays one batched XACK/XDEL, not two round
        # trips per record
        self._ack_buf: List[str] = []
        self._polls_since_backlog = 0
        # shm object plane: on a local ZOO_SHM-enabled stream record
        # payloads may arrive as slab descriptors — buffered records keep
        # their ref pinned until the window-commit ack done()s it
        self._arena = _shm_arena_for_spec(
            broker if isinstance(broker, str)
            else getattr(self.broker, "spec", None))
        self._refs: Dict[str, object] = {}

    # --- ingest -------------------------------------------------------------
    def _flush_acks(self):
        if not self._ack_buf:
            return
        rids, self._ack_buf = self._ack_buf, []
        try:
            self.broker.ack_many(rids)
        except Exception as e:      # noqa: BLE001 — ack is advisory here;
            # the entries stay pending and a later XAUTOCLAIM pass re-
            # delivers them into the dedup path, so progress is never
            # blocked
            logger.warning("streaming ack of %d consumed entries failed "
                           "(%s: %s); they will replay through the PEL",
                           len(rids), type(e).__name__, e)

    def _ref_done(self, ref) -> None:
        """Mark a slab descriptor consumed (no-op for inline/legacy)."""
        if ref is None or self._arena is None:
            return
        try:
            self._arena.done(ref)
        except Exception as e:      # noqa: BLE001 — freeing must not
            # stall ingest; a sweep/gc reclaims whatever this missed
            logger.warning("shm done failed for %s: %s", ref, e)

    def _peek_done(self, payload) -> None:
        """Consume-without-decode: mark the payload's descriptors done
        straight off the envelope header (dedup replays, backlog sheds —
        paths that never map the slab)."""
        if self._arena is None:
            return
        try:
            for ref in _shm_peek_refs(payload):
                self._arena.done(ref)
        except Exception as e:      # noqa: BLE001 — malformed frame
            logger.warning("shm peek failed: %s", e)

    def _ingest_one(self, rid: str, payload: bytes, cursor: StreamCursor,
                    shedding: bool) -> None:
        if rid <= cursor.last_id:
            # replayed entry whose window already trained AND committed:
            # ack and drop — exactly-once application
            self.stats.add(records_deduped=1)
            self._ack_buf.append(rid)
            self._peek_done(payload)
            return
        if rid in self._buf_ids:
            # the same entry delivered twice (XAUTOCLAIM re-stole it while
            # it sat in our buffer): drop the duplicate but do NOT ack —
            # the buffered copy is untrained, and an early ack would turn
            # a crash here into record loss. The window-commit ack clears
            # every pending delivery of the id at once. (Its slab ref is
            # the SAME blob the buffered copy holds pinned — nothing to do)
            self.stats.add(records_deduped=1)
            return
        if shedding:
            self.stats.add(records_shed=1)
            self._ack_buf.append(rid)
            self._peek_done(payload)
            return
        try:
            x, y, et, ref = decode_record_ref(payload, self._arena)
        except StaleObjectRef:
            # the blob was already consumed (a shed/drop's ack got lost and
            # the entry replayed past its freed slab): consume the
            # redelivery too — the record's consumption already happened
            self.stats.add(records_deduped=1)
            self._ack_buf.append(rid)
            return
        self._watermark = max(self._watermark, et - self.watermark_s)
        if et < self._watermark:
            if self.late_policy == "drop":
                self.stats.add(late_dropped=1)
                self._ack_buf.append(rid)
                self._ref_done(ref)
                return
            self.stats.add(late_included=1)
        if self._buf_t0 is None:
            self._buf_t0 = time.monotonic()
        self._buf.append(_PendingRecord(rid, x, y, et))
        self._buf_ids.add(rid)
        if ref is not None:
            self._refs[rid] = ref

    def _close_size(self) -> int:
        """Rows the current buffer may close with right now (0 = keep
        accumulating)."""
        n = len(self._buf)
        if n >= self.window_records:
            return self.window_records
        if (self._buf_t0 is not None and n >= self.batch_size
                and time.monotonic() - self._buf_t0 >= self.window_age_s):
            return (n // self.batch_size) * self.batch_size
        return 0

    def next_window(self, cursor: StreamCursor,
                    should_stop: Optional[Callable[[], bool]] = None,
                    idle_s: Optional[float] = None) -> Optional[Window]:
        """Block until a window closes (count reached, or age exceeded
        with at least one whole batch buffered). Returns None when
        ``should_stop`` fires, or when the stream goes IDLE — no new
        record for ``idle_s`` (the clock resets on every ingested
        record, so a live low-rate stream keeps the call alive).
        Buffered records stay claimed-but-unacked either way, so a
        restart replays them."""
        last_progress = time.monotonic()
        with _trace.span("stream.ingest", window=cursor.window) as ingest:
            t_ingest = time.perf_counter()
            polls = before = 0
            while True:
                take = self._close_size()
                if take:
                    break
                if should_stop is not None and should_stop():
                    return None
                if idle_s is not None and \
                        time.monotonic() - last_progress >= idle_s:
                    return None
                before = len(self._buf)
                backlog = self._sampled_backlog()
                batch = self.broker.claim_batch(self.claim_size,
                                                self.poll_timeout_s)
                polls += 1
                shedding = backlog > self.max_backlog
                for rid, payload in batch:
                    self._ingest_one(rid, payload, cursor, shedding)
                self._flush_acks()      # one batched XACK/XDEL per claim
                if shedding:
                    # catching up: resample immediately so shedding stops
                    # the poll after the backlog drops below the bound,
                    # not up to 15 stale polls later
                    self._polls_since_backlog = 0
                self.stats.add(polls=1,
                               records_in=len(self._buf) - before)
                if batch:
                    last_progress = time.monotonic()
            ingest.set(polls=polls, records=take)
            self.stats.add(ingest_s=time.perf_counter() - t_ingest)
        with _trace.span("stream.assemble", window=cursor.window,
                         records=take) as t:
            t0 = time.perf_counter()
            recs, self._buf = self._buf[:take], self._buf[take:]
            self._buf_ids.difference_update(r.rid for r in recs)
            self._buf_t0 = time.monotonic() if self._buf else None
            w = self._assemble(recs, cursor.window)
            self.stats.add(assemble_s=time.perf_counter() - t0)
        return w

    def _sampled_backlog(self) -> int:
        """Broker backlog, sampled every 16th poll (XLEN + XPENDING are
        two extra round trips — refreshing a gauge against a 100k default
        bound on EVERY 0.2 s poll would double the hot path's broker
        traffic). The shed decision tolerates the staleness: the bound is
        a protection valve, not a precise limit."""
        self._polls_since_backlog -= 1
        if self._polls_since_backlog > 0:
            return int(self.stats.snapshot().get("last_backlog", 0))
        self._polls_since_backlog = 16
        try:
            backlog = int(self.broker.pending())
        except Exception:   # noqa: BLE001 — telemetry only; the claim
            backlog = 0     # itself rides the broker's retry policy
        self.stats.add(last_backlog=backlog)
        return backlog

    def _assemble(self, recs: List[_PendingRecord], index: int) -> Window:
        """Stack records into ChunkedArray columns, one chunk per
        training batch — chunk boundaries are a function of batch_size
        only, so live and replayed runs assemble identical windows."""
        nx = len(recs[0].x)
        has_y = recs[0].y is not None
        ny = len(recs[0].y) if has_y else 0
        x_chunks: List[List[np.ndarray]] = [[] for _ in range(nx)]
        y_chunks: List[List[np.ndarray]] = [[] for _ in range(ny)]
        for s in range(0, len(recs), self.batch_size):
            group = recs[s:s + self.batch_size]
            for i in range(nx):
                x_chunks[i].append(np.stack([r.x[i] for r in group]))
            for i in range(ny):
                y_chunks[i].append(np.stack([r.y[i] for r in group]))
        ets = [r.event_time for r in recs]
        return Window(
            index=index,
            ids=[r.rid for r in recs],
            x=tuple(ChunkedArray(c) for c in x_chunks),
            y=tuple(ChunkedArray(c) for c in y_chunks) if has_y else None,
            event_time_min=min(ets), event_time_max=max(ets))

    # --- commit-side --------------------------------------------------------
    def ack(self, window: Window):
        """Acknowledge a trained-and-committed window's entries (the
        trainer calls this AFTER the checkpoint carrying the cursor is
        durable — acking earlier would turn a crash into record loss).
        One batched broker call: a window commit costs two Redis round
        trips, not two per record."""
        try:
            self.broker.ack_many(window.ids)
        except Exception as e:      # noqa: BLE001 — entries stay pending
            logger.warning("streaming window ack failed (%s: %s); the %d "
                           "entries will replay through the PEL and dedup "
                           "against the committed cursor",
                           type(e).__name__, e, window.n)
        # the window's arrays were copied out at assembly; the slabs are
        # consumed now that the cursor is durable (a replay past this
        # point dedups by id, never re-maps)
        for rid in window.ids:
            self._ref_done(self._refs.pop(rid, None))
        self.stats.add(acks=window.n)

    def close(self):
        # buffered-but-untrained records: drop our pins WITHOUT consuming —
        # the unacked entries replay after restart and must re-resolve
        if self._arena is not None:
            for ref in self._refs.values():
                try:
                    self._arena.release(ref)
                except Exception as e:      # noqa: BLE001 — already freed
                    logger.warning("shm release failed for %s: %s", ref, e)
        self._refs.clear()
        close = getattr(self.broker, "close", None)
        if close is not None:
            close()
