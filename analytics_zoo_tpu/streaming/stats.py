"""Streaming-plane telemetry.

One thread-safe counter object shared by the source (ingest/window
counters), the trainer (train/commit timers, recompile accounting) and
the serving-side reloader (reload count, freshness lag). Registered on
the unified obs registry as the ``zoo_streaming_*`` families — the
ISSUE's headline gauges:

* ``last_freshness_lag_s`` — event-time -> serving-time lag of the
  newest hot-reloaded window (how stale the served weights are, in
  seconds; the streaming plane's SLO number);
* ``last_backlog`` — records sitting in the broker behind the consumer;
* ``last_records_per_s`` — training-side ingest rate over the last
  window.

``freshness_samples`` keeps the per-reload lags so the bench can report
p50/p99 without a histogram family.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict

from ..common import knobs as _knobs
from ..obs.registry import REGISTRY as _REGISTRY

__all__ = ["StreamingStats"]

#: retained per-reload freshness samples (a weeks-long reloader must not
#: grow without bound; p50/p99 over the newest 1024 reloads is the SLO)
MAX_FRESHNESS_SAMPLES = 1024


class StreamingStats:
    """Monotonic counters + last-value gauges for one streaming loop
    (thread-safe; ``last_``-prefixed adds overwrite instead of sum)."""

    _COUNTS = ("records_in", "records_trained", "records_deduped",
               "records_shed", "late_dropped", "late_included",
               "windows", "polls", "acks", "reloads",
               "recompiles_after_warm",
               # guardrail verdicts (guardrail.py): every commit scores
               # exactly one of these before serving may adopt it
               "guard_accepted", "guard_rejected", "guard_insufficient")
    _TIMES = ("ingest_s", "assemble_s", "train_s", "commit_s")

    def __init__(self, register: bool = True):
        self._lock = threading.Lock()
        self.freshness_samples = deque(maxlen=MAX_FRESHNESS_SAMPLES)
        self.reset()
        if register and _knobs.get("ZOO_OBS"):
            # obs plane: weak collector adapter — the exposition follows
            # this object's lifetime, the dict API stays the source
            _REGISTRY.register_object("zoo_streaming", self)

    def reset(self):
        with self._lock:
            for k in self._COUNTS:
                setattr(self, k, 0)
            for k in self._TIMES:
                setattr(self, k, 0.0)
            self.last_backlog = 0
            self.last_freshness_lag_s = None
            self.last_records_per_s = None
            self.last_window = None
            self.last_commit_step = None
            self.last_reload_step = None
            self.freshness_samples.clear()

    def add(self, **kw):
        with self._lock:
            for k, v in kw.items():
                if k.startswith("last_"):
                    setattr(self, k, v)
                else:
                    setattr(self, k, getattr(self, k) + v)

    def observe_freshness(self, lag_s: float):
        with self._lock:
            self.last_freshness_lag_s = round(float(lag_s), 6)
            self.freshness_samples.append(float(lag_s))

    def snapshot(self) -> Dict:
        with self._lock:
            out = {k: getattr(self, k) for k in self._COUNTS}
            out.update({k: round(getattr(self, k), 6) for k in self._TIMES})
            for k in ("last_backlog", "last_freshness_lag_s",
                      "last_records_per_s", "last_window",
                      "last_commit_step", "last_reload_step"):
                v = getattr(self, k)
                if v is not None:
                    out[k] = v
            return out
