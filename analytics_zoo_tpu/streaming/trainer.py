"""StreamingTrainer — incremental fit on fresh windows, cursor-carrying
commits, bit-exact SIGTERM resume.

The loop composes pieces every earlier PR landed: windows come out of
:class:`~analytics_zoo_tpu.streaming.source.StreamingXShards` (real Redis
transport, STATUS #30; ChunkedArray assembly, PR 1), each window runs one
incremental ``fit`` on the scan-fused engine (``initial_epoch=`` shuffle
re-alignment, PR 2/3 — ONE warm executable across windows, zero
recompiles after window 1, compile_stats-asserted by the bench and
tests), the commit rides the async CheckpointPlane (PR 6) with the
stream cursor + trace token in the manifest meta, and the serving side's
CheckpointWatcher hot-swaps the weights with zero new compiles
(``serve.StreamingReloader``). One obs trace id (PR 10) spans
ingest -> assemble -> train dispatch -> ckpt commit -> watcher reload
across the loop thread, the infeed pump workers, the ckpt writer thread
and the watcher thread.

Commit protocol (the cursor contract, docs/guides/streaming.md):

1. window W closes (stream-order deterministic composition);
2. ``fit`` trains W (deterministic: fixed batch signature, shuffle seed
   = estimator seed + window counter);
3. the checkpoint (weights + optimizer + engine step) is committed with
   ``meta["stream"] = cursor(last_id=W.last, window=k+1, ...)`` and
   FLUSHED to disk;
4. only then are W's stream entries acked.

A SIGTERM (preemption) between any two steps resumes bit-exactly: before
3, the records are unacked and replay through the PEL into the same
window; after 3 but before 4, the replayed entries dedup against the
committed cursor and are ack-compacted. Replayed records therefore
produce byte-identical weights vs the uninterrupted run.
"""

from __future__ import annotations

import logging
import time
from dataclasses import replace
from typing import Optional

from ..ckpt import format as ckpt_fmt
from ..obs import trace as _trace
from ..orca.learn.preemption import PreemptionWatcher
from .source import StreamCursor, StreamingXShards, Window
from .stats import StreamingStats

logger = logging.getLogger("analytics_zoo_tpu")

__all__ = ["StreamingTrainer"]


def _compile_counts() -> int:
    from ..compile import compile_stats
    snap = compile_stats()
    return int(snap.get("compiles", 0)) + int(snap.get("fallbacks", 0))


class StreamingTrainer:
    """Drive one estimator from one streaming source.

    ``estimator`` is a built or fresh
    :class:`~analytics_zoo_tpu.orca.learn.estimator.TPUEstimator`; its
    ``model_dir``-independent checkpoint plane knobs (``ckpt_async``,
    retention, passphrase) apply to the streaming commits too. Unless the
    caller pinned ``steps_per_dispatch``, the trainer pins it to 1 —
    the auto fuse probe times dispatches, and a timing-dependent fuse
    factor must not decide how a *resumed* run groups its steps.
    """

    def __init__(self, estimator, source: StreamingXShards, model_dir: str,
                 *, shuffle: bool = False, commit_blocking: bool = False):
        self.estimator = estimator
        self.source = source
        self.model_dir = model_dir
        self.shuffle = shuffle
        self.commit_blocking = commit_blocking
        self.cursor = StreamCursor()
        self.stats: StreamingStats = source.stats
        estimator.config.setdefault("steps_per_dispatch", 1)
        self._warm_compiles: Optional[int] = None

    # --- resume -------------------------------------------------------------
    def resume(self) -> bool:
        """Restore the newest committed checkpoint and its cursor.
        Returns False when the model_dir holds no checkpoint (fresh
        start)."""
        try:
            path = self.estimator.load_checkpoint(self.model_dir)
        except FileNotFoundError:
            return False
        meta = ckpt_fmt.manifest_meta(path) if \
            ckpt_fmt.is_plane_dir(path) else {}
        sc = meta.get("stream")
        if sc:
            self.cursor = StreamCursor.from_dict(sc)
            logger.info("streaming resume: window %d, last id %s, "
                        "%d records applied (from %s)", self.cursor.window,
                        self.cursor.last_id or "<none>",
                        self.cursor.records, path)
        else:
            logger.warning("streaming resume: %s carries no stream cursor; "
                           "starting the cursor at zero (replays dedup "
                           "against an empty last_id)", path)
        return True

    # --- the loop -----------------------------------------------------------
    def run(self, max_windows: Optional[int] = None,
            idle_timeout_s: Optional[float] = None,
            stop: Optional[object] = None) -> StreamingStats:
        """Train until ``max_windows`` windows land, the source stays
        idle past ``idle_timeout_s`` (no NEW record for that long — a
        live low-rate stream keeps the loop running), ``stop`` (a
        threading.Event) is set, or a SIGTERM preemption notice arrives.
        Safe to re-enter: the cursor carries across calls (and across
        processes via :meth:`resume`)."""
        done = 0
        watcher = PreemptionWatcher()

        def should_stop() -> bool:
            return watcher.triggered or (stop is not None and stop.is_set())

        with watcher:
            while max_windows is None or done < max_windows:
                if should_stop():
                    break
                with _trace.span("stream.window", window=self.cursor.window):
                    w = self.source.next_window(
                        self.cursor, should_stop=should_stop,
                        idle_s=idle_timeout_s)
                    if w is None:
                        if should_stop() or idle_timeout_s is not None:
                            break
                        continue
                    self._train_window(w)
                    self._commit(w)
                    # ack ONLY now: the cursor is durable, so a crash
                    # from here on dedups instead of double-training
                    self.source.ack(w)
                done += 1
        if watcher.triggered:
            logger.warning(
                "streaming loop stopped on a preemption notice at window "
                "%d (cursor committed; unacked records will replay)",
                self.cursor.window)
        return self.stats

    def _train_window(self, w: Window):
        t0 = time.perf_counter()
        before = _compile_counts()
        self.estimator.fit(
            w.to_xshards(), epochs=1, batch_size=self.source.batch_size,
            shuffle=self.shuffle, verbose=False,
            initial_epoch=w.index)
        dt = time.perf_counter() - t0
        compiled = _compile_counts() - before
        if self._warm_compiles is None:
            # window 1 pays the one compile; every later window must
            # reuse the warm executable (the streaming plane's whole
            # latency story) — track violations for the bench/CI gate
            self._warm_compiles = compiled
        elif compiled:
            self.stats.add(recompiles_after_warm=compiled)
            logger.warning("streaming window %d recompiled %d program(s); "
                           "the batch signature changed", w.index, compiled)
        self.stats.add(windows=1, records_trained=w.n, train_s=dt,
                       last_window=w.index,
                       last_records_per_s=round(w.n / max(dt, 1e-9), 3))

    def _commit(self, w: Window):
        t0 = time.perf_counter()
        self.cursor = replace(
            self.cursor, last_id=w.last_id, window=w.index + 1,
            records=self.cursor.records + w.n,
            event_time_max=max(self.cursor.event_time_max,
                               w.event_time_max))
        meta = {"stream": self.cursor.to_dict()}
        tok = _trace.token()
        if tok:
            # trace handoff to the serving side: the watcher's reload
            # span chains under this window via the manifest meta, the
            # same Dapper-style payload ride serving uses
            meta["trace"] = tok
        self.estimator.save_checkpoint(self.model_dir, meta=meta,
                                       blocking=self.commit_blocking)
        if not self.estimator.flush_checkpoints():
            # queued-but-failed write: one blocking retry — acking
            # against a non-durable cursor would lose records on crash
            self.estimator.save_checkpoint(self.model_dir, meta=meta,
                                           blocking=True)
        self.stats.add(commit_s=time.perf_counter() - t0,
                       last_commit_step=self.estimator.engine.step)

    def recompiles_after_warm(self) -> int:
        """Executables compiled after window 1 (the zero-recompile gate
        reads 0 here)."""
        return int(self.stats.snapshot().get("recompiles_after_warm", 0))
