"""tfpark migration-compat namespace (reference: pyzoo/zoo/tfpark/ — 4465 LoC
of TF1-on-Spark machinery: TFDataset families, TFOptimizer, TFNet,
KerasModel, TFEstimator, GANEstimator).

On TPU the entire export-graph/py4j/DistriOptimizer pipeline collapses into
the one jitted engine, so this package is a thin compatibility facade: the
TFDataset constructors land in XShards/BatchIterator forms, KerasModel wraps
the flax estimator, and GANEstimator is the real implementation re-exported
from orca.learn. TF1 graph-mode entry points (TFOptimizer.from_loss, TFNet)
raise with a pointer to their TPU-native replacement rather than silently
half-working."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..orca.learn.gan_estimator import GANEstimator  # noqa: F401


class TFDataset:
    """Constructor surface of tfpark.TFDataset (reference tf_dataset.py:117).
    Holds {'x','y'} host arrays; estimators consume it like any dict."""

    def __init__(self, x, y=None, batch_size: int = -1,
                 batch_per_thread: int = -1, **_):
        self.x = x
        self.y = y
        self.batch_size = batch_size if batch_size > 0 else None

    # --- reference constructors (tf_dataset.py:324-637) ---------------------
    @classmethod
    def from_ndarrays(cls, tensors, batch_size: int = -1,
                      batch_per_thread: int = -1, val_tensors=None, **kw):
        if isinstance(tensors, (list, tuple)) and len(tensors) == 2:
            return cls(tensors[0], tensors[1], batch_size, batch_per_thread)
        return cls(tensors, None, batch_size, batch_per_thread)

    @classmethod
    def from_rdd(cls, rdd, **kwargs):
        raise NotImplementedError(
            "Spark RDDs do not exist in the TPU runtime; load data with "
            "orca.data (XShards / read_csv / read_parquet) instead")

    @classmethod
    def from_feature_set(cls, dataset, **kwargs):
        raise NotImplementedError(
            "use orca.data XShards in place of FeatureSet on TPU")

    @classmethod
    def from_image_set(cls, image_set, batch_size: int = -1, **kwargs):
        """ImageSet -> dataset (reference tf_dataset.py:407); labels ride
        along when present (feature.image.ImageSet stores images/labels via
        get_image/get_label)."""
        x = np.stack(image_set.get_image())
        labels = image_set.get_label()
        y = (np.asarray(labels)
             if labels and all(l is not None for l in labels) else None)
        return cls(x, y, batch_size)

    @classmethod
    def from_text_set(cls, text_set, batch_size: int = -1, **kwargs):
        """TextSet (word2idx'd) -> dataset (reference tf_dataset.py:445)."""
        x = np.stack([f.indices for f in text_set.features])
        labels = [getattr(f, "label", None) for f in text_set.features]
        y = (np.asarray(labels) if all(l is not None for l in labels)
             else None)
        return cls(x, y, batch_size)

    @classmethod
    def from_string_rdd(cls, string_rdd, batch_size: int = -1, **kwargs):
        """Reference tf_dataset.py:550 wraps an RDD of strings; here any
        iterable of strings becomes a (n,) object array."""
        return cls(np.asarray(list(string_rdd), dtype=object), None,
                   batch_size)

    @classmethod
    def from_bytes_rdd(cls, bytes_rdd, batch_size: int = -1, **kwargs):
        """Reference tf_dataset.py:575 (TFBytesDataset)."""
        return cls(np.asarray(list(bytes_rdd), dtype=object), None,
                   batch_size)

    @classmethod
    def from_tfrecord_file(cls, paths, feature_cols, label_cols=None,
                           batch_size: int = -1, **kwargs):
        """TFRecord corpus -> dataset (reference tf_dataset.py:480
        TFRecordDataset form) via the dependency-free reader in
        orca.data.tfrecord."""
        from ..orca.data.tfrecord import read_tfrecords_as_xshards
        from ..orca.learn.utils import concat_shards
        shards = read_tfrecords_as_xshards(paths, feature_cols=feature_cols,
                                           label_cols=label_cols)
        merged = concat_shards(shards)
        x = merged["x"]
        x = x[0] if len(x) == 1 else x
        y = merged.get("y")
        if y is not None:
            y = y[0] if len(y) == 1 else y
        return cls(x, y, batch_size)

    @classmethod
    def from_dataframe(cls, df, feature_cols, labels_cols=None, **kwargs):
        x = np.stack([np.asarray(v) for v in
                      df[feature_cols].to_numpy()]).astype(np.float32)
        y = (df[labels_cols].to_numpy() if labels_cols else None)
        return cls(x, y, kwargs.get("batch_size", -1))

    @classmethod
    def from_tf_data_dataset(cls, dataset, batch_size: int = -1, **kwargs):
        """Materialise a (finite) tf.data.Dataset to host arrays."""
        import tensorflow as tf  # noqa: F401
        xs, ys = [], []
        for item in dataset.as_numpy_iterator():
            if isinstance(item, tuple) and len(item) == 2:
                xs.append(item[0])
                ys.append(item[1])
            else:
                xs.append(item)
        x = np.stack(xs)
        y = np.stack(ys) if ys else None
        return cls(x, y, batch_size)

    def to_dict(self) -> Dict[str, Any]:
        return {"x": self.x} if self.y is None else {"x": self.x,
                                                     "y": self.y}


class KerasModel:
    """reference tfpark/model.py:30 KerasModel(tf.keras model) — here it
    wraps either our pipeline Keras net or any flax module."""

    def __init__(self, model, loss="mean_squared_error", optimizer="adam",
                 metrics=None):
        from ..pipeline.api.keras.engine.topology import KerasNet
        if isinstance(model, KerasNet):
            model.compile(optimizer=optimizer, loss=loss, metrics=metrics)
            self._est = model.estimator
        else:
            from ..orca.learn.estimator import TPUEstimator
            self._est = TPUEstimator(model, loss=loss, optimizer=optimizer,
                                     metrics=metrics)

    def fit(self, x, y=None, batch_size=32, epochs=1, distributed=True,
            **kwargs):
        data = x.to_dict() if isinstance(x, TFDataset) else (
            {"x": x, "y": y} if y is not None else x)
        bs = getattr(x, "batch_size", None) or batch_size
        return self._est.fit(data, epochs=epochs, batch_size=bs, **kwargs)

    def evaluate(self, x, y=None, batch_per_thread=32, distributed=True):
        data = x.to_dict() if isinstance(x, TFDataset) else (
            {"x": x, "y": y} if y is not None else x)
        return self._est.evaluate(data, batch_size=batch_per_thread)

    def predict(self, x, batch_per_thread=32, distributed=True):
        data = ({"x": x.x} if isinstance(x, TFDataset)
                else ({"x": x} if not isinstance(x, dict) else x))
        return self._est.predict(data, batch_size=batch_per_thread)

    def save_weights(self, path):
        self._est.save(path)

    def load_weights(self, path):
        self._est.load(path)


class TFOptimizer:
    @classmethod
    def from_loss(cls, *args, **kwargs):
        raise NotImplementedError(
            "TF1 graph export is not part of the TPU stack: write the model "
            "as a flax module (or keras pipeline net) and use "
            "orca.learn.Estimator.from_keras — the loss/grad/allreduce "
            "pipeline is one jitted XLA program (SURVEY.md §3.2)")

    from_keras = from_loss
    from_train_op = from_loss


class TFNet:
    @classmethod
    def from_export_folder(cls, *args, **kwargs):
        raise NotImplementedError(
            "TF graph inference runs through "
            "pipeline.inference.InferenceModel (load_tf) on TPU")

    from_session = from_export_folder


def ZooOptimizer(optimizer, grad_accum_steps: int = 1):
    """Gradient-accumulation wrapper (reference tfpark/zoo_optimizer.py wraps
    a TF optimizer to sum grads over sub-batches before applying).

    TPU-native: returns an optax transformation — ``optax.MultiSteps``
    accumulates ``grad_accum_steps`` microbatch gradients on device and
    applies one update, all inside the jitted train step. Pass the result
    anywhere an optimizer is accepted (estimators, compile())."""
    import optax

    from ..orca.learn.optimizers.optimizers_impl import convert_optimizer
    tx = convert_optimizer(optimizer)
    if grad_accum_steps <= 1:
        return tx
    return optax.MultiSteps(
        tx, every_k_schedule=grad_accum_steps).gradient_transformation()


class TFEstimator:
    """reference tfpark/estimator.py:30 model_fn-style estimator."""

    def __init__(self, model_fn: Callable, *args, **kwargs):
        raise NotImplementedError(
            "model_fn-style TF estimators are replaced by "
            "orca.learn.Estimator.from_keras(model_creator) on TPU")
