"""tfpark migration-compat namespace (reference: pyzoo/zoo/tfpark/ — 4465 LoC
of TF1-on-Spark machinery: TFDataset families, TFOptimizer, TFNet,
KerasModel, TFEstimator, GANEstimator).

On TPU the entire export-graph/py4j/DistriOptimizer pipeline collapses into
the one jitted engine, so this package is a thin compatibility facade: the
TFDataset constructors land in XShards/BatchIterator forms, KerasModel wraps
the flax estimator, and GANEstimator is the real implementation re-exported
from orca.learn. TF1 graph-mode entry points (TFOptimizer.from_loss, TFNet)
raise with a pointer to their TPU-native replacement rather than silently
half-working."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..orca.learn.gan_estimator import GANEstimator  # noqa: F401


class TFDataset:
    """Constructor surface of tfpark.TFDataset (reference tf_dataset.py:117).
    Holds {'x','y'} host arrays; estimators consume it like any dict."""

    def __init__(self, x, y=None, batch_size: int = -1,
                 batch_per_thread: int = -1, **_):
        self.x = x
        self.y = y
        self.batch_size = batch_size if batch_size > 0 else None

    # --- reference constructors (tf_dataset.py:324-637) ---------------------
    @classmethod
    def from_ndarrays(cls, tensors, batch_size: int = -1,
                      batch_per_thread: int = -1, val_tensors=None, **kw):
        if isinstance(tensors, (list, tuple)) and len(tensors) == 2:
            return cls(tensors[0], tensors[1], batch_size, batch_per_thread)
        return cls(tensors, None, batch_size, batch_per_thread)

    @classmethod
    def from_rdd(cls, rdd, **kwargs):
        raise NotImplementedError(
            "Spark RDDs do not exist in the TPU runtime; load data with "
            "orca.data (XShards / read_csv / read_parquet) instead")

    @classmethod
    def from_feature_set(cls, dataset, **kwargs):
        raise NotImplementedError(
            "use orca.data XShards in place of FeatureSet on TPU")

    @classmethod
    def from_image_set(cls, image_set, batch_size: int = -1, **kwargs):
        """ImageSet -> dataset (reference tf_dataset.py:407); labels ride
        along when present (feature.image.ImageSet stores images/labels via
        get_image/get_label)."""
        x = np.stack(image_set.get_image())
        labels = image_set.get_label()
        y = (np.asarray(labels)
             if labels and all(l is not None for l in labels) else None)
        return cls(x, y, batch_size)

    @classmethod
    def from_text_set(cls, text_set, batch_size: int = -1, **kwargs):
        """TextSet (word2idx'd) -> dataset (reference tf_dataset.py:445)."""
        x = np.stack([f.indices for f in text_set.features])
        labels = [getattr(f, "label", None) for f in text_set.features]
        y = (np.asarray(labels) if all(l is not None for l in labels)
             else None)
        return cls(x, y, batch_size)

    @classmethod
    def from_string_rdd(cls, string_rdd, batch_size: int = -1, **kwargs):
        """Reference tf_dataset.py:550 wraps an RDD of strings; here any
        iterable of strings becomes a (n,) object array."""
        return cls(np.asarray(list(string_rdd), dtype=object), None,
                   batch_size)

    @classmethod
    def from_bytes_rdd(cls, bytes_rdd, batch_size: int = -1, **kwargs):
        """Reference tf_dataset.py:575 (TFBytesDataset)."""
        return cls(np.asarray(list(bytes_rdd), dtype=object), None,
                   batch_size)

    @classmethod
    def from_tfrecord_file(cls, paths, feature_cols, label_cols=None,
                           batch_size: int = -1, **kwargs):
        """TFRecord corpus -> dataset (reference tf_dataset.py:480
        TFRecordDataset form) via the dependency-free reader in
        orca.data.tfrecord."""
        from ..orca.data.tfrecord import read_tfrecords_as_xshards
        from ..orca.learn.utils import concat_shards
        shards = read_tfrecords_as_xshards(paths, feature_cols=feature_cols,
                                           label_cols=label_cols)
        merged = concat_shards(shards)
        x = merged["x"]
        x = x[0] if len(x) == 1 else x
        y = merged.get("y")
        if y is not None:
            y = y[0] if len(y) == 1 else y
        return cls(x, y, batch_size)

    @classmethod
    def from_dataframe(cls, df, feature_cols, labels_cols=None, **kwargs):
        x = np.stack([np.asarray(v) for v in
                      df[feature_cols].to_numpy()]).astype(np.float32)
        y = (df[labels_cols].to_numpy() if labels_cols else None)
        return cls(x, y, kwargs.get("batch_size", -1))

    @classmethod
    def from_tf_data_dataset(cls, dataset, batch_size: int = -1, **kwargs):
        """Materialise a (finite) tf.data.Dataset to host arrays."""
        import tensorflow as tf  # noqa: F401
        xs, ys = [], []
        for item in dataset.as_numpy_iterator():
            if isinstance(item, tuple) and len(item) == 2:
                xs.append(item[0])
                ys.append(item[1])
            else:
                xs.append(item)
        x = np.stack(xs)
        y = np.stack(ys) if ys else None
        return cls(x, y, batch_size)

    def to_dict(self) -> Dict[str, Any]:
        return {"x": self.x} if self.y is None else {"x": self.x,
                                                     "y": self.y}


class KerasModel:
    """reference tfpark/model.py:30 KerasModel(tf.keras model) — here it
    wraps either our pipeline Keras net or any flax module."""

    def __init__(self, model, loss="mean_squared_error", optimizer="adam",
                 metrics=None):
        from ..pipeline.api.keras.engine.topology import KerasNet
        if isinstance(model, KerasNet):
            model.compile(optimizer=optimizer, loss=loss, metrics=metrics)
            self._est = model.estimator
        else:
            from ..orca.learn.estimator import TPUEstimator
            self._est = TPUEstimator(model, loss=loss, optimizer=optimizer,
                                     metrics=metrics)

    def fit(self, x, y=None, batch_size=32, epochs=1, distributed=True,
            **kwargs):
        data = x.to_dict() if isinstance(x, TFDataset) else (
            {"x": x, "y": y} if y is not None else x)
        bs = getattr(x, "batch_size", None) or batch_size
        return self._est.fit(data, epochs=epochs, batch_size=bs, **kwargs)

    def evaluate(self, x, y=None, batch_per_thread=32, distributed=True):
        data = x.to_dict() if isinstance(x, TFDataset) else (
            {"x": x, "y": y} if y is not None else x)
        return self._est.evaluate(data, batch_size=batch_per_thread)

    def predict(self, x, batch_per_thread=32, distributed=True):
        data = ({"x": x.x} if isinstance(x, TFDataset)
                else ({"x": x} if not isinstance(x, dict) else x))
        return self._est.predict(data, batch_size=batch_per_thread)

    def save_weights(self, path):
        self._est.save(path)

    def load_weights(self, path):
        self._est.load(path)


class TFOptimizer:
    @classmethod
    def from_loss(cls, *args, **kwargs):
        raise NotImplementedError(
            "TF1 graph export is not part of the TPU stack: write the model "
            "as a flax module (or keras pipeline net) and use "
            "orca.learn.Estimator.from_keras — the loss/grad/allreduce "
            "pipeline is one jitted XLA program (SURVEY.md §3.2)")

    from_keras = from_loss
    from_train_op = from_loss


class TFNet:
    """Frozen-graph inference net (reference: TFNet.scala:56 executes the
    frozen graph through TF Java; python wrapper tfnet.py:180
    ``from_export_folder`` over util/tf.py ``export_tf`` folders).

    The graphdef is imported once and pruned to a concrete
    inputs->outputs function. ``predict`` executes it with TF's runtime on
    the host; ``as_inference_model()`` wraps it for the serving stack via
    ``jax2tf.call_tf`` — note call_tf executes TF kernels host-side, so on a
    TPU-only deployment prefer re-exporting the model and ``load_tf`` (the
    keras->flax conversion) for a native XLA path."""

    def __init__(self, fn, input_names, output_names):
        self._fn = fn
        self.input_names = list(input_names)
        self.output_names = list(output_names)

    @classmethod
    def from_frozen_graph(cls, pb_path: str, input_names, output_names
                          ) -> "TFNet":
        """Load a frozen GraphDef ``.pb`` plus explicit tensor names
        (e.g. ``["input:0"]`` / ``["logits:0"]``)."""
        import tensorflow as tf
        gd = tf.compat.v1.GraphDef()
        with open(pb_path, "rb") as f:
            gd.ParseFromString(f.read())

        def _import():
            tf.compat.v1.import_graph_def(gd, name="")

        wrapped = tf.compat.v1.wrap_function(_import, [])
        fn = wrapped.prune(
            feeds=[wrapped.graph.as_graph_element(n) for n in input_names],
            fetches=[wrapped.graph.as_graph_element(n) for n in output_names])
        return cls(fn, input_names, output_names)

    @classmethod
    def from_export_folder(cls, folder: str) -> "TFNet":
        """Load an ``export_tf`` folder: ``frozen_inference_graph.pb`` +
        ``graph_meta.json`` with input/output tensor names (reference layout:
        pyzoo/zoo/util/tf.py:184-198)."""
        import json as _json
        import os
        if not os.path.isdir(folder):
            raise ValueError(f"{folder} does not exist")
        with open(os.path.join(folder, "graph_meta.json")) as f:
            meta = _json.load(f)
        return cls.from_frozen_graph(
            os.path.join(folder, "frozen_inference_graph.pb"),
            meta["input_names"], meta["output_names"])

    @classmethod
    def from_session(cls, sess, inputs, outputs, **_) -> "TFNet":
        """Freeze the session's graph on the given tensors (reference
        tfnet.py:237 from_session -> export_tf -> TFNet)."""
        import tensorflow as tf
        from tensorflow.python.framework import graph_util  # noqa: WPS433
        with sess.graph.as_default():
            gd = tf.compat.v1.graph_util.convert_variables_to_constants(
                sess, sess.graph_def, [t.op.name for t in outputs])
        import tempfile, os  # noqa: E401
        tmp = tempfile.mkdtemp(prefix="zoo_tfnet_")
        pb = os.path.join(tmp, "frozen_inference_graph.pb")
        with open(pb, "wb") as f:
            f.write(gd.SerializeToString())
        return cls.from_frozen_graph(pb, [t.name for t in inputs],
                                     [t.name for t in outputs])

    def predict(self, x, batch_size: int = 0, distributed: bool = False):
        import numpy as _np
        import tensorflow as tf
        xs = x if isinstance(x, (list, tuple)) else [x]
        outs = self._fn(*[tf.convert_to_tensor(_np.asarray(a)) for a in xs])
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        outs = [_np.asarray(o) for o in outs]
        return outs if len(outs) > 1 else outs[0]

    def as_inference_model(self):
        """Wrap for ClusterServing / InferenceModel.predict (host-side TF
        execution via call_tf; see class docstring for the TPU caveat).

        The wrapper runs EAGERLY (``InferenceModel._eager``): call_tf under
        ``jax.jit`` requires the TF function to be XLA-compilable, and frozen
        graphs with NMS/lookup ops — TFNet's main use case — are not; eager
        call_tf lets TF execute its own kernels host-side instead."""
        from ..pipeline.inference import InferenceModel
        from jax.experimental import jax2tf
        cfn = jax2tf.call_tf(self._fn)      # once — apply_fn runs per request

        def apply_fn(variables, *x):
            out = cfn(*x)
            # pruned concrete functions return a list of fetches; a single
            # output unwraps so predict() returns the array itself
            if isinstance(out, (list, tuple)) and len(out) == 1:
                return out[0]
            return out

        im = InferenceModel()
        im._apply_fn = apply_fn
        im._variables = {}
        im._eager = True
        return im


def ZooOptimizer(optimizer, grad_accum_steps: int = 1):
    """Gradient-accumulation wrapper (reference tfpark/zoo_optimizer.py wraps
    a TF optimizer to sum grads over sub-batches before applying).

    TPU-native: returns an optax transformation — ``optax.MultiSteps``
    accumulates ``grad_accum_steps`` microbatch gradients on device and
    applies one update, all inside the jitted train step. Pass the result
    anywhere an optimizer is accepted (estimators, compile())."""
    import optax

    from ..orca.learn.optimizers.optimizers_impl import convert_optimizer
    tx = convert_optimizer(optimizer)
    if grad_accum_steps <= 1:
        return tx
    return optax.MultiSteps(
        tx, every_k_schedule=grad_accum_steps).gradient_transformation()


class TFEstimator:
    """reference tfpark/estimator.py:30 model_fn-style estimator."""

    def __init__(self, model_fn: Callable, *args, **kwargs):
        raise NotImplementedError(
            "model_fn-style TF estimators are replaced by "
            "orca.learn.Estimator.from_keras(model_creator) on TPU")
