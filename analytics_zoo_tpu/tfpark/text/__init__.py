from .estimator import BERTClassifier, BERTNER, BERTSQuAD, bert_input_fn
from .keras import NER, POSTagger, IntentEntity

__all__ = ["BERTClassifier", "BERTNER", "BERTSQuAD", "bert_input_fn",
           "NER", "POSTagger", "IntentEntity"]
