"""BERT text estimators — TPU-native equivalents of the reference's
tfpark.text.estimator family (pyzoo/zoo/tfpark/text/estimator/: bert_base.py
BERTBaseEstimator over tf.estimator + bert_input_fn, bert_classifier.py,
bert_ner.py, bert_squad.py).

The reference wraps Google's TF1 BERT checkpoint graph in a tf.estimator and
ships it through TFEstimator to Spark workers. Here the encoder is the flax
``BERT`` from the keras pipeline layers (one jitted XLA program, flash
attention inside), each task adds its head in flax, and training runs on the
unified TPUEstimator — the public surface (``fit``/``evaluate``/``predict``
over feature dicts) matches the reference estimators.

Feature dict convention (same keys as the reference's bert_input_fn,
bert_base.py:30-60): ``input_ids``, optional ``token_type_ids``, optional
``input_mask``; labels under ``label_ids`` / (``start_positions``,
``end_positions``) for SQuAD.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import flax.linen as nn
import jax.numpy as jnp

from ...orca.learn.estimator import TPUEstimator
from ...pipeline.api.keras.layers.self_attention import BERT


def bert_input_fn(features: Dict[str, np.ndarray],
                  labels: Optional[np.ndarray] = None,
                  batch_size: int = 32) -> Dict[str, Any]:
    """Assemble the estimator data dict from BERT feature arrays (the
    reference's bert_input_fn builds a TFDataset the same way)."""
    ids = np.asarray(features["input_ids"], np.int32)
    xs = [ids]
    tt = features.get("token_type_ids", features.get("segment_ids"))
    mask = features.get("input_mask", features.get("attention_mask"))
    if tt is not None or mask is not None:
        # positional convention: (ids, token_type_ids[, input_mask])
        xs.append(np.asarray(tt, np.int32) if tt is not None
                  else np.zeros_like(ids))
    if mask is not None:
        xs.append(np.asarray(mask, np.int32))
    data: Dict[str, Any] = {"x": tuple(xs) if len(xs) > 1 else xs[0]}
    if labels is not None:
        data["y"] = labels
    return data


class _BertWithHead(nn.Module):
    """BERT encoder + task head. head: 'pooled' (b,h)->logits over classes,
    'tokens' per-token logits, 'span' start/end logits."""
    bert_kwargs: Tuple[Tuple[str, Any], ...]
    num_out: int
    head: str = "pooled"
    head_drop: float = 0.1

    @nn.compact
    def __call__(self, ids, token_type_ids=None, input_mask=None,
                 train: bool = False):
        seq, pooled = BERT(**dict(self.bert_kwargs), name="bert")(
            ids, token_type_ids, attention_mask=input_mask, train=train)
        if self.head == "pooled":
            h = nn.Dropout(self.head_drop, deterministic=not train)(pooled)
            return nn.Dense(self.num_out, name="head")(h)
        h = nn.Dropout(self.head_drop, deterministic=not train)(seq)
        return nn.Dense(self.num_out, name="head")(h)   # (b, s, num_out)


class BERTBaseEstimator(TPUEstimator):
    """Shared constructor surface (reference bert_base.py:125-134:
    bert_config_file/init_checkpoint/... params). TPU-native: BERT hyper-
    params are passed directly (or read from a bert_config.json via
    ``bert_config_file``); ``init_checkpoint`` loads a pickled params tree
    saved by this framework."""

    def __init__(self, *, num_out: int, head: str,
                 bert_config: Optional[dict] = None,
                 bert_config_file: Optional[str] = None,
                 init_checkpoint: Optional[str] = None,
                 optimizer="adam", loss=None, metrics=None,
                 model_dir: Optional[str] = None, **bert_kwargs):
        if bert_config_file:
            import json
            with open(bert_config_file) as f:
                raw = json.load(f)
            bert_config = {
                "vocab": raw.get("vocab_size", 30522),
                "hidden_size": raw.get("hidden_size", 768),
                "n_block": raw.get("num_hidden_layers", 12),
                "n_head": raw.get("num_attention_heads", 12),
                "seq_len": raw.get("max_position_embeddings", 512),
                "intermediate_size": raw.get("intermediate_size", 3072),
                "hidden_p_drop": raw.get("hidden_dropout_prob", 0.1),
                "attn_p_drop": raw.get(
                    "attention_probs_dropout_prob", 0.1)}
        cfg = dict(bert_config or {})
        cfg.update(bert_kwargs)
        module = _BertWithHead(
            bert_kwargs=tuple(sorted(cfg.items())), num_out=num_out,
            head=head)
        super().__init__(module, loss=loss, optimizer=optimizer,
                         metrics=metrics, model_dir=model_dir)
        if init_checkpoint:
            self.load(init_checkpoint)


class BERTClassifier(BERTBaseEstimator):
    """Sequence classification on the pooled [CLS] output (reference
    bert_classifier.py:51: make_bert_classifier_model_fn -> dense over
    pooled)."""

    def __init__(self, num_classes: int, **kwargs):
        from functools import partial
        from ...orca.learn.losses import sparse_categorical_crossentropy
        kwargs.setdefault("loss", partial(sparse_categorical_crossentropy,
                                          from_logits=True))
        kwargs.setdefault("metrics", ["sparse_categorical_accuracy"])
        super().__init__(num_out=num_classes, head="pooled", **kwargs)


class BERTNER(BERTBaseEstimator):
    """Token-level entity tagging (reference bert_ner.py:51: per-token dense
    over the sequence output, labels (b, s))."""

    def __init__(self, num_entities: int, **kwargs):
        from functools import partial
        from ...orca.learn.losses import sparse_categorical_crossentropy
        kwargs.setdefault("loss", partial(sparse_categorical_crossentropy,
                                          from_logits=True))
        kwargs.setdefault("metrics", None)
        super().__init__(num_out=num_entities, head="tokens", **kwargs)


def _squad_loss(y, logits):
    """y: (b, 2) start/end token indices; logits: (b, s, 2)."""
    import jax

    start_logits, end_logits = logits[..., 0], logits[..., 1]

    def ce(pos_logits, pos):
        logp = jax.nn.log_softmax(pos_logits, axis=-1)
        return -jnp.take_along_axis(logp, pos[:, None], axis=-1)[:, 0]

    return 0.5 * (ce(start_logits, y[:, 0].astype(jnp.int32)) +
                  ce(end_logits, y[:, 1].astype(jnp.int32)))


class BERTSQuAD(BERTBaseEstimator):
    """Extractive QA: start/end span logits per token (reference
    bert_squad.py:56: two-unit dense over sequence output, losses averaged
    over start+end positions)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("loss", _squad_loss)
        kwargs.setdefault("metrics", None)
        super().__init__(num_out=2, head="tokens", **kwargs)
