"""Keras-style text models — TPU-native equivalents of the reference's
tfpark.text.keras family (pyzoo/zoo/tfpark/text/keras/: ner.py NER,
pos_tagging.py POSTagger, intent_extraction.py IntentEntity — all thin
wrappers over nlp-architect BiLSTM "labor" models).

nlp-architect doesn't exist here; the models are re-implemented as flax
BiLSTM taggers over word(+char) embeddings, trained by the unified engine:

* ``NER``        — word + char-CNN embeddings -> BiLSTM -> per-token softmax
  (the reference's NERCRF uses a CRF decode layer; greedy softmax decoding
  is used instead, which is the usual TPU-friendly simplification).
* ``POSTagger``  — same skeleton, POS tag inventory.
* ``IntentEntity`` — joint model: shared BiLSTM, intent head on the final
  state + slot head per token (intent_extraction.py MultiTaskIntentModel).

Each model exposes fit/evaluate/predict + save/load via its TPUEstimator.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import flax.linen as nn

from ...ops.embedding import MXUEmbed
import jax
import jax.numpy as jnp

from ...orca.learn.estimator import TPUEstimator
from ...orca.learn.losses import sparse_categorical_crossentropy


def _token_ce(y, logits):
    """Per-token CE that ignores padding label 0 (tag inventories here
    reserve 0 = PAD, matching the reference's padded-sentence batches)."""
    per_tok = sparse_categorical_crossentropy(y, logits, from_logits=True)
    mask = (y > 0).astype(per_tok.dtype)
    return (per_tok * mask).sum(-1) / jnp.maximum(mask.sum(-1), 1.0)


class _BiLSTM(nn.Module):
    units: int

    @nn.compact
    def __call__(self, x):
        fwd = nn.RNN(nn.LSTMCell(features=self.units), keep_order=True)(x)
        bwd = nn.RNN(nn.LSTMCell(features=self.units), reverse=True,
                     keep_order=True)(x)
        return jnp.concatenate([fwd, bwd], axis=-1)


class _TaggerNet(nn.Module):
    """word ids (b,s) [+ char ids (b,s,w)] -> per-token tag logits."""
    vocab_size: int
    num_tags: int
    word_emb_dim: int = 100
    char_vocab_size: int = 0
    char_emb_dim: int = 30
    lstm_units: int = 100
    dropout: float = 0.5

    @nn.compact
    def __call__(self, word_ids, char_ids=None, train: bool = False):
        h = MXUEmbed(self.vocab_size, self.word_emb_dim,
                     name="word_embedding")(word_ids.astype(jnp.int32))
        if char_ids is not None and self.char_vocab_size:
            c = MXUEmbed(self.char_vocab_size, self.char_emb_dim,
                         name="char_embedding")(char_ids.astype(jnp.int32))
            # char-CNN per word: conv over the char axis, max-pool
            b, s, w, d = c.shape
            c = nn.Conv(self.char_emb_dim, (3,), name="char_conv")(
                c.reshape(b * s, w, d))
            c = c.max(axis=1).reshape(b, s, self.char_emb_dim)
            h = jnp.concatenate([h, c], axis=-1)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        h = _BiLSTM(self.lstm_units, name="bilstm")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return nn.Dense(self.num_tags, name="tag_head")(h)


class _Tagger:
    """Shared estimator wrapper for NER / POSTagger."""

    def __init__(self, num_tags: int, vocab_size: int,
                 char_vocab_size: int = 0, word_emb_dim: int = 100,
                 char_emb_dim: int = 30, lstm_units: int = 100,
                 dropout: float = 0.5, optimizer="adam"):
        self.module = _TaggerNet(
            vocab_size=vocab_size, num_tags=num_tags,
            word_emb_dim=word_emb_dim, char_vocab_size=char_vocab_size,
            char_emb_dim=char_emb_dim, lstm_units=lstm_units,
            dropout=dropout)
        self.estimator = TPUEstimator(self.module, loss=_token_ce,
                                      optimizer=optimizer)

    def fit(self, x, y, batch_size: int = 32, epochs: int = 1, **kw):
        return self.estimator.fit({"x": x, "y": y}, epochs=epochs,
                                  batch_size=batch_size, **kw)

    def evaluate(self, x, y, batch_size: int = 32):
        return self.estimator.evaluate({"x": x, "y": y},
                                       batch_size=batch_size)

    def predict(self, x, batch_size: int = 32):
        logits = self.estimator.predict(x, batch_size=batch_size)
        return np.argmax(np.asarray(logits), axis=-1)

    def save_model(self, path: str):
        return self.estimator.save(path)

    def load_model(self, path: str):
        self.estimator.load(path)
        return self


class NER(_Tagger):
    """(reference ner.py NER: nlp-architect NERCRF labor)"""


class POSTagger(_Tagger):
    """(reference pos_tagging.py POSTagger)"""


class _IntentEntityNet(nn.Module):
    vocab_size: int
    num_intents: int
    num_entities: int
    word_emb_dim: int = 100
    lstm_units: int = 100
    dropout: float = 0.5

    @nn.compact
    def __call__(self, word_ids, train: bool = False):
        h = MXUEmbed(self.vocab_size, self.word_emb_dim,
                     name="word_embedding")(word_ids.astype(jnp.int32))
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        h = _BiLSTM(self.lstm_units, name="bilstm")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        intent_logits = nn.Dense(self.num_intents, name="intent_head")(
            h.mean(axis=1))
        slot_logits = nn.Dense(self.num_entities, name="slot_head")(h)
        # fixed-shape packing: (b, 1+s, max(num_intents, num_entities))
        width = max(self.num_intents, self.num_entities)

        def pad(t):
            return jnp.pad(t, [(0, 0)] * (t.ndim - 1) +
                           [(0, width - t.shape[-1])],
                           constant_values=-1e9)

        return jnp.concatenate([pad(intent_logits)[:, None], pad(slot_logits)],
                               axis=1)


def _intent_entity_loss(num_intents, num_entities):
    def loss(y, packed):
        # y: (b, 1+s) — y[:,0] intent id, y[:,1:] slot ids (0 = PAD)
        intent_logits = packed[:, 0, :num_intents]
        slot_logits = packed[:, 1:, :num_entities]
        intent_l = sparse_categorical_crossentropy(
            y[:, 0], intent_logits, from_logits=True)
        slot_l = _token_ce(y[:, 1:], slot_logits)
        return intent_l + slot_l
    return loss


class IntentEntity:
    """Joint intent + slot model (reference intent_extraction.py
    MultiTaskIntentModel). Labels pack as (b, 1+s): column 0 = intent id,
    rest = per-token slot ids (0 = PAD)."""

    def __init__(self, num_intents: int, num_entities: int, vocab_size: int,
                 word_emb_dim: int = 100, lstm_units: int = 100,
                 dropout: float = 0.5, optimizer="adam"):
        self.num_intents = num_intents
        self.num_entities = num_entities
        self.module = _IntentEntityNet(
            vocab_size=vocab_size, num_intents=num_intents,
            num_entities=num_entities, word_emb_dim=word_emb_dim,
            lstm_units=lstm_units, dropout=dropout)
        self.estimator = TPUEstimator(
            self.module, loss=_intent_entity_loss(num_intents, num_entities),
            optimizer=optimizer)

    @staticmethod
    def pack_labels(intents: np.ndarray, slots: np.ndarray) -> np.ndarray:
        return np.concatenate([np.asarray(intents).reshape(-1, 1),
                               np.asarray(slots)], axis=1).astype(np.int32)

    def fit(self, x, intents, slots, batch_size: int = 32, epochs: int = 1,
            **kw):
        y = self.pack_labels(intents, slots)
        return self.estimator.fit({"x": x, "y": y}, epochs=epochs,
                                  batch_size=batch_size, **kw)

    def predict(self, x, batch_size: int = 32
                ) -> Tuple[np.ndarray, np.ndarray]:
        packed = np.asarray(self.estimator.predict(x,
                                                   batch_size=batch_size))
        intent = np.argmax(packed[:, 0, :self.num_intents], axis=-1)
        slots = np.argmax(packed[:, 1:, :self.num_entities], axis=-1)
        return intent, slots

    def save_model(self, path: str):
        return self.estimator.save(path)

    def load_model(self, path: str):
        self.estimator.load(path)
        return self
