"""Dependency-free authenticated encryption for model artifacts.

The reference serves encrypted OpenVINO/BigDL models
(InferenceModel.scala:315-323 doLoadEncryptedOpenVINO — decrypt with a
secret key before loading, so model weights at rest on serving hosts are
not plaintext). The TPU-native analogue is format-agnostic: encrypt the
serialized checkpoint bytes themselves.

Scheme (stdlib only — the TPU image carries no cryptography package):

* key derivation: PBKDF2-HMAC-SHA256 over the passphrase with a random
  16-byte salt (200k iterations) → one 32-byte master key, split into an
  encryption key and a MAC key via HMAC domain separation;
* cipher: HMAC-SHA256 in counter mode as the keystream PRF (a standard
  PRF→stream-cipher construction). The keystream is generated with one
  single-iteration PBKDF2 call — PBKDF2's block function at iterations=1
  IS HMAC(key, nonce ‖ counter_be32), and hashlib.pbkdf2_hmac runs the
  whole block chain in OpenSSL C (~60 MB/s measured end-to-end vs
  ~15 MB/s for a per-block Python loop);
* integrity: encrypt-then-MAC with HMAC-SHA256 over header ‖ ciphertext —
  tampering or a wrong key fails loudly BEFORE any unpickling happens,
  which also keeps `load_encrypted` safe against pickle-bomb swaps.

Wire format v2: MAGIC2 ‖ salt(16) ‖ nonce(16) ‖ ciphertext ‖ tag(32),
keystream generated in 64 MB segments with the segment index appended to
the nonce — whole-buffer big-int XOR materialized ~3-4 full-size copies,
so a multi-GB checkpoint peaked at several times its size in host memory
(round-4 advisor); segments bound the transient copies at 64 MB each.
v1 artifacts (single whole-buffer keystream) remain readable.
"""

from __future__ import annotations

import hashlib
import hmac
import os

MAGIC = b"ZOOENC1\x00"
MAGIC2 = b"ZOOENC2\x00"
_ITERATIONS = 200_000
_SEGMENT = 64 << 20


def _derive_keys(passphrase: str, salt: bytes):
    master = hashlib.pbkdf2_hmac("sha256", passphrase.encode("utf-8"),
                                 salt, _ITERATIONS, dklen=32)
    enc_key = hmac.new(master, b"encrypt", hashlib.sha256).digest()
    mac_key = hmac.new(master, b"mac", hashlib.sha256).digest()
    return enc_key, mac_key


def _keystream_xor(enc_key: bytes, nonce: bytes, data: bytes) -> bytes:
    """v1 path: one whole-buffer keystream (kept for reading old
    artifacts; peaks at several times the data size in host memory)."""
    if not data:
        return b""
    # PBKDF2(iterations=1, dklen=n) == HMAC(key, nonce || be32(i)) block
    # chain, computed entirely inside OpenSSL — the fast stdlib route to
    # an HMAC-CTR keystream
    stream = hashlib.pbkdf2_hmac("sha256", enc_key, nonce, 1,
                                 dklen=len(data))
    # whole-buffer XOR through big ints: C-speed, no per-byte Python loop
    return (int.from_bytes(data, "big") ^
            int.from_bytes(stream, "big")).to_bytes(len(data), "big")


def _keystream_xor_segmented(enc_key: bytes, nonce: bytes,
                             data: bytes) -> bytes:
    """v2 path: independent 64 MB keystream segments (segment index
    appended to the nonce), so transient copies are bounded at segment
    size instead of the whole artifact."""
    out = []
    for seg, j in enumerate(range(0, len(data), _SEGMENT)):
        chunk = data[j:j + _SEGMENT]
        seg_nonce = nonce + seg.to_bytes(4, "big")
        stream = hashlib.pbkdf2_hmac("sha256", enc_key, seg_nonce, 1,
                                     dklen=len(chunk))
        out.append((int.from_bytes(chunk, "big") ^
                    int.from_bytes(stream, "big"))
                   .to_bytes(len(chunk), "big"))
    return b"".join(out)


def encrypt_bytes(data: bytes, passphrase: str) -> bytes:
    salt, nonce = os.urandom(16), os.urandom(16)
    enc_key, mac_key = _derive_keys(passphrase, salt)
    ct = _keystream_xor_segmented(enc_key, nonce, data)
    header = MAGIC2 + salt + nonce
    tag = hmac.new(mac_key, header + ct, hashlib.sha256).digest()
    return header + ct + tag


def decrypt_bytes(blob: bytes, passphrase: str) -> bytes:
    if len(blob) < len(MAGIC) + 16 + 16 + 32 or \
            not (blob.startswith(MAGIC) or blob.startswith(MAGIC2)):
        raise ValueError("not an analytics-zoo-tpu encrypted artifact")
    v2 = blob.startswith(MAGIC2)
    off = len(MAGIC2) if v2 else len(MAGIC)
    salt, nonce = blob[off:off + 16], blob[off + 16:off + 32]
    ct, tag = blob[off + 32:-32], blob[-32:]
    enc_key, mac_key = _derive_keys(passphrase, salt)
    expect = hmac.new(mac_key, blob[:-32 - len(ct)] + ct,
                      hashlib.sha256).digest()
    if not hmac.compare_digest(tag, expect):
        raise ValueError("decryption failed: wrong key or tampered "
                         "artifact (integrity check)")
    xor = _keystream_xor_segmented if v2 else _keystream_xor
    return xor(enc_key, nonce, ct)
