"""Nested-structure helpers (flatten / pack / map) over dict/list/tuple trees.

Same role as the reference's ``pyzoo/zoo/util/nest.py`` (used by XShards and
every estimator to handle {'x': ..., 'y': ...} shard dicts); implemented on
plain Python so it works on numpy, pandas, and jax leaves alike.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Sequence


def _is_leaf(x: Any) -> bool:
    return not isinstance(x, (dict, list, tuple))


def flatten(structure: Any) -> List[Any]:
    """Depth-first leaf list; dicts iterate in sorted-key order."""
    if _is_leaf(structure):
        return [structure]
    out: List[Any] = []
    if isinstance(structure, dict):
        for k in sorted(structure):
            out.extend(flatten(structure[k]))
    else:
        for v in structure:
            out.extend(flatten(v))
    return out


def pack_sequence_as(structure: Any, flat: Sequence[Any]) -> Any:
    """Inverse of :func:`flatten` against the shape of ``structure``."""
    flat = list(flat)

    def _pack(s):
        if _is_leaf(s):
            return flat.pop(0)
        if isinstance(s, dict):
            return {k: _pack(s[k]) for k in sorted(s)}
        vals = [_pack(v) for v in s]
        return tuple(vals) if isinstance(s, tuple) else vals

    packed = _pack(structure)
    if flat:
        raise ValueError(f"{len(flat)} leaves left over after packing")
    return packed


def map_structure(fn: Callable, *structures: Any) -> Any:
    flats = [flatten(s) for s in structures]
    n = len(flats[0])
    if any(len(f) != n for f in flats):
        raise ValueError("structures do not have matching leaf counts")
    results = [fn(*leaves) for leaves in zip(*flats)]
    return pack_sequence_as(structures[0], results)


def ptensor_like(structure: Any) -> Any:
    return structure
