"""Minimal protobuf wire-format helpers shared by the TensorBoard event
writer (utils/tensorboard.py) and the ONNX loader (pipeline/api/onnx) — this
stack carries no protobuf/onnx runtime dependency."""

from __future__ import annotations

import struct
from typing import Iterator, Tuple, Union


def varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def pb_tag(field: int, wire: int) -> bytes:
    return varint((field << 3) | wire)


def pb_packed_floats(field: int, vals) -> bytes:
    """Length-delimited packed float32 list (FloatList.value and friends)."""
    body = struct.pack(f"<{len(vals)}f", *[float(v) for v in vals])
    return pb_tag(field, 2) + varint(len(body)) + body


def pb_packed_int64s(field: int, vals) -> bytes:
    """Length-delimited packed varint list (Int64List.value, BlobShape.dim)."""
    body = b"".join(varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in vals)
    return pb_tag(field, 2) + varint(len(body)) + body


def read_varint(data: bytes, i: int) -> Tuple[int, int]:
    v = 0
    shift = 0
    while True:
        b = data[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def decode_fields(data: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
    """Yield (field_number, wire_type, value). Length-delimited and fixed
    fields yield raw bytes; varints yield ints."""
    i = 0
    while i < len(data):
        key, i = read_varint(data, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = read_varint(data, i)
            yield field, wire, v
        elif wire == 1:
            yield field, wire, data[i:i + 8]
            i += 8
        elif wire == 2:
            ln, i = read_varint(data, i)
            yield field, wire, data[i:i + ln]
            i += ln
        elif wire == 5:
            yield field, wire, data[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def signed64(v: int) -> int:
    """Interpret a varint as two's-complement int64 (protobuf int64)."""
    return v - (1 << 64) if v >= (1 << 63) else v
