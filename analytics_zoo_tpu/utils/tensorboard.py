"""Self-contained TensorBoard event writer/reader (parity: the reference
ships its own TB implementation JVM-side — zoo/.../tensorboard/Summary.scala:182,
FileWriter.scala:89, EventWriter.scala:75, FileReader.scala:121 — backing
setTensorBoard/getTrainSummary).

No TF dependency: events files are hand-encoded protobuf records in the
TFRecord framing (length + masked crc32c). Scalars only — that is all the
reference's get_train_summary/get_validation_summary expose."""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

# --- crc32c (Castagnoli), table-driven --------------------------------------

_CRC_TABLE = []


def _build_table():
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_build_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


# --- minimal protobuf encoding (wire helpers shared with the ONNX loader
# and the TFRecord/Caffe codecs — one encoder set, utils/protostream.py) ---

from analytics_zoo_tpu.utils.protostream import decode_fields as \
    _decode_fields  # noqa: E402
from analytics_zoo_tpu.utils.protostream import pb_tag as _tag  # noqa: E402
from analytics_zoo_tpu.utils.protostream import varint as _varint  # noqa


def _pb_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _pb_int64(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _pb_string(field: int, v: str) -> bytes:
    return _pb_bytes(field, v.encode("utf-8"))


def encode_scalar_event(tag: str, value: float, step: int,
                        wall_time: Optional[float] = None) -> bytes:
    summary_value = _pb_string(1, tag) + _pb_float(2, float(value))
    summary = _pb_bytes(1, summary_value)
    event = (_pb_double(1, wall_time or time.time()) +
             _pb_int64(2, int(step)) + _pb_bytes(5, summary))
    return event


def encode_file_version() -> bytes:
    return (_pb_double(1, time.time()) +
            _pb_string(3, "brain.Event:2"))


def _frame(record: bytes) -> bytes:
    header = struct.pack("<Q", len(record))
    return (header + struct.pack("<I", _masked_crc(header)) + record +
            struct.pack("<I", _masked_crc(record)))


class FileWriter:
    """Append scalar events to an events file under log_dir (reference
    FileWriter.scala:89)."""

    def __init__(self, log_dir: str, flush_secs: float = 2.0):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._lock = threading.Lock()
        self._f.write(_frame(encode_file_version()))
        self._f.flush()

    def add_scalar(self, tag: str, value: float, step: int):
        with self._lock:
            self._f.write(_frame(encode_scalar_event(tag, value, step)))

    def flush(self):
        with self._lock:
            self._f.flush()

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


# --- reader -------------------------------------------------------------------

def read_scalars(log_dir_or_file: str) -> Dict[str, List[Tuple[int, float]]]:
    """Parse events files -> {tag: [(step, value), ...]} (reference
    FileReader.scala:121 readScalar)."""
    paths = []
    if os.path.isdir(log_dir_or_file):
        for name in sorted(os.listdir(log_dir_or_file)):
            if "tfevents" in name:
                paths.append(os.path.join(log_dir_or_file, name))
    else:
        paths = [log_dir_or_file]
    out: Dict[str, List[Tuple[int, float]]] = {}
    for path in paths:
        with open(path, "rb") as f:
            data = f.read()
        i = 0
        while i + 12 <= len(data):
            (length,) = struct.unpack("<Q", data[i:i + 8])
            record = data[i + 12:i + 12 + length]
            i += 12 + length + 4
            step = 0
            summary = None
            for field, wire, val in _decode_fields(record):
                if field == 2 and wire == 0:
                    step = val
                elif field == 5 and wire == 2:
                    summary = val
            if summary is None:
                continue
            for field, wire, val in _decode_fields(summary):
                if field == 1 and wire == 2:
                    tag, simple = None, None
                    for f2, w2, v2 in _decode_fields(val):
                        if f2 == 1 and w2 == 2:
                            tag = v2.decode("utf-8")
                        elif f2 == 2 and w2 == 5:
                            (simple,) = struct.unpack("<f", v2)
                    if tag is not None and simple is not None:
                        out.setdefault(tag, []).append((step, simple))
    return out
