from .model.forecast import (Forecaster, LSTMForecaster, MTNetForecaster,
                             Seq2SeqForecaster, TCNForecaster)

__all__ = ["Forecaster", "LSTMForecaster", "TCNForecaster",
           "Seq2SeqForecaster", "MTNetForecaster"]
