from .model.forecast import (Forecaster, LSTMForecaster, MTNetForecaster,
                             Seq2SeqForecaster, TCNForecaster)
from .model.tcmf import TCMF, TCMFForecaster

__all__ = ["Forecaster", "LSTMForecaster", "TCNForecaster",
           "Seq2SeqForecaster", "MTNetForecaster", "TCMF", "TCMFForecaster"]
