"""AutoTS — automated time-series pipeline (reference:
pyzoo/zoo/zouwu/autots/forecast.py:22 AutoTSTrainer.fit -> :94 TSPipeline;
search path SURVEY.md §3.6). Trials run on the chip-pinned TPUSearchEngine
instead of Ray Tune actors."""

from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

from ...automl import hp
from ...automl.search.search_engine import TPUSearchEngine
from ..config.recipe import (LSTMGridRandomRecipe, Recipe,
                             convert_bayes_config)
from ..feature.time_sequence import TimeSequenceFeatureTransformer
from ..model.forecast import LSTMForecaster, Seq2SeqForecaster, TCNForecaster


class AutoTSTrainer:
    """(reference: zouwu/autots/forecast.py:22-93)"""

    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 horizon: int = 1, extra_features_col: Optional[List] = None,
                 search_alg=None, search_alg_params=None, scheduler=None,
                 scheduler_params=None, name: str = "autots",
                 logs_dir: Optional[str] = None):
        self.dt_col = dt_col
        self.target_col = target_col
        self.horizon = horizon
        self.extra_features_col = extra_features_col
        self.name = name
        # scheduler="asha" routes trials through the fault-tolerant rung
        # scheduler (pause/resume at rung boundaries, retry-with-backoff,
        # SIGTERM study checkpointing when logs_dir is set); the reference
        # forwarded the same kwargs to Ray Tune's scheduler slot
        self.scheduler = scheduler
        self.scheduler_params = scheduler_params
        self.logs_dir = logs_dir

    def fit(self, train_df: pd.DataFrame,
            validation_df: Optional[pd.DataFrame] = None,
            metric: str = "mse", recipe: Optional[Recipe] = None,
            mc: bool = False, resources_per_trial=None,
            upload_dir=None) -> "TSPipeline":
        recipe = recipe or LSTMGridRandomRecipe(num_rand_samples=1)
        space = recipe.search_space([])
        model_type = recipe.model_type()
        trainer = self

        class _TSTrialModel:
            def __init__(self, config, mesh):
                self.config = dict(config)
                self.mesh = mesh

            def fit_eval(self, data, validation_data, epochs, metric,
                         state=None):
                """``epochs`` is a CUMULATIVE budget and ``state`` the dict
                from a previous call (scheduler pause/resume protocol): a
                resumed trial keeps training its existing forecaster instead
                of rebuilding — legacy callers (state=None) see one
                fit-from-scratch to the full budget, as before."""
                cfg = convert_bayes_config(self.config)
                past = int(cfg.get("past_seq_len", 50))
                if state is not None:
                    tsft = state["tsft"]
                    forecaster = state["forecaster"]
                    epochs_done = int(state.get("epochs_done", 0))
                    x, y = tsft.transform(data, is_train=True)
                else:
                    tsft = TimeSequenceFeatureTransformer(
                        horizon=trainer.horizon, dt_col=trainer.dt_col,
                        target_col=trainer.target_col,
                        extra_features_col=trainer.extra_features_col)
                    x, y = tsft.fit_transform(data, past_seq_len=past)
                    forecaster = trainer._build_forecaster(
                        model_type, cfg, tsft.feature_num)
                    epochs_done = 0
                if validation_data is not None:
                    vx, vy = tsft.transform(validation_data, is_train=True)
                else:
                    vx, vy = x, y
                if model_type == "LSTM" and trainer.horizon == 1:
                    target_y, vtarget = y[:, 0:1], vy[:, 0:1]
                elif model_type == "MTNet":
                    target_y, vtarget = y, vy          # (n, horizon)
                else:
                    target_y, vtarget = y[..., None], vy[..., None]
                if int(epochs) > epochs_done:
                    forecaster.fit(x, target_y,
                                   epochs=int(epochs) - epochs_done,
                                   batch_size=int(cfg.get("batch_size", 32)))
                pred = forecaster.predict(vx)
                score = float(np.mean(
                    (pred.reshape(vtarget.shape) - vtarget) ** 2))
                state = {"forecaster": forecaster, "tsft": tsft,
                         "epochs_done": int(epochs)}
                return score, {metric: score}, state

        engine = TPUSearchEngine(name=self.name, logs_dir=self.logs_dir,
                                 scheduler=self.scheduler,
                                 scheduler_params=self.scheduler_params)
        self.engine = engine
        # reference recipes' reward_metric is a tune reward (maximized
        # negative loss): reward_metric=-0.05 stops once mse <= 0.05
        reward = getattr(recipe, "reward_metric", None)
        # the per-trial epoch budget: recipes carry it as `epochs` (LSTM) or
        # `training_iteration` (the tune-style recipes); under
        # scheduler="asha" this is max_t, the top-rung budget
        max_t = int(getattr(recipe, "epochs", None)
                    or getattr(recipe, "training_iteration", 5) or 5)
        engine.compile(train_df, lambda cfg, mesh: _TSTrialModel(cfg, mesh),
                       space, n_sampling=recipe.num_samples,
                       epochs=max_t,
                       validation_data=validation_df, metric=metric,
                       metric_mode="min",
                       search_alg=getattr(recipe, "search_algorithm", None),
                       stop_score=None if reward is None else -reward)
        engine.run()
        best = engine.get_best_trial()
        # store the CONVERTED config: downstream consumers (incremental
        # TSPipeline.fit, save/load) read plain keys like batch_size
        return TSPipeline(best.model_state["forecaster"],
                          best.model_state["tsft"],
                          convert_bayes_config(best.config), self)

    def _build_forecaster(self, model_type: str, cfg: Dict, feature_num: int):
        if model_type == "TCN":
            return TCNForecaster(
                past_seq_len=int(cfg.get("past_seq_len", 50)),
                future_seq_len=self.horizon,
                input_feature_num=feature_num, output_feature_num=1,
                num_channels=cfg.get("num_channels", (16,) * 3),
                kernel_size=int(cfg.get("kernel_size", 3)),
                dropout=float(cfg.get("dropout", 0.2)),
                lr=float(cfg.get("lr", 1e-3)),
                loss=cfg.get("loss", "mse"))
        if model_type == "Seq2Seq":
            return Seq2SeqForecaster(
                past_seq_len=int(cfg.get("past_seq_len", 50)),
                future_seq_len=self.horizon,
                input_feature_num=feature_num, output_feature_num=1,
                lstm_hidden_dim=int(cfg.get("latent_dim", 64)),
                lr=float(cfg.get("lr", 1e-3)))
        if model_type == "MTNet":
            from ..model.forecast import MTNetForecaster
            return MTNetForecaster(
                target_dim=self.horizon, feature_dim=feature_num,
                ar_window_size=int(cfg.get("ar_size", 4)),
                cnn_height=int(cfg.get("cnn_height", 3)),
                cnn_hid_size=int(cfg.get("cnn_hid_size", 32)),
                lr=float(cfg.get("lr", 1e-3)),
                loss=cfg.get("loss", "mse"))
        if "lstm_1_units" in cfg:
            # BayesRecipe layout: per-layer units/dropout keys (the
            # reference's VanillaLSTM reads the same names)
            units = (int(cfg["lstm_1_units"]),
                     int(cfg.get("lstm_2_units", cfg["lstm_1_units"])))
            dropouts = (float(cfg.get("dropout_1", 0.2)),
                        float(cfg.get("dropout_2", 0.2)))
        else:
            units = cfg.get("lstm_units", (16, 8))
            dropouts = cfg.get("dropouts", 0.2)
        return LSTMForecaster(
            target_dim=self.horizon, feature_dim=feature_num,
            lstm_units=units, dropouts=dropouts,
            lr=float(cfg.get("lr", 1e-3)), loss=cfg.get("loss", "mse"))


class TSPipeline:
    """(reference: zouwu/autots/forecast.py:94-200: predict/evaluate/
    save/load + incremental fit)"""

    def __init__(self, forecaster, tsft: TimeSequenceFeatureTransformer,
                 config: Dict, trainer: AutoTSTrainer):
        self.forecaster = forecaster
        self.tsft = tsft
        self.config = config
        self.trainer = trainer

    def predict(self, input_df: pd.DataFrame) -> pd.DataFrame:
        x, _ = self.tsft.transform(input_df, is_train=False)
        pred = self.forecaster.predict(x)
        pred = self.tsft.inverse_transform_y(
            pred.reshape(pred.shape[0], -1))
        dt = pd.to_datetime(input_df[self.trainer.dt_col])
        freq = dt.diff().mode().iloc[0] if len(dt) > 1 else pd.Timedelta("1h")
        rows = []
        for i in range(pred.shape[0]):
            base = dt.iloc[min(self.tsft.past_seq_len - 1 + i, len(dt) - 1)]
            rows.append([base + freq] + list(pred[i]))
        cols = [self.trainer.dt_col] + [
            f"{self.trainer.target_col}_{j}" if pred.shape[1] > 1 else
            self.trainer.target_col for j in range(pred.shape[1])]
        return pd.DataFrame(rows, columns=cols)

    def evaluate(self, input_df: pd.DataFrame,
                 metrics: List[str] = ("mse",),
                 multioutput: str = "uniform_average") -> Dict[str, float]:
        from ..model.forecast import evaluate_metrics
        x, y = self.tsft.transform(input_df, is_train=True)
        pred = self.forecaster.predict(x)
        y2 = y if pred.ndim == 2 and pred.shape == y.shape else \
            y.reshape(pred.shape) if y.size == pred.size else y[:, :1]
        return evaluate_metrics(y2, pred.reshape(y2.shape), metrics)

    def fit(self, input_df, validation_df=None, mc=False, epochs: int = 1,
            **_):
        """Incremental fit on new data (reference: forecast.py:110)."""
        x, y = self.tsft.transform(input_df, is_train=True)
        target = y[:, 0:1] if getattr(self.forecaster.module, "target_dim",
                                      None) == 1 else y[..., None]
        if isinstance(self.forecaster, LSTMForecaster):
            target = y[:, :self.forecaster.module.target_dim]
        self.forecaster.fit(x, target, epochs=epochs,
                            batch_size=int(self.config.get("batch_size", 32)))
        return self

    def save(self, pipeline_file: str):
        import cloudpickle
        state = {"config": self.config,
                 "tsft": self.tsft,
                 "engine_state": self.forecaster.estimator.engine.get_state(),
                 "module": self.forecaster.module,
                 "trainer": {"dt_col": self.trainer.dt_col,
                             "target_col": self.trainer.target_col,
                             "horizon": self.trainer.horizon,
                             "extra": self.trainer.extra_features_col}}
        with open(pipeline_file, "wb") as f:
            cloudpickle.dump(state, f)
        return pipeline_file

    @staticmethod
    def load(pipeline_file: str) -> "TSPipeline":
        import cloudpickle
        from ..model.forecast import Forecaster
        with open(pipeline_file, "rb") as f:
            state = cloudpickle.load(f)
        t = state["trainer"]
        trainer = AutoTSTrainer(dt_col=t["dt_col"], target_col=t["target_col"],
                                horizon=t["horizon"],
                                extra_features_col=t["extra"])
        forecaster = Forecaster(state["module"])
        forecaster.estimator.engine.set_state(state["engine_state"])
        forecaster._fitted = True
        return TSPipeline(forecaster, state["tsft"], state["config"], trainer)
