from .recipe import (LSTMGridRandomRecipe, Recipe, SmokeRecipe,
                     TCNGridRandomRecipe)

__all__ = ["Recipe", "SmokeRecipe", "LSTMGridRandomRecipe",
           "TCNGridRandomRecipe"]
