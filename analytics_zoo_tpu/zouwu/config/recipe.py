"""AutoTS recipes — search-space presets (reference:
pyzoo/zoo/zouwu/config/recipe.py:714 LoC: SmokeRecipe, LSTMGridRandomRecipe,
Seq2SeqRandomRecipe, MTNetGridRandomRecipe, TCNGridRandomRecipe, ...)."""

from __future__ import annotations

from typing import Dict, List

from ...automl import hp


class Recipe:
    num_samples = 1
    training_iteration = 10

    def search_space(self, all_available_features: List[str]) -> Dict:
        raise NotImplementedError

    def model_type(self) -> str:
        return "LSTM"


class SmokeRecipe(Recipe):
    """(reference: recipe.py SmokeRecipe — one tiny config for CI)"""
    num_samples = 1
    training_iteration = 1

    def search_space(self, all_available_features):
        return {"lstm_units": [8], "dropouts": 0.1, "lr": 0.01,
                "batch_size": 32, "past_seq_len": 12, "loss": "mse"}


class LSTMGridRandomRecipe(Recipe):
    """(reference: recipe.py LSTMGridRandomRecipe)"""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 training_iteration: int = 10,
                 lstm_1_units=(16, 32), lstm_2_units=(8, 16),
                 batch_size=(32, 64), past_seq_len=(50,)):
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.epochs = epochs
        self.lstm_1_units = list(lstm_1_units)
        self.lstm_2_units = list(lstm_2_units)
        self.batch_size = list(batch_size)
        self.past_seq_len = list(past_seq_len)

    def search_space(self, all_available_features):
        return {
            "lstm_units": hp.sample_from(
                lambda rng: [int(rng.choice(self.lstm_1_units)),
                             int(rng.choice(self.lstm_2_units))]),
            "dropouts": hp.uniform(0.1, 0.3),
            "lr": hp.loguniform(1e-4, 1e-1),
            "batch_size": hp.grid_search(self.batch_size),
            "past_seq_len": hp.choice(self.past_seq_len),
            "loss": "mse",
        }

    def model_type(self):
        return "LSTM"


class TCNGridRandomRecipe(Recipe):
    """(reference: recipe.py TCNGridRandomRecipe)"""

    def __init__(self, num_rand_samples: int = 1, training_iteration: int = 10,
                 num_channels=((16,) * 3,), kernel_size=(3, 5),
                 batch_size=(32, 64), past_seq_len=(50,)):
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.num_channels = [tuple(c) for c in num_channels]
        self.kernel_size = list(kernel_size)
        self.batch_size = list(batch_size)
        self.past_seq_len = list(past_seq_len)

    def search_space(self, all_available_features):
        return {
            "num_channels": hp.choice(self.num_channels),
            "kernel_size": hp.choice(self.kernel_size),
            "dropout": hp.uniform(0.0, 0.3),
            "lr": hp.loguniform(1e-4, 1e-2),
            "batch_size": hp.grid_search(self.batch_size),
            "past_seq_len": hp.choice(self.past_seq_len),
            "loss": "mse",
        }

    def model_type(self):
        return "TCN"
