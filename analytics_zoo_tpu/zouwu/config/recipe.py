"""AutoTS recipes — search-space presets (reference:
pyzoo/zoo/zouwu/config/recipe.py:714 LoC: SmokeRecipe, LSTMGridRandomRecipe,
Seq2SeqRandomRecipe, MTNetGridRandomRecipe, TCNGridRandomRecipe, ...)."""

from __future__ import annotations

from typing import Dict, List

from ...automl import hp


class Recipe:
    num_samples = 1
    training_iteration = 10
    search_algorithm = None        # None (grid+random) | "bayes"

    def search_space(self, all_available_features: List[str]) -> Dict:
        raise NotImplementedError

    def model_type(self) -> str:
        return "LSTM"


def convert_bayes_config(config: Dict) -> Dict:
    """``*_float`` keys -> ints under the stripped name (the reference's
    bayes convention, automl/common/util.py:207: bayes searchers model a
    continuous space, so integer hyperparameters are searched as floats
    and rounded when the model consumes them)."""
    out = {}
    for k, v in config.items():
        if k.endswith("_float"):
            out[k[:-len("_float")]] = int(v)
        else:
            out[k] = v
    return out


class SmokeRecipe(Recipe):
    """(reference: recipe.py SmokeRecipe — one tiny config for CI)"""
    num_samples = 1
    training_iteration = 1

    def search_space(self, all_available_features):
        return {"lstm_units": [8], "dropouts": 0.1, "lr": 0.01,
                "batch_size": 32, "past_seq_len": 12, "loss": "mse"}


class LSTMGridRandomRecipe(Recipe):
    """(reference: recipe.py LSTMGridRandomRecipe)"""

    def __init__(self, num_rand_samples: int = 1, epochs: int = 5,
                 training_iteration: int = 10,
                 lstm_1_units=(16, 32), lstm_2_units=(8, 16),
                 batch_size=(32, 64), past_seq_len=(50,)):
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.epochs = epochs
        self.lstm_1_units = list(lstm_1_units)
        self.lstm_2_units = list(lstm_2_units)
        self.batch_size = list(batch_size)
        self.past_seq_len = list(past_seq_len)

    def search_space(self, all_available_features):
        return {
            "lstm_units": hp.sample_from(
                lambda rng: [int(rng.choice(self.lstm_1_units)),
                             int(rng.choice(self.lstm_2_units))]),
            "dropouts": hp.uniform(0.1, 0.3),
            "lr": hp.loguniform(1e-4, 1e-1),
            "batch_size": hp.grid_search(self.batch_size),
            "past_seq_len": hp.choice(self.past_seq_len),
            "loss": "mse",
        }

    def model_type(self):
        return "LSTM"


class TCNGridRandomRecipe(Recipe):
    """(reference: recipe.py TCNGridRandomRecipe)"""

    def __init__(self, num_rand_samples: int = 1, training_iteration: int = 10,
                 num_channels=((16,) * 3,), kernel_size=(3, 5),
                 batch_size=(32, 64), past_seq_len=(50,)):
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.num_channels = [tuple(c) for c in num_channels]
        self.kernel_size = list(kernel_size)
        self.batch_size = list(batch_size)
        self.past_seq_len = list(past_seq_len)

    def search_space(self, all_available_features):
        return {
            "num_channels": hp.choice(self.num_channels),
            "kernel_size": hp.choice(self.kernel_size),
            "dropout": hp.uniform(0.0, 0.3),
            "lr": hp.loguniform(1e-4, 1e-2),
            "batch_size": hp.grid_search(self.batch_size),
            "past_seq_len": hp.choice(self.past_seq_len),
            "loss": "mse",
        }

    def model_type(self):
        return "TCN"


class TCNSmokeRecipe(Recipe):
    """(reference: recipe.py TCNSmokeRecipe)"""
    num_samples = 1
    training_iteration = 1

    def search_space(self, all_available_features):
        return {"num_channels": (8, 8), "kernel_size": 3, "dropout": 0.1,
                "lr": 0.01, "batch_size": 32, "past_seq_len": 12,
                "loss": "mse"}

    def model_type(self):
        return "TCN"


class MTNetSmokeRecipe(Recipe):
    """(reference: recipe.py MTNetSmokeRecipe)"""
    num_samples = 1
    training_iteration = 1

    def search_space(self, all_available_features):
        return {"ar_size": 2, "cnn_height": 2, "cnn_hid_size": 16,
                "lr": 0.01, "batch_size": 32, "past_seq_len": 12,
                "loss": "mse"}

    def model_type(self):
        return "MTNet"


class MTNetGridRandomRecipe(Recipe):
    """(reference: recipe.py MTNetGridRandomRecipe — grid over cnn/ar
    geometry, random over lr/dropout)"""

    def __init__(self, num_rand_samples: int = 1, training_iteration: int = 10,
                 time_step=(12,), cnn_height=(2, 3), ar_size=(2, 4),
                 cnn_hid_size=(16, 32), batch_size=(32, 64)):
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.time_step = list(time_step)
        self.cnn_height = list(cnn_height)
        self.ar_size = list(ar_size)
        self.cnn_hid_size = list(cnn_hid_size)
        self.batch_size = list(batch_size)

    def search_space(self, all_available_features):
        return {
            "past_seq_len": hp.grid_search(self.time_step),
            "cnn_height": hp.choice(self.cnn_height),
            "ar_size": hp.choice(self.ar_size),
            "cnn_hid_size": hp.choice(self.cnn_hid_size),
            "batch_size": hp.grid_search(self.batch_size),
            "lr": hp.loguniform(1e-4, 1e-2),
            "loss": "mse",
        }

    def model_type(self):
        return "MTNet"


class Seq2SeqRandomRecipe(Recipe):
    """(reference: recipe.py Seq2SeqRandomRecipe)"""

    def __init__(self, num_rand_samples: int = 1, training_iteration: int = 10,
                 latent_dim=(32, 64, 128), batch_size=(32, 64),
                 past_seq_len=(50,)):
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.latent_dim = list(latent_dim)
        self.batch_size = list(batch_size)
        self.past_seq_len = list(past_seq_len)

    def search_space(self, all_available_features):
        return {
            "latent_dim": hp.choice(self.latent_dim),
            "batch_size": hp.grid_search(self.batch_size),
            "past_seq_len": hp.choice(self.past_seq_len),
            "lr": hp.loguniform(1e-4, 1e-2),
            "loss": "mse",
        }

    def model_type(self):
        return "Seq2Seq"


class GridRandomRecipe(LSTMGridRandomRecipe):
    """(reference: recipe.py GridRandomRecipe — the historical name for the
    LSTM grid+random preset; kept as an alias surface)"""


class RandomRecipe(Recipe):
    """(reference: recipe.py RandomRecipe — pure random sampling, no grid
    axes, so trial count == num_rand_samples)"""

    def __init__(self, num_rand_samples: int = 1, training_iteration: int = 10,
                 past_seq_len=(50,)):
        self.num_samples = num_rand_samples
        self.training_iteration = training_iteration
        self.past_seq_len = list(past_seq_len)

    def search_space(self, all_available_features):
        return {
            "lstm_units": hp.sample_from(
                lambda rng: [int(rng.choice([8, 16, 32])),
                             int(rng.choice([8, 16]))]),
            "dropouts": hp.uniform(0.1, 0.4),
            "batch_size": hp.choice([32, 64]),
            "past_seq_len": hp.choice(self.past_seq_len),
            "lr": hp.loguniform(1e-4, 1e-1),
            "loss": "mse",
        }

    def model_type(self):
        return "LSTM"


class BayesRecipe(Recipe):
    """Bayes-search LSTM recipe (reference: recipe.py:568 BayesRecipe over
    ray-tune's bayesopt searcher). Integer hyperparameters are expressed
    as ``*_float`` uniforms (bayes models a continuous space) and rounded
    via :func:`convert_bayes_config` when consumed; trials run through
    TPUSearchEngine's sequential GP-EI loop (automl/search/bayes.py)."""

    search_algorithm = "bayes"

    def __init__(self, num_samples: int = 1, look_back=2, epochs: int = 5,
                 reward_metric: float = -0.05, training_iteration: int = 5):
        self.num_samples = num_samples
        self.reward_metric = reward_metric
        self.training_iteration = training_iteration
        self.epochs = epochs
        if (isinstance(look_back, tuple) and len(look_back) == 2
                and all(isinstance(v, int) for v in look_back)):
            if look_back[1] < 2:
                raise ValueError("The max look back value should be at "
                                 "least 2")
            if look_back[0] > look_back[1]:
                raise ValueError(
                    f"look back range is inverted: {look_back} — expected "
                    "(min_len, max_len) with min_len <= max_len")
            self.bayes_past_seq_config = {
                "past_seq_len_float": hp.uniform(max(look_back[0], 2),
                                                 look_back[1])}
        elif isinstance(look_back, int):
            if look_back < 2:
                raise ValueError("look back value should not be smaller "
                                 f"than 2. Current value is {look_back}")
            self.bayes_past_seq_config = {"past_seq_len": look_back}
        else:
            raise ValueError(
                f"look back is {look_back}. look_back should be either a "
                "tuple of 2 ints (min_len, max_len) or a single int")

    def search_space(self, all_available_features=None):
        space = {
            "model": "LSTM",
            "lstm_1_units_float": hp.uniform(8, 128),
            "dropout_1": hp.uniform(0.2, 0.5),
            "lstm_2_units_float": hp.uniform(8, 128),
            "dropout_2": hp.uniform(0.2, 0.5),
            "lr": hp.uniform(0.001, 0.1),
            "batch_size_float": hp.uniform(32, 128),
            "loss": "mse",
        }
        space.update(self.bayes_past_seq_config)
        return space

    def model_type(self):
        return "LSTM"


class XgbRegressorGridRandomRecipe(Recipe):
    """(reference: recipe.py XgbRegressorGridRandomRecipe — pairs with
    AutoXGBRegressor.fit(search_space=recipe.search_space([])))"""

    def __init__(self, num_rand_samples: int = 1,
                 n_estimators=(50, 100), max_depth=(3, 6),
                 lr_range=(1e-2, 3e-1)):
        self.num_samples = num_rand_samples
        self.n_estimators = list(n_estimators)
        self.max_depth = list(max_depth)
        self.lr_range = tuple(lr_range)

    def search_space(self, all_available_features):
        return {
            "n_estimators": hp.grid_search(self.n_estimators),
            "max_depth": hp.grid_search(self.max_depth),
            "learning_rate": hp.loguniform(*self.lr_range),
        }

    def model_type(self):
        return "XGBoost"
