from .time_sequence import TimeSequenceFeatureTransformer, roll_windows

__all__ = ["TimeSequenceFeatureTransformer", "roll_windows"]
