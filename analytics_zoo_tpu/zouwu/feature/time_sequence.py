"""Time-sequence feature engineering — rolling windows + datetime features.

Mirrors the reference's TimeSequenceFeatureTransformer
(pyzoo/zoo/zouwu/feature/time_sequence.py:582 LoC: fit_transform builds
datetime features, scales, and rolls (past_seq_len, horizon) windows;
transform/inverse for inference) on pandas/numpy, producing the (x, y) arrays
the forecasters consume."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

_DT_FEATURES = ("HOUR", "DAY", "WEEKDAY", "MONTH", "IS_WEEKEND")


def gen_dt_features(dt: pd.Series, features: Sequence[str] = _DT_FEATURES
                    ) -> pd.DataFrame:
    dt = pd.to_datetime(dt)
    out = {}
    if "HOUR" in features:
        out["HOUR"] = dt.dt.hour
    if "DAY" in features:
        out["DAY"] = dt.dt.day
    if "WEEKDAY" in features:
        out["WEEKDAY"] = dt.dt.weekday
    if "MONTH" in features:
        out["MONTH"] = dt.dt.month
    if "IS_WEEKEND" in features:
        out["IS_WEEKEND"] = (dt.dt.weekday >= 5).astype(int)
    return pd.DataFrame(out, index=dt.index)


def roll_windows(arr: np.ndarray, past: int, horizon: int,
                 target_idx: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """arr (T, F) -> x (n, past, F), y (n, horizon) of column target_idx."""
    T = len(arr)
    n = T - past - horizon + 1
    if n <= 0:
        raise ValueError(
            f"series length {T} too short for past {past} + horizon {horizon}")
    idx = np.arange(past)[None, :] + np.arange(n)[:, None]
    x = arr[idx]
    yidx = np.arange(horizon)[None, :] + np.arange(n)[:, None] + past
    y = arr[yidx, target_idx]
    return x.astype(np.float32), y.astype(np.float32)


class TimeSequenceFeatureTransformer:
    def __init__(self, horizon: int = 1, dt_col: str = "datetime",
                 target_col: str = "value",
                 extra_features_col: Optional[List[str]] = None,
                 drop_missing: bool = True):
        self.horizon = horizon
        self.dt_col = dt_col
        self.target_col = target_col
        self.extra_features_col = list(extra_features_col or [])
        self.drop_missing = drop_missing
        self.past_seq_len: Optional[int] = None
        self._mean = None
        self._std = None

    # --- internals ----------------------------------------------------------
    def _feature_frame(self, df: pd.DataFrame) -> pd.DataFrame:
        df = df.sort_values(self.dt_col).reset_index(drop=True)
        if self.drop_missing:
            df = df.dropna(subset=[self.target_col])
        feats = [df[[self.target_col]]]
        if self.extra_features_col:
            feats.append(df[self.extra_features_col])
        feats.append(gen_dt_features(df[self.dt_col]))
        return pd.concat(feats, axis=1)

    # --- public -------------------------------------------------------------
    def fit_transform(self, df: pd.DataFrame, past_seq_len: int = 50
                      ) -> Tuple[np.ndarray, np.ndarray]:
        self.past_seq_len = past_seq_len
        ff = self._feature_frame(df)
        arr = ff.to_numpy(np.float32)
        self._mean = arr.mean(axis=0)
        self._std = arr.std(axis=0) + 1e-8
        arr = (arr - self._mean) / self._std
        return roll_windows(arr, past_seq_len, self.horizon)

    def transform(self, df: pd.DataFrame, is_train: bool = False
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        assert self.past_seq_len is not None, "call fit_transform first"
        ff = self._feature_frame(df)
        arr = (ff.to_numpy(np.float32) - self._mean) / self._std
        if is_train or len(arr) >= self.past_seq_len + self.horizon:
            x, y = roll_windows(arr, self.past_seq_len, self.horizon)
            return x, y
        # inference tail: single window from the last past_seq_len rows
        x = arr[-self.past_seq_len:][None, ...]
        return x.astype(np.float32), None

    def inverse_transform_y(self, y: np.ndarray) -> np.ndarray:
        return y * self._std[0] + self._mean[0]

    def scale_y(self, y: np.ndarray) -> np.ndarray:
        return (y - self._mean[0]) / self._std[0]

    @property
    def feature_num(self) -> int:
        return 1 + len(self.extra_features_col) + len(_DT_FEATURES)
