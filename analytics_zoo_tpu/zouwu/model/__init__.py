from .forecast import (Forecaster, LSTMForecaster, MTNetForecaster,
                       Seq2SeqForecaster, TCNForecaster)
from .tcmf import TCMF, TCMFForecaster
from .anomaly import AEDetector, DBScanDetector, ThresholdDetector

__all__ = ["TCMF", "TCMFForecaster", "Forecaster", "LSTMForecaster", "TCNForecaster",
           "Seq2SeqForecaster", "MTNetForecaster", "ThresholdDetector",
           "AEDetector", "DBScanDetector"]
