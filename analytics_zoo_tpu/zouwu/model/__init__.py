from .forecast import (Forecaster, LSTMForecaster, MTNetForecaster,
                       Seq2SeqForecaster, TCNForecaster)
from .anomaly import AEDetector, DBScanDetector, ThresholdDetector

__all__ = ["Forecaster", "LSTMForecaster", "TCNForecaster",
           "Seq2SeqForecaster", "MTNetForecaster", "ThresholdDetector",
           "AEDetector", "DBScanDetector"]
