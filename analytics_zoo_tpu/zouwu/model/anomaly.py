"""Anomaly detectors — reference: pyzoo/zoo/zouwu/model/anomaly/anomaly.py:171
(ThresholdDetector with absolute bounds or (y, yhat) distance + ratio-derived
threshold; AEDetector autoencoder reconstruction error; DBScanDetector)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class DetectorBase:
    def detect(self, y, **kwargs):
        raise NotImplementedError


def anomaly_indexes(anomaly_scores: np.ndarray, threshold: float) -> np.ndarray:
    return np.nonzero(anomaly_scores > threshold)[0]


class ThresholdDetector(DetectorBase):
    """(reference: anomaly.py ThresholdDetector/ThresholdEstimator)"""

    def __init__(self):
        self.th = None
        self.ratio = 0.01
        self.absolute_bounds: Optional[Tuple[float, float]] = None

    def set_params(self, mode: str = "default", ratio: float = 0.01,
                   threshold=None, **_):
        self.ratio = ratio
        if threshold is not None and isinstance(threshold, tuple):
            self.absolute_bounds = threshold
        elif threshold is not None:
            self.th = float(threshold)
        return self

    def fit(self, y: np.ndarray, y_pred: Optional[np.ndarray] = None):
        """Derive the distance threshold from the ratio of highest-error
        points (reference ThresholdEstimator.fit)."""
        if y_pred is not None:
            dist = np.abs(np.asarray(y) - np.asarray(y_pred)).reshape(len(y), -1).mean(-1)
            self.th = float(np.quantile(dist, 1 - self.ratio))
        else:
            self.absolute_bounds = (float(np.quantile(y, self.ratio / 2)),
                                    float(np.quantile(y, 1 - self.ratio / 2)))
        return self

    def detect(self, y: np.ndarray, y_pred: Optional[np.ndarray] = None
               ) -> np.ndarray:
        y = np.asarray(y)
        if y_pred is not None:
            if self.th is None:
                self.fit(y, y_pred)
            dist = np.abs(y - np.asarray(y_pred)).reshape(len(y), -1).mean(-1)
            return anomaly_indexes(dist, self.th)
        if self.absolute_bounds is None:
            self.fit(y)
        lo, hi = self.absolute_bounds
        flat = y.reshape(len(y), -1).mean(-1)
        return np.nonzero((flat < lo) | (flat > hi))[0]


class AEDetector(DetectorBase):
    """Autoencoder reconstruction-error detector (reference: anomaly.py
    AEDetector — keras dense AE; here a flax dense AE on the TPU engine)."""

    def __init__(self, roll_len: int = 24, ratio: float = 0.1,
                 compress_rate: float = 0.8, batch_size: int = 100,
                 epochs: int = 20, verbose: int = 0, sub_scalef: float = 1,
                 lr: float = 1e-3):
        self.roll_len = roll_len
        self.ratio = ratio
        self.compress_rate = compress_rate
        self.batch_size = batch_size
        self.epochs = epochs
        self.lr = lr

    def _roll(self, y: np.ndarray) -> np.ndarray:
        y = np.asarray(y, np.float32).reshape(-1)
        if self.roll_len <= 1 or len(y) < self.roll_len:
            return y[:, None]
        n = len(y) - self.roll_len + 1
        idx = np.arange(self.roll_len)[None, :] + np.arange(n)[:, None]
        return y[idx]

    def detect(self, y: np.ndarray, **_) -> np.ndarray:
        import flax.linen as nn
        from ...orca.learn.estimator import TPUEstimator
        from ...orca.learn.optimizers import Adam

        windows = self._roll(y)
        dim = windows.shape[1]
        hidden = max(int(dim * (1 - self.compress_rate)), 1)

        class AE(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = nn.tanh(nn.Dense(hidden)(x))
                return nn.Dense(dim)(h)

        mean, std = windows.mean(), windows.std() + 1e-8
        norm = (windows - mean) / std
        est = TPUEstimator(AE(), loss="mse", optimizer=Adam(lr=self.lr))
        est.fit({"x": norm, "y": norm}, epochs=self.epochs,
                batch_size=min(self.batch_size, len(norm)), verbose=False)
        recon = est.predict({"x": norm}, batch_size=1024)
        err = np.mean((recon - norm) ** 2, axis=-1)
        th = np.quantile(err, 1 - self.ratio)
        window_idx = anomaly_indexes(err, th)
        # map window index -> center point index in original series
        return np.unique(np.clip(window_idx + self.roll_len // 2, 0,
                                 len(np.asarray(y).reshape(-1)) - 1))


class DBScanDetector(DetectorBase):
    """(reference: anomaly.py DBScanDetector — sklearn DBSCAN labels -1)"""

    def __init__(self, eps: float = 0.5, min_samples: int = 5, **kwargs):
        self.eps, self.min_samples, self.kwargs = eps, min_samples, kwargs

    def detect(self, y: np.ndarray, **_) -> np.ndarray:
        from sklearn.cluster import DBSCAN
        arr = np.asarray(y, np.float32).reshape(len(y), -1)
        labels = DBSCAN(eps=self.eps, min_samples=self.min_samples,
                        **self.kwargs).fit_predict(arr)
        return np.nonzero(labels == -1)[0]
