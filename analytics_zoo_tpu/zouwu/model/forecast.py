"""Zouwu forecasters — the reference's forecaster family
(pyzoo/zoo/zouwu/model/forecast/: abstract.py Forecaster, lstm_forecaster.py:21,
tcn_forecaster.py:21, seq2seq_forecaster.py, mtnet_forecaster.py) with the same
constructor/fit/predict/evaluate/save/restore surface, running on the TPU
engine instead of tfpark-Keras/torch."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ...orca.learn.estimator import TPUEstimator
from ...orca.learn.optimizers import Adam, RMSprop, SGD
from .nets import LSTMNet, MTNetLite, Seq2SeqNet, TCNNet


def _make_optimizer(name: str, lr: float):
    table = {"adam": Adam, "sgd": SGD, "rmsprop": RMSprop}
    return table.get(str(name).lower(), Adam)(lr=lr) if not callable(name) \
        else name


_METRIC_FNS = {
    "mse": lambda y, p: float(np.mean((p - y) ** 2)),
    "mean_squared_error": lambda y, p: float(np.mean((p - y) ** 2)),
    "rmse": lambda y, p: float(np.sqrt(np.mean((p - y) ** 2))),
    "mae": lambda y, p: float(np.mean(np.abs(p - y))),
    "mean_absolute_error": lambda y, p: float(np.mean(np.abs(p - y))),
    "mape": lambda y, p: float(np.mean(np.abs((p - y) /
                                              np.clip(np.abs(y), 1e-8, None)))
                               * 100),
    "smape": lambda y, p: float(np.mean(2 * np.abs(p - y) /
                                        np.clip(np.abs(y) + np.abs(p), 1e-8,
                                                None)) * 100),
    "r2": lambda y, p: float(1 - np.sum((p - y) ** 2) /
                             max(np.sum((y - y.mean()) ** 2), 1e-12)),
}


def evaluate_metrics(y, pred, metrics: Sequence[str]):
    y = np.asarray(y)
    pred = np.asarray(pred).reshape(y.shape)
    return {m: _METRIC_FNS[m.lower()](y, pred) for m in metrics}


class Forecaster:
    """(reference abstract: zouwu/model/forecast/abstract.py)"""

    def __init__(self, module, loss="mse", optimizer="Adam", lr: float = 1e-3):
        self.module = module
        self.estimator = TPUEstimator(module, loss=loss,
                                      optimizer=_make_optimizer(optimizer, lr))
        self._fitted = False

    def fit(self, x, y=None, validation_data=None, epochs: int = 1,
            metric: str = "mse", batch_size: int = 32, **kwargs):
        """x: (n, past_seq_len, feature_dim); y: (n, ...) target windows
        (reference: tcn_forecaster.py:70)."""
        if y is None and isinstance(x, tuple):
            x, y = x
        data = {"x": np.asarray(x, np.float32),
                "y": np.asarray(y, np.float32)}
        if validation_data is not None:
            validation_data = {"x": np.asarray(validation_data[0], np.float32),
                               "y": np.asarray(validation_data[1], np.float32)}
        stats = self.estimator.fit(data, epochs=epochs, batch_size=batch_size,
                                   validation_data=validation_data,
                                   verbose=False, **kwargs)
        self._fitted = True
        return stats

    def predict(self, x, batch_size: int = 1024) -> np.ndarray:
        if not self._fitted:
            raise RuntimeError("forecaster needs to be fitted before predict")
        return np.asarray(self.estimator.predict(
            {"x": np.asarray(x, np.float32)}, batch_size=batch_size))

    def evaluate(self, x, y, metrics: Sequence[str] = ("mse",),
                 multioutput: str = "uniform_average"):
        pred = self.predict(x)
        y = np.asarray(y, np.float32)
        if multioutput == "raw_values" and y.ndim >= 2:
            return {m: np.stack([
                _METRIC_FNS[m.lower()](y[..., i],
                                       pred.reshape(y.shape)[..., i])
                for i in range(y.shape[-1])]) for m in metrics}
        return evaluate_metrics(y, pred, metrics)

    def save(self, checkpoint_file: str):
        self.estimator.save(checkpoint_file)

    def restore(self, checkpoint_file: str):
        # need built engine before load; callers restore after a fit() or we
        # lazily build on first predict via stored state
        self.estimator.load(checkpoint_file)
        self._fitted = True


class LSTMForecaster(Forecaster):
    """(reference: lstm_forecaster.py:21-69)"""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 lstm_units: Tuple[int, ...] = (16, 8), dropouts=0.2,
                 metric: str = "mean_squared_error", lr: float = 0.001,
                 loss: str = "mse", optimizer: str = "Adam", **_):
        if isinstance(dropouts, (int, float)):
            dropouts = tuple([float(dropouts)] * len(tuple(lstm_units)))
        module = LSTMNet(target_dim=target_dim,
                         lstm_units=tuple(int(u) for u in lstm_units),
                         dropouts=tuple(dropouts))
        self.feature_dim = feature_dim
        super().__init__(module, loss=loss, optimizer=optimizer, lr=lr)


class TCNForecaster(Forecaster):
    """(reference: tcn_forecaster.py:21-69)"""

    def __init__(self, past_seq_len: int, future_seq_len: int,
                 input_feature_num: int, output_feature_num: int,
                 num_channels: Sequence[int] = (30,) * 8, kernel_size: int = 7,
                 dropout: float = 0.2, optimizer: str = "Adam",
                 loss: str = "mse", lr: float = 0.001, **_):
        module = TCNNet(past_seq_len=past_seq_len,
                        future_seq_len=future_seq_len,
                        output_feature_num=output_feature_num,
                        num_channels=tuple(int(c) for c in num_channels),
                        kernel_size=kernel_size, dropout=dropout)
        self.data_config = {
            "past_seq_len": past_seq_len, "future_seq_len": future_seq_len,
            "input_feature_num": input_feature_num,
            "output_feature_num": output_feature_num}
        super().__init__(module, loss=loss, optimizer=optimizer, lr=lr)

    def fit(self, x, y=None, validation_data=None, epochs=1, metric="mse",
            batch_size=32, **kwargs):
        if y is not None:
            self._check_data(np.asarray(x), np.asarray(y))
        return super().fit(x, y, validation_data, epochs, metric, batch_size,
                           **kwargs)

    def _check_data(self, x, y):
        """(reference: tcn_forecaster.py:93-110)"""
        c = self.data_config
        assert x.ndim == 3 and y.ndim == 3, \
            "x and y must be 3-dim (n, seq_len, feature_num)"
        assert x.shape[1] == c["past_seq_len"], \
            f"x seq_len {x.shape[1]} != past_seq_len {c['past_seq_len']}"
        assert x.shape[2] == c["input_feature_num"], \
            f"x feature_num {x.shape[2]} != {c['input_feature_num']}"
        assert y.shape[1] == c["future_seq_len"], \
            f"y seq_len {y.shape[1]} != future_seq_len {c['future_seq_len']}"
        assert y.shape[2] == c["output_feature_num"], \
            f"y feature_num {y.shape[2]} != {c['output_feature_num']}"


class Seq2SeqForecaster(Forecaster):
    """(reference: seq2seq_forecaster.py)"""

    def __init__(self, past_seq_len: int, future_seq_len: int,
                 input_feature_num: int, output_feature_num: int,
                 lstm_hidden_dim: int = 128, dropout: float = 0.2,
                 optimizer: str = "Adam", loss: str = "mse",
                 lr: float = 0.001, **_):
        module = Seq2SeqNet(future_seq_len=future_seq_len,
                            output_feature_num=output_feature_num,
                            latent_dim=lstm_hidden_dim, dropout=dropout)
        super().__init__(module, loss=loss, optimizer=optimizer, lr=lr)


class MTNetForecaster(Forecaster):
    """(reference: mtnet_forecaster.py — wraps MTNet keras; here the lite
    cnn+attention+AR variant in nets.MTNetLite)"""

    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 long_series_num: int = 1, series_length: int = 1,
                 ar_window_size: int = 1, cnn_height: int = 1,
                 cnn_hid_size: int = 32, lr: float = 0.001,
                 loss: str = "mae", metric: str = "mean_absolute_error", **_):
        module = MTNetLite(target_dim=target_dim,
                           ar_window=max(ar_window_size, 1),
                           cnn_kernel=max(cnn_height, 1),
                           cnn_channels=cnn_hid_size)
        super().__init__(module, loss=loss, optimizer="Adam", lr=lr)
