"""Time-series network modules (flax) backing the Zouwu forecasters.

Reference models: LSTM keras graph (pyzoo/zoo/zouwu/model/forecast/
lstm_forecaster.py:70 + zoo/automl VanillaLSTM), TCN torch impl
(zouwu/model/tcn.py, dilated causal residual blocks), Seq2Seq keras
(zouwu/model/Seq2Seq.py). TPU notes: recurrence uses flax's scan-based
nn.RNN with OptimizedLSTMCell (lax.scan — no Python loops under jit);
TCN is causal-padded Conv1D stacks, which XLA fuses well and is usually
the faster pick on TPU.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class LSTMNet(nn.Module):
    """Stacked LSTM -> Dense(target_dim). Input (B, T, F) -> (B, target_dim)."""
    target_dim: int = 1
    lstm_units: Tuple[int, ...] = (16, 8)
    dropouts: Tuple[float, ...] = (0.2, 0.2)

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, units in enumerate(self.lstm_units):
            rnn = nn.RNN(nn.OptimizedLSTMCell(units), name=f"lstm_{i}")
            x = rnn(x)
            rate = self.dropouts[min(i, len(self.dropouts) - 1)]
            if rate:
                x = nn.Dropout(rate, deterministic=not train)(x)
        x = x[:, -1]  # last timestep
        return nn.Dense(self.target_dim, name="head")(x)


class CausalConv1D(nn.Module):
    channels: int
    kernel_size: int
    dilation: int = 1

    @nn.compact
    def __call__(self, x):
        pad = (self.kernel_size - 1) * self.dilation
        x = jnp.pad(x, ((0, 0), (pad, 0), (0, 0)))
        return nn.Conv(self.channels, (self.kernel_size,),
                       kernel_dilation=(self.dilation,), padding="VALID")(x)


class TCNBlock(nn.Module):
    channels: int
    kernel_size: int
    dilation: int
    dropout: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = CausalConv1D(self.channels, self.kernel_size, self.dilation)(x)
        y = nn.relu(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        y = CausalConv1D(self.channels, self.kernel_size, self.dilation)(y)
        y = nn.relu(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        if x.shape[-1] != self.channels:
            x = nn.Dense(self.channels, name="downsample")(x)
        return nn.relu(x + y)


class TCNNet(nn.Module):
    """Dilated causal TCN encoder -> linear head mapping the last
    receptive-field step to (future_seq_len, output_dim).
    Input (B, past, F) -> (B, future, output_dim)."""
    past_seq_len: int
    future_seq_len: int
    output_feature_num: int = 1
    num_channels: Tuple[int, ...] = (30,) * 8
    kernel_size: int = 7
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i, ch in enumerate(self.num_channels):
            x = TCNBlock(ch, self.kernel_size, 2 ** i, self.dropout,
                         name=f"block_{i}")(x, train=train)
        last = x[:, -1]
        out = nn.Dense(self.future_seq_len * self.output_feature_num,
                       name="head")(last)
        return out.reshape(out.shape[0], self.future_seq_len,
                           self.output_feature_num)


class Seq2SeqNet(nn.Module):
    """LSTM encoder-decoder (reference zouwu/model/Seq2Seq.py): encoder folds
    the past; decoder unrolls future_seq_len steps feeding back its output."""
    future_seq_len: int
    output_feature_num: int = 1
    latent_dim: int = 128
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        B = x.shape[0]
        enc_cell = nn.OptimizedLSTMCell(self.latent_dim, name="encoder")
        carry, _ = nn.RNN(enc_cell, return_carry=True,
                          name="encoder_scan")(x)
        dec_cell = nn.OptimizedLSTMCell(self.latent_dim, name="decoder")
        head = nn.Dense(self.output_feature_num, name="head")
        y = jnp.zeros((B, self.output_feature_num), x.dtype)
        # static unroll: future_seq_len is a small compile-time constant, and
        # repeated calls to the same submodules share parameters
        ys = []
        for _ in range(self.future_seq_len):
            carry, h = dec_cell(carry, y)
            y = head(h)
            ys.append(y)
        return jnp.stack(ys, axis=1)


class MTNetLite(nn.Module):
    """Compact MTNet-style forecaster (reference MTNetForecaster wraps the
    MTNet keras model, zouwu/model/MTNet_keras.py): CNN feature extraction over
    long/short windows + attention + autoregressive linear path. This lite
    variant keeps the cnn+ar decomposition (the load-bearing parts) in a
    jit-friendly form."""
    target_dim: int = 1
    ar_window: int = 4
    cnn_kernel: int = 3
    cnn_channels: int = 32
    dropout: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: (B, T, F)
        y = CausalConv1D(self.cnn_channels, self.cnn_kernel)(x)
        y = nn.relu(y)
        y = nn.Dropout(self.dropout, deterministic=not train)(y)
        att = nn.softmax(nn.Dense(1, name="attn")(y), axis=1)  # (B,T,1)
        ctx = jnp.sum(att * y, axis=1)  # (B,C)
        nonlinear = nn.Dense(self.target_dim, name="head")(ctx)
        # autoregressive linear component over the last ar_window steps
        ar_in = x[:, -self.ar_window:, :].reshape(x.shape[0], -1)
        linear = nn.Dense(self.target_dim, name="ar")(ar_in)
        return nonlinear + linear
